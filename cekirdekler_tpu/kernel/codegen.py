"""Kernel codegen: lower parsed kernel ASTs to vectorized JAX functions.

Strategy (the TPU-first answer to the reference's per-work-item OpenCL
execution model, SURVEY.md §7): instead of launching one scalar program per
work item, we *vectorize over work items* — a launch chunk of ``B``
consecutive work items becomes one array program where every scalar local
variable is a ``(B,)`` vector and ``get_global_id(0)`` is
``offset + iota(B)``.  This maps the kernel straight onto the TPU VPU/MXU
and lets XLA fuse the whole body.

Key mechanisms:

- **Affine index tracking** — every integer value carries an optional
  ``(stride, offset)`` annotation meaning ``value == stride*gid + offset``.
  Loads/stores with stride-1 indices lower to
  ``lax.dynamic_slice`` / ``lax.dynamic_update_slice`` (contiguous DMA-
  friendly vector ops); anything else falls back to gather/scatter.
- **Masked control flow** — ``if``/``else`` run both branches under
  disjoint masks (stores become masked read-modify-writes, locals merge via
  ``where``); an early ``return`` folds into a cumulative return-mask.
  This is the standard SIMT→SIMD predication transform.
- **Vectorized loops** — ``for``/``while`` lower to ``lax.while_loop`` with
  a per-item active mask (loops run until *all* items are done — exactly the
  mandelbrot iteration pattern); locals keep their declared C dtype so loop
  carries are shape/dtype-stable and nothing recompiles when trip counts
  change at runtime.

The launch boundary: ``build_kernel_fn`` returns ``fn(offset, *buffers,
value_args) -> updated buffers``, where ``offset`` is a *runtime* scalar —
the load balancer can re-partition the global range every call without
triggering recompilation (the reference's NDRange-offset semantics,
Cores.cs:607-613, preserved under jit).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..errors import KernelCompileError, KernelLanguageError
from . import lang
from .lang import (
    Assign,
    BinOp,
    Break,
    Call,
    Cast,
    Continue,
    CrementStmt,
    Decl,
    For,
    DoWhile,
    If,
    Index,
    KernelDef,
    Num,
    Return,
    Ternary,
    UnOp,
    Var,
    While,
)

__all__ = ["build_kernel_fn", "KernelBuildInfo", "ctype_to_dtype"]


# ---------------------------------------------------------------------------
# C type lattice
# ---------------------------------------------------------------------------

_INT_TYPES = {"char", "uchar", "short", "ushort", "int", "uint", "long", "ulong", "bool"}
_FLOAT_TYPES = {"float", "double", "half"}
_RANK = {
    "bool": 0, "char": 1, "uchar": 1, "short": 2, "ushort": 2,
    "int": 3, "uint": 4, "long": 5, "ulong": 6,
    "half": 7, "float": 8, "double": 9,
}


def _x64_enabled() -> bool:
    return bool(jax.config.read("jax_enable_x64"))


def ctype_to_dtype(ctype: str):
    """Map a C type to the jnp dtype actually used on this backend.  long and
    double degrade to 32-bit when x64 is disabled (standard JAX behavior on
    TPU; the CPU test rig enables x64 for full-width parity)."""
    table = {
        "bool": jnp.bool_,
        "char": jnp.int8,
        "uchar": jnp.uint8,
        "short": jnp.int16,
        "ushort": jnp.uint16,
        "int": jnp.int32,
        "uint": jnp.uint32,
        "long": jnp.int64 if _x64_enabled() else jnp.int32,
        "ulong": jnp.uint64 if _x64_enabled() else jnp.uint32,
        "half": jnp.float16,
        "float": jnp.float32,
        "double": jnp.float64 if _x64_enabled() else jnp.float32,
    }
    if ctype not in table:
        raise KernelLanguageError(f"unsupported type {ctype!r}")
    return jnp.dtype(table[ctype])


def _dtype_to_ctype(dtype) -> str:
    name = jnp.dtype(dtype).name
    table = {
        "bool": "bool", "int8": "char", "uint8": "uchar", "int16": "short",
        "uint16": "ushort", "int32": "int", "uint32": "uint", "int64": "long",
        "uint64": "ulong", "float16": "half", "float32": "float",
        "float64": "double", "bfloat16": "half",
    }
    return table.get(name, "float")


def _promote(t1: str, t2: str) -> str:
    """C usual arithmetic conversions (simplified to the rank lattice)."""
    a, b = (t1, t2) if _RANK[t1] >= _RANK[t2] else (t2, t1)
    if a in _FLOAT_TYPES:
        return a
    # integer promotion: everything below int promotes to int
    if _RANK[a] < _RANK["int"]:
        return "int"
    return a


@dataclass
class KVal:
    """A value in the vectorized program.

    ``affine`` — when not None, ``(stride, const)`` with a *Python-int*
    stride such that ``value == stride * gid + const`` elementwise (``gid``
    being the global work-item id vector); ``const`` is a Python int or a
    traced scalar.  Drives the contiguous slice fast path: stride-1 indices
    with an int ``const`` lower to dynamic_slice/dynamic_update_slice over a
    ``const``-padded buffer (padding makes tail chunks exact — a clamped
    slice would silently shift the window).
    """

    value: Any
    ctype: str
    affine: Optional[tuple[int, Any]] = None

    @property
    def is_vector(self) -> bool:
        return hasattr(self.value, "ndim") and self.value.ndim > 0


class _Ctx:
    """Interpretation context for one kernel launch chunk.

    ``shape`` is the vector shape every work-item-parallel value carries:
    ``(B,)`` for the XLA lowering, ``(rows, 128)`` for the Pallas tile
    lowering (pallas_backend.py) — the interpreter itself is shape-agnostic.
    """

    pallas = False  # the Pallas tile subclass flips this

    def __init__(self, B: int, offset, global_size, local_size: int, ctx_info: dict):
        self.B = B
        self.shape: tuple[int, ...] = (B,)
        self.offset = offset  # scalar int32 (traced)
        self.env: dict[str, KVal] = {}
        self.bufs: dict[str, Any] = {}
        self.buf_ctypes: dict[str, str] = {}
        self.stored: set[str] = set()
        self.mask: Any = None  # None == all-active; else bool of self.shape
        self.return_mask: Any = None  # items that already returned
        self.global_size = global_size
        self.local_size = local_size
        self.info = ctx_info
        idx = jnp.arange(B, dtype=jnp.int32)
        self.gid = KVal(offset + idx, "int", affine=(1, 0))
        # padded-view cache for shifted slice loads: name -> {const: padded}
        self._pad_cache: dict[str, dict[int, Any]] = {}
        # remainder stack (statements that can still run after the current
        # one, per enclosing block) — liveness input for free-run
        # elimination; and the active (mask, names) free-run grant
        self._after_stack: list[list] = []
        self._freerun: tuple | None = None
        # private fixed-size arrays (``float acc[4];``): name -> length;
        # the env value is a (length, *shape) vector-per-element stack
        self.private: dict[str, int] = {}
        # per-innermost-loop masks: lanes that executed `break` (persist
        # for the loop's remaining iterations) / `continue` (reset per
        # iteration) — saved and restored by _exec_loop
        self.break_mask: Any = None
        self.continue_mask: Any = None
        # statically-proven lane-uniform locals (set by build_kernel_fn
        # from _uniform_vars) — drives scalarized uniform-index loads
        self.uniform_vars: set[str] = set()
        # helper functions (lang.FuncDef by name) inlined at call sites
        self.helpers: dict = {}

    def broadcast_scalar(self, val, dtype):
        """Materialize a scalar as a full work-item vector of this ctx's
        shape (subclasses may force a computed layout)."""
        return jnp.full(self.shape, val, dtype=dtype)

    def force_computed(self, vec):
        """Hook for the Pallas subclass: rewrite a (possibly constant)
        vector so Mosaic assigns it a non-replicated layout, making it a
        legal while-loop carry.  Identity for the XLA lowering."""
        return vec

    def padded_view(self, name: str, c: int):
        """Buffer padded so the shifted window [offset+c, offset+c+B) is
        always in bounds; returns (padded, left_pad).  Edge padding so an
        out-of-range element reads the nearest valid one — the SAME clamp
        semantics as the gather path (a zero pad would give the two load
        paths different out-of-bounds values for the same kernel)."""
        cache = self._pad_cache.setdefault(name, {})
        if c in cache:
            return cache[c]
        buf = self.bufs[name]
        lo, hi = max(0, -c), max(0, c)
        padded = jnp.pad(buf, (lo, hi), mode="edge")
        cache[c] = (padded, lo)
        return padded, lo

    def invalidate_padded(self, name: str) -> None:
        self._pad_cache.pop(name, None)

    def active_mask(self):
        """Combined current mask (branch mask minus returned / broken /
        continued items)."""
        m = self.mask
        for excl in (self.return_mask, self.break_mask, self.continue_mask):
            if excl is not None:
                inv = jnp.logical_not(excl)
                m = inv if m is None else jnp.logical_and(m, inv)
        return m


# ---------------------------------------------------------------------------
# expression evaluation
# ---------------------------------------------------------------------------


def _as_dtype(v: KVal, ctype: str) -> KVal:
    if v.ctype == ctype:
        return v
    dt = ctype_to_dtype(ctype)
    val = v.value
    if hasattr(val, "astype"):
        val = val.astype(dt)
    else:
        val = jnp.asarray(val, dtype=dt) if not isinstance(val, (int, float, bool)) else dt.type(val)
    affine = v.affine if (v.ctype in _INT_TYPES and ctype in _INT_TYPES) else None
    return KVal(val, ctype, affine)


def _const_int(v: KVal) -> Optional[int]:
    """Python-int view of a compile-time constant, else None."""
    if v.affine is not None and v.affine[0] == 0 and isinstance(v.affine[1], int):
        return v.affine[1]
    if isinstance(v.value, int):
        return v.value
    return None


def _eval(ctx: _Ctx, node) -> KVal:
    if isinstance(node, Num):
        return KVal(node.value, node.ctype, affine=(0, node.value) if node.ctype in _INT_TYPES else None)
    if isinstance(node, Var):
        if node.name in ctx.private:
            raise KernelLanguageError(
                f"private array {node.name!r} used without an index",
                line=node.line,
            )
        if node.name in ctx.env:
            return ctx.env[node.name]
        raise KernelCompileError(f"undefined variable {node.name!r}", line=node.line)
    if isinstance(node, Index):
        return _load(ctx, node)
    if isinstance(node, BinOp):
        return _binop(ctx, node)
    if isinstance(node, UnOp):
        v = _eval(ctx, node.operand)
        if node.op == "+":
            return v
        if node.op == "-":
            aff = None
            if v.affine is not None:
                s, o = v.affine
                aff = (-s, -o if isinstance(o, int) else -o)
            return KVal(-_num(v), v.ctype if v.ctype in _FLOAT_TYPES else _promote(v.ctype, "int"), aff)
        if node.op == "!":
            return KVal(jnp.logical_not(_truthy(v)), "bool")
        if node.op == "~":
            return KVal(~_num(_as_dtype(v, _promote(v.ctype, "int"))), _promote(v.ctype, "int"))
        raise KernelCompileError(f"unknown unary op {node.op}", line=node.line)
    if isinstance(node, Ternary):
        c = _truthy(_eval(ctx, node.cond))
        a = _eval(ctx, node.then)
        b = _eval(ctx, node.other)
        t = _promote(a.ctype, b.ctype)
        av, bv = _num(_as_dtype(a, t)), _num(_as_dtype(b, t))
        return KVal(jnp.where(c, av, bv), t)
    if isinstance(node, Cast):
        return _as_dtype(_eval(ctx, node.operand), node.ctype)
    if isinstance(node, Call):
        return _call(ctx, node)
    raise KernelCompileError(f"cannot evaluate node {type(node).__name__}", line=getattr(node, "line", 0))


def _num(v: KVal):
    """Raw numeric payload with the KVal's dtype materialized."""
    val = v.value
    if isinstance(val, (int, float, bool)):
        return ctype_to_dtype(v.ctype).type(val)
    return val


def _truthy(v: KVal):
    if v.ctype == "bool":
        return v.value if hasattr(v.value, "dtype") else jnp.asarray(v.value, jnp.bool_)
    return _num(v) != 0


def _binop(ctx: _Ctx, node: BinOp) -> KVal:
    op = node.op
    if op in ("&&", "||"):
        l = _truthy(_eval(ctx, node.left))
        r = _truthy(_eval(ctx, node.right))
        fn = jnp.logical_and if op == "&&" else jnp.logical_or
        return KVal(fn(l, r), "bool")

    a = _eval(ctx, node.left)
    b = _eval(ctx, node.right)

    if op in ("==", "!=", "<", ">", "<=", ">="):
        t = _promote(a.ctype, b.ctype)
        av, bv = _num(_as_dtype(a, t)), _num(_as_dtype(b, t))
        fns = {
            "==": jnp.equal, "!=": jnp.not_equal, "<": jnp.less,
            ">": jnp.greater, "<=": jnp.less_equal, ">=": jnp.greater_equal,
        }
        return KVal(fns[op](av, bv), "bool")

    t = _promote(a.ctype, b.ctype)
    ac, bc = _as_dtype(a, t), _as_dtype(b, t)
    av, bv = _num(ac), _num(bc)

    affine = None
    if t in _INT_TYPES:
        ka, kb = ac.affine, bc.affine
        ca, cb = _const_int(ac), _const_int(bc)
        if op == "+" and ka is not None and kb is not None:
            affine = (ka[0] + kb[0], _add_off(ka[1], kb[1]))
        elif op == "-" and ka is not None and kb is not None:
            affine = (ka[0] - kb[0], _sub_off(ka[1], kb[1]))
        elif op == "*" and ka is not None and cb is not None:
            affine = (ka[0] * cb, _mul_off(ka[1], cb))
        elif op == "*" and kb is not None and ca is not None:
            affine = (kb[0] * ca, _mul_off(kb[1], ca))

    if op == "+":
        return KVal(av + bv, t, affine)
    if op == "-":
        return KVal(av - bv, t, affine)
    if op == "*":
        return KVal(av * bv, t, affine)
    if op == "/":
        if t in _FLOAT_TYPES:
            return KVal(av / bv, t)
        return KVal(lax.div(jnp.asarray(av), jnp.asarray(bv)), t)  # C truncating division
    if op == "%":
        if t in _FLOAT_TYPES:
            return KVal(jnp.fmod(av, bv), t)
        return KVal(lax.rem(jnp.asarray(av), jnp.asarray(bv)), t)  # C remainder semantics
    if op in ("&", "|", "^"):
        it = t if t in _INT_TYPES else "int"
        av, bv = _num(_as_dtype(ac, it)), _num(_as_dtype(bc, it))
        fns = {"&": jnp.bitwise_and, "|": jnp.bitwise_or, "^": jnp.bitwise_xor}
        return KVal(fns[op](av, bv), it)
    if op in ("<<", ">>"):
        it = t if t in _INT_TYPES else "int"
        av = _num(_as_dtype(ac, it))
        bv = _num(_as_dtype(bc, it))
        fn = jnp.left_shift if op == "<<" else jnp.right_shift
        return KVal(fn(av, bv), it)
    raise KernelCompileError(f"unknown operator {op}", line=node.line)


def _add_off(a, b):
    return a + b


def _sub_off(a, b):
    return a - b


def _mul_off(a, c):
    return a * c


# ---------------------------------------------------------------------------
# builtins
# ---------------------------------------------------------------------------

_UNARY_FLOAT = {
    "sqrt": jnp.sqrt, "rsqrt": lax.rsqrt, "cbrt": jnp.cbrt, "exp": jnp.exp,
    "exp2": jnp.exp2, "exp10": lambda x: jnp.power(10.0, x), "log": jnp.log,
    "log2": jnp.log2, "log10": jnp.log10, "sin": jnp.sin, "cos": jnp.cos,
    "tan": jnp.tan, "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh, "asinh": jnp.arcsinh,
    "acosh": jnp.arccosh, "atanh": jnp.arctanh, "fabs": jnp.abs,
    "floor": jnp.floor, "ceil": jnp.ceil, "round": jnp.round, "rint": jnp.round,
    "trunc": jnp.trunc, "erf": lax.erf, "erfc": lax.erfc,
    "degrees": jnp.degrees, "radians": jnp.radians, "sign": jnp.sign,
}

_BINARY_FLOAT = {
    "pow": jnp.power, "powr": jnp.power, "atan2": jnp.arctan2,
    "fmod": jnp.fmod, "remainder": jnp.remainder, "hypot": jnp.hypot,
    "copysign": jnp.copysign, "fdim": lambda a, b: jnp.maximum(a - b, 0.0),
    "nextafter": jnp.nextafter,
}

_UNSUPPORTED_CALLS = {
    "barrier": "work-group barriers (no shared memory in the vectorized TPU contract; "
               "use separate kernels — the reference's pipelines exist for exactly this)",
    "mem_fence": "memory fences (XLA orders operations by data flow)",
    "work_group_barrier": "work-group barriers",
}


def _inline_helper(ctx: _Ctx, fdef, arg_nodes, call_line: int) -> KVal:
    """Inline a helper call: evaluate args in the caller's scope, execute
    the body in a FRESH scope — helpers see ONLY their params and locals
    (no captured kernel variables, no buffers, no caller private arrays,
    and no inherited uniformity facts, whose names are kernel-scoped) —
    and return the final ``return expr;`` value coerced to the declared
    return type."""
    if len(arg_nodes) != len(fdef.params):
        raise KernelCompileError(
            f"helper {fdef.name!r} takes {len(fdef.params)} argument(s), "
            f"got {len(arg_nodes)}", line=call_line,
        )
    stack = ctx.info.setdefault("inline_stack", [])
    if fdef.name in stack:
        raise KernelLanguageError(
            f"recursive helper call {fdef.name!r} is not supported",
            line=call_line,
        )
    vals = [_eval(ctx, a) for a in arg_nodes]
    saved_env, saved_priv = ctx.env, ctx.private
    saved_bufs, saved_bct = ctx.bufs, ctx.buf_ctypes
    saved_uniform = ctx.uniform_vars
    ctx.env = {
        p.name: _as_dtype(v, p.ctype) for p, v in zip(fdef.params, vals)
    }
    ctx.private = {}
    ctx.bufs = {}
    ctx.buf_ctypes = {}
    ctx.uniform_vars = set()
    stack.append(fdef.name)
    # the final `return expr;` still READS helper locals after any loops in
    # the body — it must be visible to free-run liveness (else a loop
    # would treat the returned accumulator as dead-after and skip its
    # per-lane freeze)
    ctx._after_stack.append([fdef.body[-1]])
    try:
        _exec_block(ctx, fdef.body[:-1])
        ret = _eval(ctx, fdef.body[-1].value)
        return _as_dtype(ret, fdef.ret_ctype)
    finally:
        ctx._after_stack.pop()
        stack.pop()
        ctx.env = saved_env
        ctx.private = saved_priv
        ctx.bufs = saved_bufs
        ctx.buf_ctypes = saved_bct
        ctx.uniform_vars = saved_uniform


def _call(ctx: _Ctx, node: Call) -> KVal:
    name = node.name
    if name in ctx.helpers:
        return _inline_helper(ctx, ctx.helpers[name], node.args, node.line)
    if name.startswith("native_") or name.startswith("half_"):
        name = name.split("_", 1)[1]
    if name.startswith("atomic_") or name.startswith("atom_"):
        raise KernelLanguageError(
            f"{node.name}: atomics are not supported in the vectorized TPU contract; "
            "express reductions as separate reduction kernels",
            line=node.line,
        )
    if name in _UNSUPPORTED_CALLS:
        raise KernelLanguageError(f"{name}: {_UNSUPPORTED_CALLS[name]}", line=node.line)

    args = [_eval(ctx, a) for a in node.args]

    if name in ("get_global_id", "get_local_id", "get_group_id", "get_global_size",
                "get_local_size", "get_num_groups", "get_global_offset", "get_work_dim"):
        dim = _const_int(args[0]) if args else 0
        if name != "get_work_dim" and dim not in (0, None):
            raise KernelLanguageError(
                f"{name}({dim}): only dimension 0 is supported (the reference's "
                "NDRange is 1-D, ClNdRange.cs:29-71)", line=node.line)
        if name == "get_global_id":
            return ctx.gid
        if name == "get_global_size":
            return KVal(ctx.global_size, "int", affine=(0, ctx.global_size) if isinstance(ctx.global_size, int) else None)
        if name == "get_local_size":
            return KVal(ctx.local_size, "int", affine=(0, ctx.local_size))
        if name == "get_local_id":
            g = _num(ctx.gid)
            return KVal(lax.rem(g, jnp.int32(ctx.local_size)), "int")
        if name == "get_group_id":
            g = _num(ctx.gid)
            return KVal(lax.div(g, jnp.int32(ctx.local_size)), "int")
        if name == "get_num_groups":
            gs = ctx.global_size
            return KVal(gs // ctx.local_size if isinstance(gs, int) else lax.div(gs, ctx.local_size), "int")
        if name == "get_global_offset":
            return KVal(0, "int", affine=(0, 0))
        return KVal(1, "int", affine=(0, 1))  # get_work_dim

    if name in _UNARY_FLOAT:
        a = args[0]
        t = a.ctype if a.ctype in _FLOAT_TYPES else "float"
        if name in ("fabs", "sign") and a.ctype in _INT_TYPES:
            t = a.ctype
            return KVal(jnp.abs(_num(a)) if name == "fabs" else jnp.sign(_num(a)), t)
        return KVal(_UNARY_FLOAT[name](_num(_as_dtype(a, t))), t)

    if name in _BINARY_FLOAT:
        t = _promote(args[0].ctype, args[1].ctype)
        if t not in _FLOAT_TYPES:
            t = "float"
        av, bv = _num(_as_dtype(args[0], t)), _num(_as_dtype(args[1], t))
        return KVal(_BINARY_FLOAT[name](av, bv), t)

    if name == "abs":
        return KVal(jnp.abs(_num(args[0])), args[0].ctype)
    if name in ("min", "fmin"):
        t = _promote(args[0].ctype, args[1].ctype)
        return KVal(jnp.minimum(_num(_as_dtype(args[0], t)), _num(_as_dtype(args[1], t))), t)
    if name in ("max", "fmax"):
        t = _promote(args[0].ctype, args[1].ctype)
        return KVal(jnp.maximum(_num(_as_dtype(args[0], t)), _num(_as_dtype(args[1], t))), t)
    if name == "clamp":
        t = _promote(_promote(args[0].ctype, args[1].ctype), args[2].ctype)
        x, lo, hi = (_num(_as_dtype(a, t)) for a in args)
        return KVal(jnp.clip(x, lo, hi), t)
    if name in ("mad", "fma"):
        t = "float"
        for a in args:
            t = _promote(t, a.ctype) if a.ctype in _FLOAT_TYPES else t
        a, b, c = (_num(_as_dtype(x, t)) for x in args)
        return KVal(a * b + c, t)
    if name == "mix":
        t = "float"
        a, b, w = (_num(_as_dtype(x, t)) for x in args)
        return KVal(a + (b - a) * w, t)
    if name == "step":
        t = "float"
        edge, x = (_num(_as_dtype(a, t)) for a in args)
        return KVal(jnp.where(x < edge, 0.0, 1.0).astype(ctype_to_dtype(t)), t)
    if name == "smoothstep":
        t = "float"
        e0, e1, x = (_num(_as_dtype(a, t)) for a in args)
        u = jnp.clip((x - e0) / (e1 - e0), 0.0, 1.0)
        return KVal(u * u * (3.0 - 2.0 * u), t)
    if name == "select":
        # OpenCL select(a, b, c) == c ? b : a
        c = _truthy(args[2])
        t = _promote(args[0].ctype, args[1].ctype)
        return KVal(jnp.where(c, _num(_as_dtype(args[1], t)), _num(_as_dtype(args[0], t))), t)
    if name == "isnan":
        return KVal(jnp.isnan(_num(args[0])), "bool")
    if name == "isinf":
        return KVal(jnp.isinf(_num(args[0])), "bool")
    if name == "isfinite":
        return KVal(jnp.isfinite(_num(args[0])), "bool")

    raise KernelLanguageError(f"unknown function {node.name!r}", line=node.line)


# ---------------------------------------------------------------------------
# loads / stores
# ---------------------------------------------------------------------------


def _private_index(ctx: _Ctx, node: Index, k: int):
    """Evaluate a private-array index: (const | per-lane vector, clamped)."""
    idx = _eval(ctx, node.index)
    if idx.ctype not in _INT_TYPES:
        raise KernelLanguageError("array index must be an integer", line=node.line)
    c = _const_int(idx)
    if c is not None:
        if not 0 <= c < k:
            raise KernelCompileError(
                f"private array index {c} out of bounds [0, {k})", line=node.line
            )
        return c
    iv = _num(_as_dtype(idx, "int"))
    if not hasattr(iv, "ndim") or iv.ndim == 0:
        iv = jnp.full(ctx.shape, iv, dtype=jnp.int32)
    return jnp.clip(iv, 0, k - 1)


def _private_load(ctx: _Ctx, node: Index) -> KVal:
    k = ctx.private[node.base]
    val = ctx.env[node.base]
    ix = _private_index(ctx, node, k)
    if isinstance(ix, int):
        return KVal(val.value[ix], val.ctype)
    return KVal(jnp.take_along_axis(val.value, ix[None], axis=0)[0], val.ctype)


def _private_store(ctx: _Ctx, node: Index, v: KVal) -> None:
    k = ctx.private[node.base]
    cur = ctx.env[node.base]
    payload = _num(_as_dtype(v, cur.ctype))
    if not hasattr(payload, "ndim") or payload.ndim == 0:
        payload = ctx.broadcast_scalar(payload, ctype_to_dtype(cur.ctype))
    m = ctx.active_mask()
    ix = _private_index(ctx, node, k)
    if isinstance(ix, int):
        row = cur.value[ix]
        new_row = payload if m is None else jnp.where(m, payload, row)
        ctx.env[node.base] = KVal(cur.value.at[ix].set(new_row), cur.ctype)
        return
    # per-lane dynamic element: each lane updates its own (index, lane) cell
    gathered = jnp.take_along_axis(cur.value, ix[None], axis=0)[0]
    new_vals = payload if m is None else jnp.where(m, payload, gathered)
    ctx.env[node.base] = KVal(_scatter_lanes(cur.value, ix, new_vals), cur.ctype)


def _scatter_lanes(stack, ix, vals):
    """stack[(ix[lane], lane)] = vals[lane] for every lane position."""
    lanes = jnp.indices(stack.shape[1:])
    return stack.at[(ix,) + tuple(lanes)].set(vals)


def _loaded(value, ctype: str) -> KVal:
    """Wrap a loaded buffer value as its DECLARED ctype: when the caller's
    array dtype differs (e.g. f16 storage behind a float-declared param),
    the load converts so every in-kernel computation runs in the declared
    type — the store's cast back to storage dtype is the symmetric
    inverse.  Without this, loop carries seeded from a load keep the
    storage dtype while arithmetic promotes, and lax.while raises a carry
    dtype mismatch at trace time."""
    dt = ctype_to_dtype(ctype)
    if hasattr(value, "dtype") and value.dtype != dt:
        value = value.astype(dt)
    return KVal(value, ctype)


def _load(ctx: _Ctx, node: Index) -> KVal:
    if node.base in ctx.private:
        return _private_load(ctx, node)
    if node.base not in ctx.bufs:
        raise KernelCompileError(f"{node.base!r} is not an array parameter", line=node.line)
    buf = ctx.bufs[node.base]
    ctype = ctx.buf_ctypes[node.base]
    idx = _eval(ctx, node.index)
    if idx.ctype not in _INT_TYPES:
        raise KernelLanguageError("array index must be an integer", line=node.line)
    if ctx.pallas:
        kv = ctx.pallas_load(node, buf, ctype, idx)  # type: ignore[attr-defined]
        return _loaded(kv.value, ctype)
    if idx.affine is not None and idx.affine[0] == 1 and isinstance(idx.affine[1], int):
        c = idx.affine[1]
        if c == 0:
            start = jnp.asarray(ctx.offset, jnp.int32)
            return _loaded(lax.dynamic_slice(buf, (start,), (ctx.B,)), ctype)
        padded, lo = ctx.padded_view(node.base, c)
        start = jnp.asarray(ctx.offset + c + lo, jnp.int32)
        return _loaded(lax.dynamic_slice(padded, (start,), (ctx.B,)), ctype)
    if ctx.uniform_vars and _expr_uniform(
        node.index, ctx.uniform_vars, frozenset(ctx.private)
    ):
        # lane-uniform index (the n-body ``x[j]`` pattern): ONE element
        # load broadcast to the chunk instead of a (B,)-wide gather per
        # loop iteration — the dominant cost of gather-loop kernels
        iv = _num(_as_dtype(idx, "int"))
        sidx = iv if (not hasattr(iv, "ndim") or iv.ndim == 0) else iv.reshape(-1)[0]
        sidx = jnp.clip(jnp.asarray(sidx, jnp.int32), 0, buf.shape[0] - 1)
        return _loaded(lax.dynamic_slice(buf, (sidx,), (1,))[0], ctype)
    iv = _num(_as_dtype(idx, "int"))
    if not hasattr(iv, "ndim") or iv.ndim == 0:
        iv = jnp.full((ctx.B,), iv, dtype=jnp.int32)
    return _loaded(jnp.take(buf, iv, mode="clip"), ctype)


def _store(ctx: _Ctx, node: Index, val: KVal) -> None:
    if node.base in ctx.private:
        _private_store(ctx, node, val)
        return
    if node.base not in ctx.bufs:
        raise KernelCompileError(f"{node.base!r} is not an array parameter", line=node.line)
    buf = ctx.bufs[node.base]
    ctype = ctx.buf_ctypes[node.base]
    v = _num(_as_dtype(val, ctype))
    if not hasattr(v, "ndim") or v.ndim == 0:
        v = ctx.broadcast_scalar(v, ctype_to_dtype(ctype))
    if hasattr(buf, "dtype") and v.dtype != buf.dtype:
        # a store converts to the buffer's STORAGE dtype (a caller may
        # pass e.g. f16 arrays to a float-declared kernel — compute runs
        # in the declared ctype, storage keeps the array's dtype); the
        # gather path's .at[].set already casts, the slice paths below
        # would crash on the mismatch instead
        v = v.astype(buf.dtype)
    idx = _eval(ctx, node.index)
    if ctx.pallas:
        ctx.pallas_store(node, buf, ctype, idx, v)  # type: ignore[attr-defined]
        return
    m = ctx.active_mask()
    if (idx.affine is not None and idx.affine[0] == 1
            and isinstance(idx.affine[1], int) and m is None):
        c = idx.affine[1]
        if c == 0:
            start = jnp.asarray(ctx.offset, jnp.int32)
            ctx.bufs[node.base] = lax.dynamic_update_slice(buf, v, (start,))
        else:
            n = buf.shape[0]
            lo, hi = max(0, -c), max(0, c)
            padded = jnp.pad(buf, (lo, hi))
            start = jnp.asarray(ctx.offset + c + lo, jnp.int32)
            updated = lax.dynamic_update_slice(padded, v, (start,))
            ctx.bufs[node.base] = lax.slice(updated, (lo,), (lo + n,))
        ctx.invalidate_padded(node.base)
    else:
        iv = _num(_as_dtype(idx, "int"))
        if not hasattr(iv, "ndim") or iv.ndim == 0:
            iv = jnp.full((ctx.B,), iv, dtype=jnp.int32)
        if m is not None:
            # redirect masked-off lanes out of bounds and drop them — a
            # read-modify-write would race with active lanes hitting the
            # same index (duplicate-index scatter order is unspecified)
            iv = jnp.where(m, iv, jnp.int32(buf.shape[0]))
        ctx.bufs[node.base] = buf.at[iv].set(v, mode="drop")
        ctx.invalidate_padded(node.base)
    ctx.stored.add(node.base)


# ---------------------------------------------------------------------------
# statements
# ---------------------------------------------------------------------------


def _exec_block(ctx: _Ctx, stmts: list) -> None:
    # remainder stack: lets a loop see every statement that can still run
    # after it returns (this block's tail + all enclosing blocks' tails) —
    # the liveness input for free-run predication elimination (_exec_loop)
    stack = ctx._after_stack
    for i, s in enumerate(stmts):
        stack.append(stmts[i + 1 :])
        try:
            _exec(ctx, s)
        finally:
            stack.pop()


def _exec(ctx: _Ctx, node) -> None:
    if isinstance(node, Decl):
        for name, init in node.names:
            if name in node.arrays:
                if ctx.pallas:
                    from .pallas_backend import PallasUnsupported

                    raise PallasUnsupported(
                        f"private array {name!r} (Pallas tile path has no "
                        "per-item scratch stacking; XLA lowering handles it)"
                    )
                k = node.arrays[name]
                ctx.private[name] = k
                ctx.env[name] = KVal(
                    jnp.zeros((k,) + ctx.shape, ctype_to_dtype(node.ctype)),
                    node.ctype,
                )
                continue
            if init is not None:
                v = _as_dtype(_eval(ctx, init), node.ctype)
            else:
                v = KVal(ctype_to_dtype(node.ctype).type(0), node.ctype,
                         affine=(0, 0) if node.ctype in _INT_TYPES else None)
            ctx.env[name] = v
        return
    if isinstance(node, Assign):
        if node.target is None:  # bare call statement
            _eval(ctx, node.value)
            return
        _assign(ctx, node.target, node.op, node.value)
        return
    if isinstance(node, CrementStmt):
        one = Num(value=1, ctype="int", line=node.line)
        _assign(ctx, node.target, "+=" if node.op == "++" else "-=", one)
        return
    if isinstance(node, If):
        _exec_if(ctx, node)
        return
    if isinstance(node, (For, While)):
        _exec_loop(ctx, node)
        return
    if isinstance(node, DoWhile):
        # body once unconditionally (under the active mask), then the loop.
        # The first pass counts as "inside a loop" for nested loops: the
        # body re-runs via the While, so an inner loop's free-run liveness
        # cannot be derived from the remainder stack alone.  break/continue
        # in the first pass bind to THIS do-while: continue skips the rest
        # of the pass, break also excludes the lane from the While.
        saved_bk, saved_cn = ctx.break_mask, ctx.continue_mask
        ctx.break_mask = None
        ctx.continue_mask = None
        ctx.info["in_loop"] = ctx.info.get("in_loop", 0) + 1
        try:
            _exec_block(ctx, node.body)
        finally:
            ctx.info["in_loop"] -= 1
            first_broke = ctx.break_mask
            ctx.break_mask, ctx.continue_mask = saved_bk, saved_cn
        loop = While(cond=node.cond, body=node.body, line=node.line)
        if first_broke is not None:
            outer = ctx.mask
            nb = jnp.logical_not(first_broke)
            ctx.mask = nb if outer is None else jnp.logical_and(outer, nb)
            try:
                _exec_loop(ctx, loop)
            finally:
                ctx.mask = outer
        else:
            _exec_loop(ctx, loop)
        return
    if isinstance(node, (Break, Continue)):
        if not ctx.info.get("in_loop", 0):
            raise KernelLanguageError(
                f"'{'break' if isinstance(node, Break) else 'continue'}' "
                "outside a loop", line=node.line,
            )
        m = ctx.active_mask()
        if m is None:
            m = jnp.ones(ctx.shape, jnp.bool_)
        if isinstance(node, Break):
            ctx.break_mask = (
                m if ctx.break_mask is None else jnp.logical_or(ctx.break_mask, m)
            )
        else:
            ctx.continue_mask = (
                m if ctx.continue_mask is None
                else jnp.logical_or(ctx.continue_mask, m)
            )
        return
    if isinstance(node, Return):
        m = ctx.active_mask()
        if m is None:
            m = jnp.ones(ctx.shape, jnp.bool_)
        ctx.return_mask = m if ctx.return_mask is None else jnp.logical_or(ctx.return_mask, m)
        return
    raise KernelCompileError(f"cannot execute node {type(node).__name__}", line=getattr(node, "line", 0))


def _assign(ctx: _Ctx, target, op: str, value_expr) -> None:
    rhs = _eval(ctx, value_expr)
    if op != "=":
        base_op = op[:-1]
        cur = _eval(ctx, target)
        rhs = _binop(ctx, BinOp(op=base_op, left=_Lit(cur), right=_Lit(rhs), line=getattr(target, "line", 0)))
    if isinstance(target, Var):
        name = target.name
        if name in ctx.private:
            raise KernelLanguageError(
                f"cannot assign to private array {name!r} as a whole; "
                "assign elements", line=getattr(target, "line", 0),
            )
        if name in ctx.env:
            old = ctx.env[name]
            new = _as_dtype(rhs, old.ctype)  # assignment keeps the declared C type
            m = ctx.active_mask()
            fr = ctx._freerun
            if (
                m is not None
                and fr is not None
                and m is fr[0]
                and name in fr[1]
            ):
                m = None  # free-run: dead lanes' values are never observed
            if m is not None:
                ov, nv = _num(old), _num(new)
                merged = jnp.where(m, nv, ov)
                new = KVal(merged, old.ctype, None)
            ctx.env[name] = new
        else:
            raise KernelCompileError(f"assignment to undeclared variable {name!r}",
                                     line=getattr(target, "line", 0))
        return
    if isinstance(target, Index):
        _store(ctx, target, rhs)
        return
    raise KernelCompileError("invalid assignment target", line=getattr(target, "line", 0))


class _Lit:
    """Wrap an already-evaluated KVal so it can re-enter _eval."""

    def __init__(self, v: KVal):
        self.v = v
        self.line = 0


_orig_eval = _eval


def _eval(ctx: _Ctx, node) -> KVal:  # noqa: F811 - deliberate wrapper
    if isinstance(node, _Lit):
        return node.v
    return _orig_eval(ctx, node)


def _exec_if(ctx: _Ctx, node: If) -> None:
    cond = _truthy(_eval(ctx, node.cond))
    is_const_true = isinstance(node.cond, Num) and node.cond.value == 1
    if is_const_true and not node.other:
        _exec_block(ctx, node.then)  # bare { } block
        return

    outer_mask = ctx.mask
    cvec = jnp.broadcast_to(cond, ctx.shape) if (not hasattr(cond, "ndim") or cond.ndim == 0) else cond

    # early-return pattern: if (cond) return;
    then_mask = cvec if outer_mask is None else jnp.logical_and(outer_mask, cvec)
    else_mask = jnp.logical_not(cvec) if outer_mask is None else jnp.logical_and(outer_mask, jnp.logical_not(cvec))

    ctx.mask = then_mask
    # the else branch runs AFTER the then branch in trace order: for a loop
    # inside `then`, reads in `other` are still pending — they must count
    # as "read after the loop" for free-run liveness
    ctx._after_stack.append(node.other)
    try:
        _exec_block(ctx, node.then)
    finally:
        ctx._after_stack.pop()
    if node.other:
        ctx.mask = else_mask
        _exec_block(ctx, node.other)
    ctx.mask = outer_mask


def _exec_loop(ctx: _Ctx, node) -> None:
    """Lower for/while to a vectorized lax.while_loop with a per-item active
    mask (see module docstring)."""
    if isinstance(node, For):
        if node.init is not None:
            _exec(ctx, node.init)
        cond_expr = node.cond if node.cond is not None else Num(value=1, ctype="int", line=node.line)
        body = list(node.body) + ([node.step] if node.step is not None else [])
        body_core, step_stmt = list(node.body), node.step
    else:
        cond_expr = node.cond
        body = list(node.body)
        body_core, step_stmt = body, None

    carried_vars = sorted(_assigned_vars(body) & set(ctx.env.keys()))
    carried_bufs = sorted(_stored_bufs(body) & set(ctx.bufs.keys()))

    outer_mask = ctx.active_mask()

    # Free-run predication elimination: a carried variable that is never
    # read AFTER the loop needs no per-lane where-freeze — once a lane's
    # active bit clears it can never re-set (each pass computes
    # ``active = prev AND cond``, monotone in ``prev``), so a dead lane's
    # free-running value only feeds the cond (ANDed away) and masked
    # stores.  This is the optimization the hand-written mandelbrot
    # kernel applies manually (ops/mandelbrot.py: escaped orbits free-run
    # to inf) and removes the dominant per-iteration where chain.  Only at
    # top level (in_loop == 0): inside an enclosing loop the body re-runs,
    # so "after" cannot be derived from the remainder stack alone.
    freerun: set[str] = set()
    if not ctx.info.get("in_loop", 0):
        read_later: set[str] = set()
        for rest in ctx._after_stack:
            _vars_read(rest, read_later)
        freerun = {v for v in carried_vars if v not in read_later}

    # broadcast carried locals to the work-item shape so loop-carry shapes
    # are stable (broadcast_scalar: the Pallas subclass forces a computed
    # Mosaic layout — a jnp.full constant gets a replicated layout the
    # body's computed carries cannot be relaid out to)
    for name in carried_vars:
        v = ctx.env[name]
        val = _num(v)
        if not hasattr(val, "ndim") or val.ndim == 0:
            val = ctx.broadcast_scalar(val, ctype_to_dtype(v.ctype))
        ctx.env[name] = KVal(val, v.ctype, None)

    var_ctypes = {k: ctx.env[k].ctype for k in carried_vars}

    def eval_cond(env, bufs):
        saved_env, saved_bufs, saved_mask = ctx.env, ctx.bufs, ctx.mask
        ctx.env = dict(saved_env)
        ctx.env.update({k: KVal(v, var_ctypes[k], None) for k, v in env.items()})
        ctx.bufs = dict(saved_bufs)
        ctx.bufs.update(bufs)
        c = _truthy(_eval(ctx, cond_expr))
        ctx.env, ctx.bufs, ctx.mask = saved_env, saved_bufs, saved_mask
        if not hasattr(c, "ndim") or c.ndim == 0:
            c = jnp.broadcast_to(c, ctx.shape)
        return c

    init_env = {k: ctx.env[k].value for k in carried_vars}
    init_bufs = {k: ctx.bufs[k] for k in carried_bufs}

    # Pallas/Mosaic: no bool array in a while-loop carry (relayout
    # limitation — the same constraint the hand-written mandelbrot kernel
    # works around, ops/mandelbrot.py); carry the mask as f32 0/1 and
    # re-derive the bool inside the body
    mask_in_carry_f32 = ctx.pallas

    def to_carry_mask(m):
        return ctx.force_computed(m.astype(jnp.float32)) if mask_in_carry_f32 else m

    def from_carry_mask(m):
        return (m > 0.0) if mask_in_carry_f32 else m

    # ROTATED loop: the carry holds the mask of lanes that executed the
    # PREVIOUS pass; each body pass evaluates the condition FIRST (on the
    # carried state), ANDs it in, and executes under that mask.  Putting
    # cond and body in the same trace lets XLA CSE their shared
    # subexpressions (the end-of-body placement recomputed e.g. zx*zx both
    # in the cond and in the next pass's body — ~15% of mandelbrot's
    # per-iteration work).  Price: one trailing fully-masked pass before
    # cond_fun sees an all-false mask (and one masked pass for loops never
    # entered) — masked execution has no observable effects.
    prev0 = outer_mask if outer_mask is not None else jnp.ones(ctx.shape, jnp.bool_)

    def cond_fun(carry):
        prev, _, _ = carry
        if mask_in_carry_f32:
            return jnp.sum(prev) > 0.0
        return jnp.any(prev)

    def body_fun(carry):
        prev, env_vals, buf_vals = carry
        prev = from_carry_mask(prev)
        saved_env, saved_bufs, saved_mask = dict(ctx.env), dict(ctx.bufs), ctx.mask
        saved_stored = set(ctx.stored)
        saved_rm = ctx.return_mask
        saved_fr = ctx._freerun
        saved_bk, saved_cn = ctx.break_mask, ctx.continue_mask
        ctx.info["in_loop"] = ctx.info.get("in_loop", 0) + 1
        try:
            for k in carried_vars:
                ctx.env[k] = KVal(env_vals[k], var_ctypes[k], None)
            for k in carried_bufs:
                ctx.bufs[k] = buf_vals[k]
            ctx._pad_cache.clear()  # buffers swapped to loop tracers
            active = jnp.logical_and(prev, eval_cond(env_vals, buf_vals))
            ctx.mask = active
            ctx.return_mask = None
            ctx.break_mask = None      # break binds to THIS loop
            ctx.continue_mask = None
            # assignments whose mask is EXACTLY this loop's active mask may
            # skip the where-merge for free-run variables (see above)
            ctx._freerun = (active, freerun) if freerun else None
            env_keys_before = set(ctx.env.keys())
            _exec_block(ctx, body_core)
            # C semantics: `continue` jumps to the for-step (which still
            # runs for continued lanes); `break` skips it too
            ctx.continue_mask = None
            if step_stmt is not None:
                _exec(ctx, step_stmt)
            if ctx.return_mask is not None:
                raise KernelLanguageError(
                    "'return' inside a loop is not supported; use the loop condition",
                    line=getattr(node, "line", 0),
                )
            new_env = {k: _num(ctx.env[k]) for k in carried_vars}
            new_bufs = {k: ctx.bufs[k] for k in carried_bufs}
            # drop loop-local declarations so carry structure stays stable
            # (private-array registrations scope out with their env entry,
            # else a loop-local array would shadow a same-named buffer
            # param after the loop)
            for k in set(ctx.env.keys()) - env_keys_before:
                del ctx.env[k]
                ctx.private.pop(k, None)
            # lanes that broke leave the loop for good
            out_active = (
                active
                if ctx.break_mask is None
                else jnp.logical_and(active, jnp.logical_not(ctx.break_mask))
            )
            return (to_carry_mask(out_active), new_env, new_bufs)
        finally:
            ctx.info["in_loop"] -= 1
            ctx.env, ctx.bufs, ctx.mask = saved_env, saved_bufs, saved_mask
            ctx.stored = saved_stored | ctx.stored
            ctx.return_mask = saved_rm
            ctx._freerun = saved_fr
            ctx.break_mask, ctx.continue_mask = saved_bk, saved_cn

    active_f, env_f, bufs_f = lax.while_loop(
        cond_fun, body_fun, (to_carry_mask(prev0), init_env, init_bufs)
    )
    ctx._pad_cache.clear()
    for k in carried_vars:
        ctx.env[k] = KVal(env_f[k], var_ctypes[k], None)
    for k in carried_bufs:
        ctx.bufs[k] = bufs_f[k]
        ctx.stored.add(k)


# ---------------------------------------------------------------------------
# uniformity analysis — which locals provably hold the SAME value in every
# lane (work item) of a launch chunk.  A load indexed by a uniform
# expression (the n-body pattern ``x[j]`` with a uniform loop counter) can
# then be scalarized: one dynamic_slice element broadcast to the chunk,
# instead of a (B,)-wide gather per loop iteration.
# ---------------------------------------------------------------------------

_UNIFORM_CALLS = {
    "get_global_size", "get_local_size", "get_num_groups",
    "get_global_offset", "get_work_dim",
}
_LANE_CALLS = {"get_global_id", "get_local_id", "get_group_id"}
_PURE_BUILTINS = (
    set(_UNARY_FLOAT) | set(_BINARY_FLOAT)
    | {"abs", "min", "max", "fmin", "fmax", "clamp", "mad", "fma", "mix",
       "step", "smoothstep", "select", "isnan", "isinf", "isfinite"}
)


def _expr_uniform(node, uset: set[str], private: set[str] = frozenset()) -> bool:
    """True iff ``node`` provably evaluates identically in every lane."""
    if isinstance(node, Num):
        return True
    if isinstance(node, Var):
        return node.name in uset
    if isinstance(node, Index):
        # a BUFFER load at a uniform index yields the same element in every
        # lane; a PRIVATE array's rows are per-lane, so its loads never are
        if node.base in private:
            return False
        return _expr_uniform(node.index, uset, private)
    if isinstance(node, BinOp):
        return (_expr_uniform(node.left, uset, private)
                and _expr_uniform(node.right, uset, private))
    if isinstance(node, UnOp):
        return _expr_uniform(node.operand, uset, private)
    if isinstance(node, Cast):
        return _expr_uniform(node.operand, uset, private)
    if isinstance(node, Ternary):
        return (
            _expr_uniform(node.cond, uset, private)
            and _expr_uniform(node.then, uset, private)
            and _expr_uniform(node.other, uset, private)
        )
    if isinstance(node, Call):
        name = node.name
        if name.startswith(("native_", "half_")):
            name = name.split("_", 1)[1]
        if name in _LANE_CALLS:
            return False
        if name in _UNIFORM_CALLS:
            return True
        if name not in _PURE_BUILTINS:
            # user helpers (and anything unrecognized) may read lane state
            return False
        return all(_expr_uniform(a, uset, private) for a in node.args)
    return False  # unknown node kind: be conservative


def _has_divergent_exit(stmts: list, divergent: bool, uset, private) -> bool:
    """True if a break/continue can execute under a lane-divergent
    condition anywhere in THIS loop's body (nested loops scope their own
    break/continue and are checked when their own walk runs)."""
    for s in stmts:
        if isinstance(s, (Break, Continue)) and divergent:
            return True
        if isinstance(s, If):
            d = divergent or not _expr_uniform(s.cond, uset, private)
            if _has_divergent_exit(s.then, d, uset, private):
                return True
            if _has_divergent_exit(s.other, d, uset, private):
                return True
    return False


def _contains_return(stmts: list) -> bool:
    for s in stmts:
        if isinstance(s, Return):
            return True
        if isinstance(s, If) and (_contains_return(s.then) or _contains_return(s.other)):
            return True
        if isinstance(s, For):
            inner = ([s.init] if s.init is not None else []) + s.body + (
                [s.step] if s.step is not None else []
            )
            if _contains_return(inner):
                return True
        if isinstance(s, (While, DoWhile)) and _contains_return(s.body):
            return True
    return False


def _private_array_names(stmts: list, out: set[str] | None = None) -> set[str]:
    if out is None:
        out = set()
    for s in stmts:
        if isinstance(s, Decl):
            out.update(s.arrays)
        elif isinstance(s, If):
            _private_array_names(s.then, out)
            _private_array_names(s.other, out)
        elif isinstance(s, For):
            if s.init is not None:
                _private_array_names([s.init], out)
            _private_array_names(s.body, out)
        elif isinstance(s, (While, DoWhile)):
            _private_array_names(s.body, out)
    return out


def _uniform_vars(body: list, value_params: set[str]) -> set[str]:
    """Monotone-poisoning fixed point: start assuming every local is
    uniform; poison any variable assigned a non-uniform value or assigned
    under a non-uniform condition (divergent masks make merged values
    differ per lane); repeat until stable."""
    # an early `return` folds into a persistent per-lane return-mask that
    # divergently suppresses EVERY later assignment — modeling which
    # suffixes that poisons is subtle, and kernels with early returns are
    # rare, so any Return disables the analysis outright (sound by
    # construction; a divergent return once miscompiled a scalarized load
    # here)
    if _contains_return(body):
        return set()
    private = _private_array_names(body)
    uset: set[str] = (set(value_params) | set(_assigned_vars(body))) - private
    # declared-but-unassigned names also start uniform (zero-init)

    changed = True
    while changed:
        changed = False

        def poison(name: str) -> None:
            nonlocal changed
            if name in uset:
                uset.discard(name)
                changed = True

        def walk(stmts, divergent: bool) -> None:
            for s in stmts:
                if isinstance(s, Decl):
                    for name, init in s.names:
                        if name in s.arrays:
                            poison(name)  # per-lane stores make stacks diverge
                        elif init is not None and not _expr_uniform(init, uset, private):
                            poison(name)
                        elif divergent and init is not None:
                            poison(name)
                elif isinstance(s, Assign) and isinstance(s.target, Var):
                    if divergent or not _expr_uniform(s.value, uset, private):
                        poison(s.target.name)
                elif isinstance(s, CrementStmt) and isinstance(s.target, Var):
                    if divergent:
                        poison(s.target.name)
                elif isinstance(s, If):
                    d = divergent or not _expr_uniform(s.cond, uset, private)
                    walk(s.then, d)
                    walk(s.other, d)
                elif isinstance(s, For):
                    d = divergent
                    if s.init is not None:
                        walk([s.init], d)
                    cond_u = s.cond is None or _expr_uniform(s.cond, uset, private)
                    d = d or not cond_u
                    inner = s.body + ([s.step] if s.step is not None else [])
                    # a break/continue under a divergent condition makes
                    # per-lane trip counts differ: every assignment in the
                    # loop diverges
                    d = d or _has_divergent_exit(s.body, d, uset, private)
                    walk(inner, d)
                elif isinstance(s, (While, DoWhile)):
                    d = divergent or not _expr_uniform(s.cond, uset, private)
                    d = d or _has_divergent_exit(s.body, d, uset, private)
                    walk(s.body, d)

        walk(body, False)
    return uset


def _vars_read(node, out: set[str] | None = None) -> set[str]:
    """Every variable NAME referenced anywhere under ``node`` (statements,
    expressions, conditions, indices).  Conservative liveness input for
    free-run elimination: a name in here might be read."""
    if out is None:
        out = set()
    if isinstance(node, Var):
        out.add(node.name)
        return out
    if isinstance(node, Index):
        # base is a plain string (buffer or private array) — count it
        out.add(node.base)
        _vars_read(node.index, out)
        return out
    if isinstance(node, _Lit):
        return out
    if isinstance(node, (list, tuple)):
        for x in node:
            _vars_read(x, out)
        return out
    if hasattr(node, "__dict__"):
        for v in vars(node).values():
            if isinstance(v, (list, tuple)) or hasattr(v, "__dict__"):
                _vars_read(v, out)
    return out


def _assigned_vars(stmts: list) -> set[str]:
    out: set[str] = set()

    def walk(s):
        if isinstance(s, Decl):
            out.update(n for n, _ in s.names)
        elif isinstance(s, Assign) and isinstance(s.target, Var):
            out.add(s.target.name)
        elif isinstance(s, Assign) and isinstance(s.target, Index):
            # element store: carries the whole private array through loops
            # (buffer bases are filtered out by the env intersection)
            out.add(s.target.base)
        elif isinstance(s, CrementStmt) and isinstance(s.target, Var):
            out.add(s.target.name)
        elif isinstance(s, CrementStmt) and isinstance(s.target, Index):
            out.add(s.target.base)
        elif isinstance(s, If):
            for x in s.then:
                walk(x)
            for x in s.other:
                walk(x)
        elif isinstance(s, For):
            if s.init is not None:
                walk(s.init)
            if s.step is not None:
                walk(s.step)
            for x in s.body:
                walk(x)
        elif isinstance(s, (While, DoWhile)):
            for x in s.body:
                walk(x)

    for s in stmts:
        walk(s)
    return out


def _stored_bufs(stmts: list) -> set[str]:
    out: set[str] = set()

    def walk(s):
        if isinstance(s, (Assign, CrementStmt)) and isinstance(getattr(s, "target", None), Index):
            out.add(s.target.base)
        if isinstance(s, If):
            for x in s.then + s.other:
                walk(x)
        elif isinstance(s, For):
            if s.init is not None:
                walk(s.init)
            if s.step is not None:
                walk(s.step)
            for x in s.body:
                walk(x)
        elif isinstance(s, (While, DoWhile)):
            for x in s.body:
                walk(x)

    for s in stmts:
        walk(s)
    return out


# ---------------------------------------------------------------------------
# kernel function construction
# ---------------------------------------------------------------------------


@dataclass
class KernelBuildInfo:
    """Static description of one compiled kernel function."""

    name: str
    array_params: list[str]
    value_params: list[str]
    array_ctypes: dict[str, str]
    stored_params: list[str]  # params the kernel writes (discovered at trace)


def build_kernel_fn(
    kernel: KernelDef,
    chunk: int,
    local_size: int,
    global_size: int,
) -> tuple[Callable, KernelBuildInfo]:
    """Build the vectorized launch function for one kernel.

    Returns ``(fn, info)`` where ``fn(offset, arrays_tuple, values_tuple)``
    processes work items ``[offset, offset+chunk)`` and returns the tuple of
    updated arrays (all array params, in declaration order).  ``offset`` is a
    runtime scalar — re-balancing never recompiles.  ``chunk`` is static.
    """
    array_params = [p for p in kernel.params if p.is_pointer]
    value_params = [p for p in kernel.params if not p.is_pointer]
    info = KernelBuildInfo(
        name=kernel.name,
        array_params=[p.name for p in array_params],
        value_params=[p.name for p in value_params],
        array_ctypes={p.name: p.ctype for p in array_params},
        stored_params=[],
    )

    uniform = _uniform_vars(kernel.body, {p.name for p in value_params})

    def fn(offset, arrays: tuple, values: tuple = ()):
        ctx = _Ctx(chunk, jnp.asarray(offset, jnp.int32), global_size, local_size, {})
        ctx.uniform_vars = uniform
        ctx.helpers = getattr(kernel, "helpers", {}) or {}
        for p, arr in zip(array_params, arrays):
            ctx.bufs[p.name] = arr
            ctx.buf_ctypes[p.name] = p.ctype
        for p, v in zip(value_params, values):
            ctx.env[p.name] = KVal(jnp.asarray(v, ctype_to_dtype(p.ctype)), p.ctype)
        _exec_block(ctx, kernel.body)
        info.stored_params = [n for n in info.array_params if n in ctx.stored]
        return tuple(ctx.bufs[p.name] for p in array_params)

    return fn, info
