from .lang import extract_kernel_names, parse_kernels
from .registry import KernelProgram, PythonKernel, kernel

__all__ = [
    "KernelProgram",
    "PythonKernel",
    "extract_kernel_names",
    "kernel",
    "parse_kernels",
]
