"""Task pool + device pool batch scheduler.

TPU-native analogue of the reference's ``Pool.*`` namespace
(ClPipeline.cs:3241-5080): freeze compute calls into :class:`ClTask`
objects, queue them in :class:`ClTaskPool`, and let a
:class:`ClDevicePool` drain pools greedily — each chip runs its own
consumer thread with a private per-chip scheduler, taking the next task
the moment it goes idle (the reference's DEVICE_COMPUTE_AT_WILL,
ClPipeline.cs:3792-3807).

Control tasks mirror the reference's private message protocol
(ClPipeline.cs:3247-3321):

- ``DEVICE_SELECT_BEGIN(i)`` / ``DEVICE_SELECT_END`` — pin the tasks in
  between to chip ``i``.
- ``GLOBAL_SYNCHRONIZATION`` — barrier: everything dispatched before it
  completes before anything after it starts.
- ``BROADCAST`` — run the task once on EVERY chip (replicated init).
- ``SERIAL_MODE_BEGIN`` / ``SERIAL_MODE_END`` — strict submission-order
  execution (a barrier after every task in the span).

Chips can be hot-added mid-run (reference: addDevice spawns a new
DevicePoolThread live, ClPipeline.cs:4333-4390).
"""

from __future__ import annotations

import enum
import itertools
import queue
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Sequence

from ..arrays.clarray import ClArray, ParameterGroup
from ..core.cruncher import NumberCruncher
from ..errors import CekirdeklerError
from ..hardware import Device, Devices
from ..metrics.registry import REGISTRY
from ..trace.spans import TRACER

__all__ = ["ClTaskType", "ClTask", "ClTaskPool", "ClDevicePool", "PoolType"]

_task_ids = itertools.count(1)


class ClTaskType(enum.Enum):
    COMPUTE = "compute"
    DEVICE_SELECT_BEGIN = "device_select_begin"
    DEVICE_SELECT_END = "device_select_end"
    GLOBAL_SYNCHRONIZATION = "global_synchronization"
    BROADCAST = "broadcast"
    SERIAL_MODE_BEGIN = "serial_mode_begin"
    SERIAL_MODE_END = "serial_mode_end"


class PoolType(enum.Enum):
    DEVICE_COMPUTE_AT_WILL = "at_will"   # greedy (reference default)
    # DEVICE_ROUND_ROBIN exists in the reference but is unimplemented there
    # (ClPipeline.cs:3792-3807); we reserve the name for parity
    DEVICE_ROUND_ROBIN = "round_robin"


@dataclass
class ClTask:
    """A frozen compute call (reference: ClTask, ClPipeline.cs:3331-3520).

    Built via ``array.task(...)`` / ``group.task(...)`` (ClArray.cs:1552)
    or directly.  ``callback`` fires after completion with the task.
    """

    params: Sequence[ClArray] = ()
    kernel_names: Sequence[str] = ()
    compute_id: int = 0
    global_range: int = 0
    local_range: int = 256
    global_offset: int = 0
    values: Sequence | dict = ()
    task_type: ClTaskType = ClTaskType.COMPUTE
    select_device: int | None = None       # DEVICE_SELECT_BEGIN argument
    callback: Callable[["ClTask"], None] | None = None
    # tenant tag: the serving tier's per-tenant label (serve/), carried
    # so pool tasks attribute to the same tenant series (None = the
    # untagged pre-serving behavior, metrics unchanged)
    tenant: str | None = None
    task_id: int = field(default_factory=lambda: next(_task_ids))

    def compute(self, cruncher: NumberCruncher) -> None:
        """Run the frozen call on the given cruncher (reference:
        ClTask.compute, ClPipeline.cs:3386)."""
        group = ParameterGroup(list(self.params))
        group.compute(
            cruncher,
            self.compute_id,
            list(self.kernel_names),
            self.global_range,
            self.local_range,
            global_offset=self.global_offset,
            values=self.values,
        )

    @staticmethod
    def device_select_begin(device_index: int) -> "ClTask":
        return ClTask(task_type=ClTaskType.DEVICE_SELECT_BEGIN, select_device=device_index)

    @staticmethod
    def device_select_end() -> "ClTask":
        return ClTask(task_type=ClTaskType.DEVICE_SELECT_END)

    @staticmethod
    def global_synchronization() -> "ClTask":
        return ClTask(task_type=ClTaskType.GLOBAL_SYNCHRONIZATION)

    @staticmethod
    def serial_mode_begin() -> "ClTask":
        return ClTask(task_type=ClTaskType.SERIAL_MODE_BEGIN)

    @staticmethod
    def serial_mode_end() -> "ClTask":
        return ClTask(task_type=ClTaskType.SERIAL_MODE_END)

    def as_broadcast(self) -> "ClTask":
        """Mark this task to run once on every chip (reference BROADCAST)."""
        self.task_type = ClTaskType.BROADCAST
        return self


class ClTaskPool:
    """Thread-safe ordered task list (reference: ClTaskPool,
    ClPipeline.cs:3650-3790)."""

    def __init__(self, tasks: Sequence[ClTask] = ()):  # noqa: D107
        self._tasks: list[ClTask] = list(tasks)
        self._lock = threading.Lock()

    def add(self, task: ClTask) -> "ClTaskPool":
        with self._lock:
            self._tasks.append(task)
        return self

    def feed(self, other: "ClTaskPool", tenant: str | None = None) -> None:
        """Append copies of another pool's tasks (reference: feed,
        ClPipeline.cs:3660-3670).

        ``tenant`` tags the fed tasks with the serving tier's per-tenant
        label (``ClTask.tenant``) so pool work attributes to the same
        ``tenant=...`` metric series the front-end uses; tasks already
        carrying their own tag keep it, and untagged feeds (the default)
        change nothing.

        ``other.snapshot()`` is taken BEFORE acquiring our lock: holding
        it across the call nests two ClTaskPool locks, so concurrent
        ``a.feed(b)`` / ``b.feed(a)`` acquire them in opposite orders —
        the ABBA deadlock ckcheck's lock-order pass flags (and
        ``a.feed(a)`` would self-deadlock on the non-reentrant lock)."""
        tasks = other.snapshot()
        if tenant is not None:
            tasks = [
                t if t.tenant is not None else replace(t, tenant=str(tenant))
                for t in tasks
            ]
        with self._lock:
            self._tasks.extend(tasks)

    def snapshot(self) -> list[ClTask]:
        with self._lock:
            return list(self._tasks)

    def __len__(self) -> int:
        with self._lock:
            return len(self._tasks)


class _Consumer(threading.Thread):
    """Per-chip consumer (reference: DevicePoolThread,
    ClPipeline.cs:4740-5080): private cruncher, greedy pulls from the shared
    pipe plus a pinned queue for device-selected/broadcast tasks.

    With ``fine_grained_queue_control`` on, the consumer throttles on real
    in-flight depth — it claims no new task while
    ``count_markers_remaining() >= queue_limit`` (reference:
    ``markersRemaining() < queueLimit`` gating, ClPipeline.cs:4899-4909).
    Markers retire on actual device completion (utils/markers.py), so this
    bounds device work in flight, not host dispatch."""

    def __init__(self, pool: "ClDevicePool", device: Device, index: int):
        super().__init__(daemon=True, name=f"devpool-{index}")
        self.pool = pool
        self.device = device
        self.index = index
        self.pinned: "queue.Queue[ClTask | None]" = queue.Queue()
        self.cruncher = NumberCruncher(Devices([device]), pool.kernel_source)
        if pool.fine_grained_queue_control:
            self.cruncher.fine_grained_queue_control = True
        self.tasks_done = 0
        self.max_inflight_seen = 0
        self._halt = False

    def _throttle(self) -> None:
        if not self.pool.fine_grained_queue_control:
            return
        while not self._halt:
            depth = self.cruncher.count_markers_remaining()
            self.max_inflight_seen = max(self.max_inflight_seen, depth)
            if depth < self.pool.queue_limit:
                return
            time.sleep(0.0005)

    def run(self) -> None:  # pragma: no cover - exercised via pool tests
        while not self._halt:
            # claim up to the ADAPTIVE queue depth per wake (the reference's
            # pool-progress heuristic shrinks per-device claims as the pool
            # drains so the tail stays balanced, ClPipeline.cs:4188-4230)
            self._throttle()
            batch: list[ClTask] = []
            try:
                batch.append(self.pinned.get_nowait())
            except queue.Empty:
                try:
                    batch.append(self.pool._pipe.get(timeout=0.05))
                except queue.Empty:
                    continue
            while len(batch) < self.pool._adaptive_depth():
                try:
                    batch.append(self.pool._pipe.get_nowait())
                except queue.Empty:
                    break
            for task in batch:
                try:
                    self._throttle()
                    _tt = TRACER.t0()
                    task.compute(self.cruncher)
                    TRACER.record(
                        "pool-task", _tt, cid=task.compute_id,
                        lane=self.index,
                        tag=(f"task{task.task_id}" if task.tenant is None
                             else f"task{task.task_id}@{task.tenant}"),
                    )
                    # tenant-tagged tasks attribute to the serving
                    # tier's per-tenant series; untagged tasks keep the
                    # exact pre-serving series (no label-set change)
                    if task.tenant is not None:
                        REGISTRY.counter(
                            "ck_pool_tasks_total",
                            "device-pool tasks completed",
                            lane=self.index, tenant=task.tenant,
                        ).inc()
                    else:
                        REGISTRY.counter(
                            "ck_pool_tasks_total",
                            "device-pool tasks completed",
                            lane=self.index,
                        ).inc()
                    self.tasks_done += 1
                    if task.callback is not None:
                        task.callback(task)
                except Exception as e:  # surface through the pool
                    # under the inflight condition's lock: finish()'s
                    # error swap must never interleave with an append
                    # (ckcheck lockset finding — the list rode bare
                    # GIL-atomicity before)
                    with self.pool._inflight_lock:
                        self.pool._errors.append(e)
                    # one bad task must not poison this chip's private
                    # cruncher for the remaining tasks (the per-compute
                    # error gate is for user-owned crunchers)
                    self.cruncher.reset_errors()
                finally:
                    self.pool._done_one()

    def stop(self) -> None:
        self._halt = True


class ClDevicePool:
    """Greedy batch scheduler over chips (reference: ClDevicePool,
    ClPipeline.cs:3933-4737).

    One consumer thread + private single-chip :class:`NumberCruncher` per
    device; a producer thread walks enqueued task pools, interprets control
    tasks, and pushes compute tasks to the shared pipe.
    """

    def __init__(
        self,
        devices: Devices,
        kernel_source,
        pool_type: PoolType = PoolType.DEVICE_COMPUTE_AT_WILL,
        max_queues_per_device: int = 4,
        fine_grained_queue_control: bool = False,
        queue_limit: int = 8,
        backpressure: int = 0,
    ):
        """``fine_grained_queue_control`` enables marker-based in-flight
        throttling per chip with ``queue_limit`` as the depth bound
        (reference: ClPipeline.cs:4899-4909).  ``backpressure`` bounds the
        shared pipe (producer blocks when full; 0 = auto: 8 slots per
        device) so a task storm cannot enqueue unboundedly."""
        if pool_type is not PoolType.DEVICE_COMPUTE_AT_WILL:
            raise CekirdeklerError(
                "only DEVICE_COMPUTE_AT_WILL is implemented (the reference's "
                "ROUND_ROBIN is unimplemented there too, ClPipeline.cs:3792-3807)"
            )
        self.kernel_source = kernel_source
        self.max_queues_per_device = max_queues_per_device
        self.fine_grained_queue_control = fine_grained_queue_control
        self.queue_limit = max(1, queue_limit)
        cap = backpressure if backpressure > 0 else 8 * max(1, len(devices))
        self._pipe: "queue.Queue[ClTask]" = queue.Queue(maxsize=cap)
        self._pools: "queue.Queue[ClTaskPool]" = queue.Queue()
        self._errors: list[Exception] = []
        self._inflight = 0
        self._inflight_lock = threading.Condition()
        # append-only under _consumers_lock; len()/iteration reads are
        # GIL-atomic snapshots that may miss a hot-added chip for one
        # wake — the adaptive-depth heuristic tolerates that by design
        # ckcheck: ok append-only list; snapshot reads tolerate staleness
        self._consumers: list[_Consumer] = []
        self._consumers_lock = threading.Lock()
        for d in devices:
            self._add_consumer(d)
        self._producer = threading.Thread(target=self._produce, daemon=True, name="devpool-producer")
        self._running = True
        self._producer.start()

    def _adaptive_depth(self) -> int:
        """Per-wake claim depth from pool progress: claim deep while much
        work remains, shrink to 1 near the tail so the last tasks spread
        across chips (reference heuristic, ClPipeline.cs:4188-4230)."""
        with self._inflight_lock:
            remaining = self._inflight
        n = max(1, len(self._consumers))
        return max(1, min(self.max_queues_per_device, remaining // (2 * n)))

    # -- device management ---------------------------------------------------
    def _add_consumer(self, device: Device) -> None:
        c = _Consumer(self, device, len(self._consumers))
        self._consumers.append(c)
        c.start()

    def add_device(self, device: Device) -> None:
        """Hot-add a chip mid-run (reference: ClPipeline.cs:4333-4390)."""
        with self._consumers_lock:
            self._add_consumer(device)

    @property
    def num_devices(self) -> int:
        return len(self._consumers)

    def tasks_done_per_device(self) -> list[int]:
        return [c.tasks_done for c in self._consumers]

    def max_inflight_depth(self) -> int:
        """Largest marker-observed in-flight depth any chip reached — with
        fine-grained control on, bounded by ``queue_limit`` + one task's
        dispatch burst."""
        return max((c.max_inflight_seen for c in self._consumers), default=0)

    # -- accounting ----------------------------------------------------------
    def _dispatch_one(self) -> None:
        with self._inflight_lock:
            self._inflight += 1

    def _done_one(self) -> None:
        with self._inflight_lock:
            self._inflight -= 1
            self._inflight_lock.notify_all()

    def _drain(self) -> None:
        with self._inflight_lock:
            while self._inflight > 0:
                self._inflight_lock.wait(timeout=0.5)

    # -- producer ------------------------------------------------------------
    def _produce(self) -> None:  # pragma: no cover - exercised via tests
        while self._running:
            try:
                pool = self._pools.get(timeout=0.05)
            except queue.Empty:
                continue
            selected: int | None = None
            serial = False
            for task in pool.snapshot():
                tt = task.task_type
                if tt is ClTaskType.DEVICE_SELECT_BEGIN:
                    selected = task.select_device
                    continue
                if tt is ClTaskType.DEVICE_SELECT_END:
                    selected = None
                    continue
                if tt is ClTaskType.GLOBAL_SYNCHRONIZATION:
                    self._drain()
                    continue
                if tt is ClTaskType.SERIAL_MODE_BEGIN:
                    serial = True
                    continue
                if tt is ClTaskType.SERIAL_MODE_END:
                    serial = False
                    continue
                if tt is ClTaskType.BROADCAST:
                    with self._consumers_lock:
                        targets = list(self._consumers)
                    for c in targets:
                        self._dispatch_one()
                        c.pinned.put(task)
                    self._drain()
                    continue
                # plain compute
                self._dispatch_one()
                if selected is not None:
                    with self._consumers_lock:
                        if not (0 <= selected < len(self._consumers)):
                            self._done_one()
                            with self._inflight_lock:  # the errors lock
                                self._errors.append(
                                    CekirdeklerError(
                                        f"device_select index {selected} "
                                        "out of range")
                                )
                            continue
                        self._consumers[selected].pinned.put(task)
                else:
                    self._pipe.put(task)
                if serial:
                    self._drain()
            self._pools.task_done()

    # -- public API ----------------------------------------------------------
    def enqueue_task_pool(self, pool: ClTaskPool) -> None:
        """Queue a pool for execution (reference: enqueueTaskPool,
        ClPipeline.cs:4400-4409)."""
        self._pools.put(pool)

    def finish(self) -> None:
        """Block until all enqueued pools are fully executed (reference:
        finish, ClPipeline.cs:4433+)."""
        # ckcheck: ok queue.Queue.join has no timeout form; consumer
        # threads are daemons dispose() stops, and task_done fires in
        # their finally — finish() blocking until then is the contract
        self._pools.join()
        self._drain()
        with self._inflight_lock:
            errs, self._errors = self._errors, []
        if errs:
            raise errs[0]

    def dispose(self) -> None:
        self._running = False
        for c in self._consumers:
            c.stop()
        for c in self._consumers:
            c.join(timeout=2.0)
        for c in self._consumers:
            c.cruncher.dispose()

    def __enter__(self) -> "ClDevicePool":
        return self

    def __exit__(self, *exc) -> None:
        self.dispose()
