from .device_pipeline import ArrayRole, ClPipeline, DevicePipeline, PipelineStage
from .pool import ClDevicePool, ClTask, ClTaskPool, ClTaskType, PoolType

__all__ = [
    "ArrayRole",
    "ClDevicePool",
    "ClPipeline",
    "ClTask",
    "ClTaskPool",
    "ClTaskType",
    "DevicePipeline",
    "PipelineStage",
    "PoolType",
]
