"""Device→device pipeline and single-chip multi-stage pipeline.

TPU-native analogue of the reference's ``ClPipeline``/``ClPipelineStage``
(ClPipeline.cs:29-2356) and ``SingleGPUPipeline.DevicePipeline``
(ClPipeline.cs:2357-3240): a linear graph of stages, each bound to a chip,
all running concurrently on successive data generations; results flow
stage→stage each ``push``.

Where the reference forwards results through HOST arrays with double
buffering (forwardResults deep-copies output→duplicate input,
ClPipeline.cs:624-1580; switchBuffers swaps the sets, :87-111), the TPU
build forwards device→device — ``jax.device_put`` moves the output value
to the next stage's chip over ICI, never touching the host.  And because
XLA arrays are immutable values, the double-buffer sets collapse to plain
value handoff: a stage's new output cannot clobber the value the next
stage still holds.

Latency: data pushed at push t is computed by stage 0 at t, reaches stage
k at push t+k; with S stages, ``push`` returns True (results valid) from
push S onward (the reference's 2·stages-2 counter covers its double-init,
ClPipeline.cs:114-122).
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Sequence

import jax
import numpy as np

from ..arrays.clarray import ClArray, wrap
from ..errors import CekirdeklerError, ComputeValidationError
from ..hardware import Device
from ..kernel.registry import KernelProgram

__all__ = ["PipelineStage", "ClPipeline", "DevicePipeline"]


@dataclass
class _Slot:
    """A logical array bound to a stage (reference: ClPipelineStageBuffer)."""

    arr: ClArray
    role: str                      # "input" | "hidden" | "output"
    value: Any = None              # device value (jax.Array) for this stage


class PipelineStage:
    """One pipeline stage: kernels + device + input/hidden/output buffers
    (reference: ClPipelineStage, ClPipeline.cs:140-1703).

    Kernel argument order is inputs, then hiddens, then outputs.
    """

    def __init__(
        self,
        kernel_source,
        kernels: str | Sequence[str],
        global_range: int,
        local_range: int = 256,
        values: Sequence | dict = (),
        init_kernels: str | Sequence[str] = (),
    ):
        self.program = KernelProgram(kernel_source)
        self.kernels = kernels.split() if isinstance(kernels, str) else list(kernels)
        self.init_kernels = (
            init_kernels.split() if isinstance(init_kernels, str) else list(init_kernels)
        )
        self.global_range = global_range
        self.local_range = local_range
        self.values = values
        self.inputs: list[_Slot] = []
        self.hiddens: list[_Slot] = []
        self.outputs: list[_Slot] = []
        self.device: Device | None = None
        self.prev: "PipelineStage | None" = None
        self.next: "PipelineStage | None" = None
        self.elapsed_ms = 0.0

    # -- buffer binding (reference: addInput/Hidden/OutputBuffers) -----------
    def add_input(self, *arrays, **flags) -> "PipelineStage":
        self.inputs.extend(_Slot(wrap(a, **flags), "input") for a in arrays)
        return self

    def add_hidden(self, *arrays, **flags) -> "PipelineStage":
        self.hiddens.extend(_Slot(wrap(a, **flags), "hidden") for a in arrays)
        return self

    def add_output(self, *arrays, **flags) -> "PipelineStage":
        self.outputs.extend(_Slot(wrap(a, **flags), "output") for a in arrays)
        return self

    # -- graph building (reference: prependToStage/appendToStage) ------------
    def append_to(self, prev: "PipelineStage") -> "PipelineStage":
        prev.next, self.prev = self, prev
        return self

    def prepend_to(self, nxt: "PipelineStage") -> "PipelineStage":
        nxt.prev, self.next = self, nxt
        return self

    # -- execution -----------------------------------------------------------
    def _slots(self) -> list[_Slot]:
        return self.inputs + self.hiddens + self.outputs

    def _bind(self, jdev) -> None:
        import jax.numpy as jnp

        for s in self._slots():
            if s.value is None:
                s.value = jax.device_put(s.arr.host(), jdev)

    def _run(self, kernel_names: list[str]) -> None:
        """Launch the kernel sequence on the stage's device values."""
        import time

        t0 = time.perf_counter()
        slots = self._slots()
        bufs = tuple(s.value for s in slots)
        offset = 0
        for name in kernel_names:
            va = (
                self.values.get(name, ())
                if isinstance(self.values, dict)
                else tuple(self.values)
            )
            fn, _ = self.program.launcher(
                name, self.global_range, self.local_range, self.global_range
            )
            n_arr = self.program.array_param_count(name)
            out = fn(offset, bufs[:n_arr], tuple(va))
            bufs = tuple(out) + bufs[n_arr:]
        for s, b in zip(slots, bufs):
            s.value = b
        self.elapsed_ms = (time.perf_counter() - t0) * 1000.0


class ClPipeline:
    """Linear device→device pipeline (reference: ClPipeline,
    ClPipeline.cs:29-139).

    Build via ``ClPipeline.make(stages, devices)`` — one device per stage
    (reference stages may span multiple devices via their own cruncher; here
    a stage is one chip, the framework's Cores covers intra-stage
    multi-chip).
    """

    def __init__(self, stages: list[PipelineStage]):
        self.stages = stages
        self.push_count = 0
        self._pool = ThreadPoolExecutor(max_workers=max(2, len(stages)))

    @classmethod
    def make(cls, stages: Sequence[PipelineStage], devices: Sequence[Device]) -> "ClPipeline":
        """Wire a linear pipeline onto devices and run initializer kernels
        (reference: makePipeline + initializer double-run,
        ClPipeline.cs:1582-1699)."""
        stages = list(stages)
        if not stages:
            raise CekirdeklerError("pipeline needs at least one stage")
        devices = list(devices)
        if len(devices) == 1:
            # single-chip pipeline: every stage on the one device
            devices = devices * len(stages)
        if len(devices) < len(stages):
            raise CekirdeklerError(
                f"{len(stages)} stages need {len(stages)} devices (or exactly 1 "
                f"for a single-chip pipeline); got {len(devices)}"
            )
        for i, (st, d) in enumerate(zip(stages, devices)):
            st.device = d
            if i > 0:
                st.prev, stages[i - 1].next = stages[i - 1], st
            st._bind(d.jax_device)
            for s in st._slots():
                if s.arr.size < st.global_range:
                    raise ComputeValidationError(
                        f"stage {i} array '{s.arr.name}' smaller than global range"
                    )
        for st in stages:
            if st.init_kernels:
                st._run(st.init_kernels)
        return cls(stages)

    def push(
        self,
        data: Sequence | None = None,
        results: Sequence | None = None,
    ) -> bool:
        """Advance the pipeline one generation (reference: pushData,
        ClPipeline.cs:49-122).

        ``data``: host arrays for stage 0's inputs (optional).
        ``results``: host arrays that receive the LAST stage's outputs
        (optional).  Returns True once results are valid (push_count ≥
        number of stages).
        """
        first, last = self.stages[0], self.stages[-1]
        if data is not None:
            datas = list(data) if isinstance(data, (list, tuple)) else [data]
            if len(datas) != len(first.inputs):
                raise ComputeValidationError(
                    f"push data count {len(datas)} != stage-0 inputs {len(first.inputs)}"
                )
            for slot, d in zip(first.inputs, datas):
                host = d.host() if isinstance(d, ClArray) else np.asarray(d)
                slot.value = jax.device_put(host, first.device.jax_device)

        # all stages compute concurrently on their current values
        futures = [self._pool.submit(st._run, st.kernels) for st in self.stages]
        for f in futures:
            f.result()

        # read back last stage's outputs (device→host)
        if results is not None:
            outs = list(results) if isinstance(results, (list, tuple)) else [results]
            if len(outs) != len(last.outputs):
                raise ComputeValidationError(
                    f"results count {len(outs)} != last-stage outputs {len(last.outputs)}"
                )
            for slot, r in zip(last.outputs, outs):
                target = r.host() if isinstance(r, ClArray) else r
                np.copyto(target, np.asarray(slot.value), casting="unsafe")

        # forward outputs device→device into the next stage's inputs
        # (ICI transfer; replaces the reference's host-hop forwardResults)
        for st in self.stages[:-1]:
            nxt = st.next
            n = min(len(st.outputs), len(nxt.inputs))
            for o_slot, i_slot in zip(st.outputs[:n], nxt.inputs[:n]):
                i_slot.value = jax.device_put(o_slot.value, nxt.device.jax_device)

        self.push_count += 1
        return self.push_count >= len(self.stages)

    def performance_report(self) -> str:
        lines = ["pipeline stages:"]
        for i, st in enumerate(self.stages):
            lines.append(
                f"  stage {i} [{st.device.name if st.device else '?'}]: "
                f"{st.elapsed_ms:8.3f} ms  kernels={' '.join(st.kernels)}"
            )
        return "\n".join(lines)

    def dispose(self) -> None:
        self._pool.shutdown(wait=False)
        for st in self.stages:
            for s in st._slots():
                s.value = None


class DevicePipeline(ClPipeline):
    """Single-chip N-stage pipeline (reference: SingleGPUPipeline.
    DevicePipeline, ClPipeline.cs:2357-3240) — same generation semantics,
    every stage on ONE chip; concurrency comes from XLA async dispatch
    (replacing the reference's enqueue-mode queue rotation)."""

    @classmethod
    def make(cls, stages: Sequence[PipelineStage], device: Device) -> "DevicePipeline":
        return super().make(stages, [device])

    def feed(self, data=None, results=None) -> bool:
        """Reference naming (feed ≙ push, ClPipeline.cs:2577-2593)."""
        return self.push(data, results)
