"""Device→device pipeline and single-chip multi-stage pipeline.

TPU-native analogue of the reference's ``ClPipeline``/``ClPipelineStage``
(ClPipeline.cs:29-2356) and ``SingleGPUPipeline.DevicePipeline``
(ClPipeline.cs:2357-3240): a linear graph of stages, each bound to a chip,
all running concurrently on successive data generations; results flow
stage→stage each ``push``.

Where the reference forwards results through HOST arrays with double
buffering (forwardResults deep-copies output→duplicate input,
ClPipeline.cs:624-1580; switchBuffers swaps the sets, :87-111), the TPU
build forwards device→device — ``jax.device_put`` moves the output value
to the next stage's chip over ICI, never touching the host.  And because
XLA arrays are immutable values, the double-buffer sets collapse to plain
value handoff: a stage's new output cannot clobber the value the next
stage still holds.

Latency: data pushed at push t is computed by stage 0 at t, reaches stage
k at push t+k; with S stages, ``push`` returns True (results valid) from
push S onward (the reference's 2·stages-2 counter covers its double-init,
ClPipeline.cs:114-122).
"""

from __future__ import annotations

import enum
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Sequence

import jax
import numpy as np

from ..arrays.clarray import ClArray, wrap
from ..errors import CekirdeklerError, ComputeValidationError
from ..metrics.registry import REGISTRY
from ..hardware import Device
from ..kernel.registry import KernelProgram
from ..trace.spans import TRACER

__all__ = ["PipelineStage", "ClPipeline", "DevicePipeline", "ArrayRole"]


class ArrayRole(enum.Enum):
    """Single-device pipeline array semantics (reference:
    DevicePipelineArrayType, ClPipeline.cs:3171-3206).

    - ``INPUT``: host-fed each feed (stage 0 of the array's stage).
    - ``OUTPUT``: host-read each feed.
    - ``INTERNAL``: persists on the device across feeds, never leaves.
    - ``TRANSITION``: written by its stage, consumed by the NEXT stage on
      the following generation (the stage→stage link).
    """

    INPUT = "input"
    OUTPUT = "output"
    INTERNAL = "internal"
    TRANSITION = "transition"


@dataclass
class _Slot:
    """A logical array bound to a stage (reference: ClPipelineStageBuffer)."""

    arr: ClArray
    role: str                      # "input" | "hidden" | "output"
    value: Any = None              # device value (jax.Array) for this stage


class PipelineStage:
    """One pipeline stage: kernels + device + input/hidden/output buffers
    (reference: ClPipelineStage, ClPipeline.cs:140-1703).

    Kernel argument order is inputs, then hiddens, then outputs.
    """

    def __init__(
        self,
        kernel_source,
        kernels: str | Sequence[str],
        global_range: int,
        local_range: int = 256,
        values: Sequence | dict = (),
        init_kernels: str | Sequence[str] = (),
        devices=None,
    ):
        self.program = KernelProgram(kernel_source)
        self.kernels = kernels.split() if isinstance(kernels, str) else list(kernels)
        self.init_kernels = (
            init_kernels.split() if isinstance(init_kernels, str) else list(init_kernels)
        )
        self.global_range = global_range
        self.local_range = local_range
        self.values = values
        self.inputs: list[_Slot] = []
        self.hiddens: list[_Slot] = []
        self.outputs: list[_Slot] = []
        self.transitions: list[_Slot] = []
        self.device: Device | None = None
        # multi-chip stage (reference: a stage owns its own cruncher over a
        # ClDevices set, ClPipeline.cs:225-285): when set, this stage runs
        # its kernels through a stage-local Cores — range load-balanced
        # across ITS devices — instead of a single-chip launcher.
        # Normalized so an empty sequence means "unassigned" everywhere
        # (make() counts and allocates on the same condition).
        self.devices = devices if devices is not None and len(devices) > 0 else None
        self._cores = None
        self.prev: "PipelineStage | None" = None
        self.next: "PipelineStage | None" = None
        self.elapsed_ms = 0.0

    # -- buffer binding (reference: addInput/Hidden/OutputBuffers) -----------
    def add_input(self, *arrays, **flags) -> "PipelineStage":
        self.inputs.extend(_Slot(wrap(a, **flags), "input") for a in arrays)
        return self

    def add_hidden(self, *arrays, **flags) -> "PipelineStage":
        self.hiddens.extend(_Slot(wrap(a, **flags), "hidden") for a in arrays)
        return self

    def add_output(self, *arrays, **flags) -> "PipelineStage":
        self.outputs.extend(_Slot(wrap(a, **flags), "output") for a in arrays)
        return self

    def add_transition(self, *arrays, **flags) -> "PipelineStage":
        """Bind TRANSITION arrays: written by this stage, consumed by the
        NEXT stage one generation later (reference:
        DevicePipelineArrayType.TRANSITION, ClPipeline.cs:3171-3206).
        The builder links the matching input slot onto the next stage."""
        self.transitions.extend(_Slot(wrap(a, **flags), "transition") for a in arrays)
        return self

    def add_array(self, arr, role: "ArrayRole", **flags) -> "PipelineStage":
        """Role-based binding (reference API shape)."""
        if role is ArrayRole.INPUT:
            return self.add_input(arr, **flags)
        if role is ArrayRole.OUTPUT:
            return self.add_output(arr, **flags)
        if role is ArrayRole.INTERNAL:
            return self.add_hidden(arr, **flags)
        return self.add_transition(arr, **flags)

    # -- graph building (reference: prependToStage/appendToStage) ------------
    def append_to(self, prev: "PipelineStage") -> "PipelineStage":
        prev.next, self.prev = self, prev
        return self

    def prepend_to(self, nxt: "PipelineStage") -> "PipelineStage":
        nxt.prev, self.next = self, nxt
        return self

    # -- execution -----------------------------------------------------------
    def _slots(self) -> list[_Slot]:
        return self.inputs + self.hiddens + self.outputs + self.transitions

    def _bind(self, jdev) -> None:
        import jax.numpy as jnp

        for s in self._slots():
            if s.value is None:
                s.value = jax.device_put(s.arr.host(), jdev)

    def _run(self, kernel_names: list[str]) -> None:
        """Launch the kernel sequence on the stage's device values."""
        import time

        if self._cores is not None:
            self._run_multi(kernel_names)
            return
        _tt = TRACER.t0()
        t0 = time.perf_counter()
        slots = self._slots()
        # placement ownership: every producer of a single-chip stage's slot
        # values (push/_bind/handoff) device_puts before we get here
        bufs = tuple(s.value for s in slots)
        offset = 0
        for name in kernel_names:
            va = (
                self.values.get(name, ())
                if isinstance(self.values, dict)
                else tuple(self.values)
            )
            fn, _ = self.program.launcher(
                name, self.global_range, self.local_range, self.global_range,
                platform=(
                    self.device.jax_device.platform
                    if self.device is not None
                    else None
                ),
            )
            n_arr = self.program.array_param_count(name)
            out = fn(offset, bufs[:n_arr], tuple(va))
            bufs = tuple(out) + bufs[n_arr:]
        for s, b in zip(slots, bufs):
            s.value = b
        self.elapsed_ms = (time.perf_counter() - t0) * 1000.0
        REGISTRY.counter(
            "ck_pipeline_stages_total", "stage bodies executed",
            engine="single",
        ).inc()
        TRACER.record(
            "pipeline-stage", _tt,
            tag=f"{self.device.name if self.device else '?'}:"
                f"{'+'.join(kernel_names)}",
        )

    def _run_multi(self, kernel_names: list[str]) -> None:
        """Multi-chip stage body: pull incoming device values to host, run
        the kernels through the stage's own Cores (per-chip range split +
        load balancing), publish host arrays as the stage's new values —
        the reference's behavior exactly (each stage.run() is a full
        H2D/compute/D2H on that stage's devices; stage→stage data moves
        through host arrays, ClPipeline.cs:287-603,624-1580)."""
        import time

        _tt = TRACER.t0()
        t0 = time.perf_counter()
        slots = self._slots()
        for s in slots:
            if s.value is not None and not isinstance(s.value, np.ndarray):
                np.copyto(s.arr.host(), np.asarray(s.value), casting="unsafe")
                s.value = None
            elif isinstance(s.value, np.ndarray) and s.value is not s.arr.host():
                np.copyto(s.arr.host(), s.value, casting="unsafe")
                s.value = None
        params = [s.arr for s in slots]
        self._cores.compute(
            kernel_names, params, 1, self.global_range, self.local_range,
            value_args=self.values,
        )
        for s in self.outputs + self.transitions:
            s.value = s.arr.host()
        self.elapsed_ms = (time.perf_counter() - t0) * 1000.0
        REGISTRY.counter(
            "ck_pipeline_stages_total", "stage bodies executed",
            engine="multi",
        ).inc()
        TRACER.record(
            "pipeline-stage", _tt,
            tag=f"multi[{len(self.devices) if self.devices else 0}]:"
                f"{'+'.join(kernel_names)}",
        )


class ClPipeline:
    """Linear device→device pipeline (reference: ClPipeline,
    ClPipeline.cs:29-139).

    Build via ``ClPipeline.make(stages, devices)`` — one device per stage
    (reference stages may span multiple devices via their own cruncher; here
    a stage is one chip, the framework's Cores covers intra-stage
    multi-chip).
    """

    def __init__(self, stages: list[PipelineStage]):
        self.stages = stages
        self.push_count = 0
        self._pool = ThreadPoolExecutor(max_workers=max(2, len(stages)))

    @classmethod
    def make(cls, stages: Sequence[PipelineStage], devices: Sequence[Device]) -> "ClPipeline":
        """Wire a linear pipeline onto devices and run initializer kernels
        (reference: makePipeline + initializer double-run,
        ClPipeline.cs:1582-1699)."""
        stages = list(stages)
        if not stages:
            raise CekirdeklerError("pipeline needs at least one stage")
        devices = list(devices)
        unassigned = [st for st in stages if st.devices is None]
        if len(devices) == 1:
            # single-chip pipeline: every unassigned stage on the one device
            devices = devices * len(unassigned)
        if len(devices) < len(unassigned):
            raise CekirdeklerError(
                f"{len(unassigned)} stages need {len(unassigned)} devices (or "
                f"exactly 1 for a single-chip pipeline); got {len(devices)}"
            )
        dev_iter = iter(devices)
        for i, st in enumerate(stages):
            if i > 0:
                st.prev, stages[i - 1].next = stages[i - 1], st
            if st.devices is not None:
                # multi-chip stage: its own Cores over its device set
                # (reference: per-stage cruncher, ClPipeline.cs:225-285)
                from ..core.cores import Cores

                st._cores = Cores(st.devices, st.program)
                st.device = st.devices[0]
            else:
                st.device = next(dev_iter)
                st._bind(st.device.jax_device)
            for s in st._slots():
                if s.arr.size < st.global_range:
                    raise ComputeValidationError(
                        f"stage {i} array '{s.arr.name}' smaller than global range"
                    )
        # wire TRANSITION links: the producing stage's transition slot feeds
        # the slot in the NEXT stage bound to the same ClArray object
        for i, st in enumerate(stages):
            st._transition_links = []
            for t in st.transitions:
                if st.next is None:
                    raise ComputeValidationError(
                        f"stage {i} declares transition '{t.arr.name}' but has no next stage"
                    )
                target = next(
                    (s for s in st.next._slots() if s.arr is t.arr), None
                )
                if target is None:
                    raise ComputeValidationError(
                        f"transition '{t.arr.name}' of stage {i} is not bound "
                        f"on stage {i + 1} (declare it there as input/internal)"
                    )
                st._transition_links.append((t, target))
        for st in stages:
            if st.init_kernels:
                st._run(st.init_kernels)
        return cls(stages)

    def push(
        self,
        data: Sequence | None = None,
        results: Sequence | None = None,
    ) -> bool:
        """Advance the pipeline one generation (reference: pushData,
        ClPipeline.cs:49-122).

        ``data``: host arrays for stage 0's inputs (optional).
        ``results``: host arrays that receive the LAST stage's outputs
        (optional).  Returns True once results are valid (push_count ≥
        number of stages).
        """
        first, last = self.stages[0], self.stages[-1]
        if data is not None:
            datas = list(data) if isinstance(data, (list, tuple)) else [data]
            if len(datas) != len(first.inputs):
                raise ComputeValidationError(
                    f"push data count {len(datas)} != stage-0 inputs {len(first.inputs)}"
                )
            for slot, d in zip(first.inputs, datas):
                host = d.host() if isinstance(d, ClArray) else np.asarray(d)
                if first._cores is not None:
                    # multi-chip stage consumes host data directly
                    np.copyto(slot.arr.host(), host, casting="unsafe")
                    slot.value = None
                else:
                    slot.value = jax.device_put(host, first.device.jax_device)

        # all stages compute concurrently on their current values
        futures = [self._pool.submit(st._run, st.kernels) for st in self.stages]
        errs = []
        for f in futures:
            try:
                f.result()
            except Exception as e:  # noqa: BLE001 - first error surfaces
                errs.append(e)
        if errs:
            # black box before the raise (obs/flight.py): a crashed
            # pipeline generation dumps the flight/span/metrics state
            # when CK_POSTMORTEM_DIR is armed
            from ..obs.flight import record_crash

            record_crash("pipeline.push", errs[0], lanes={
                "stages": len(self.stages),
                "push_count": self.push_count,
            })
            raise errs[0]

        # read back last stage's outputs (device→host)
        if results is not None:
            outs = list(results) if isinstance(results, (list, tuple)) else [results]
            if len(outs) != len(last.outputs):
                raise ComputeValidationError(
                    f"results count {len(outs)} != last-stage outputs {len(last.outputs)}"
                )
            for slot, r in zip(last.outputs, outs):
                target = r.host() if isinstance(r, ClArray) else r
                np.copyto(target, np.asarray(slot.value), casting="unsafe")

        self._switch()
        self.push_count += 1
        return self.push_count >= len(self.stages)

    def _switch(self) -> None:
        """Advance generation links (the reference's switchBuffers +
        forwardResults, ClPipeline.cs:87-111,624-1580): explicit TRANSITION
        links move first; stages without transitions fall back to by-index
        output→input forwarding.  Same-chip handoff is a free value move;
        cross-chip rides ICI via ``device_put``."""
        def handoff(v, nxt):
            # a multi-chip producer publishes its LIVE arr.host() buffer,
            # which its own next-generation compute overwrites concurrently
            # with the consumer's read — and jax.device_put of a numpy
            # array may read it lazily, racing the same way.  Snapshot
            # host-published values for EVERY consumer kind.
            if isinstance(v, np.ndarray):
                v = np.array(v)
            if nxt._cores is not None:
                # multi-chip consumer takes host data (its compute uploads
                # per-chip range slices from it)
                return v if isinstance(v, np.ndarray) else np.asarray(v)
            return jax.device_put(v, nxt.device.jax_device)

        for st in self.stages[:-1]:
            nxt = st.next
            links = getattr(st, "_transition_links", [])
            if links:
                for src, dst in links:
                    dst.value = handoff(src.value, nxt)
                continue
            n = min(len(st.outputs), len(nxt.inputs))
            for o_slot, i_slot in zip(st.outputs[:n], nxt.inputs[:n]):
                i_slot.value = handoff(o_slot.value, nxt)

    @property
    def streamed_transfers(self) -> bool:
        """Streamed partition transfers inside multi-chip stages: each
        such stage runs its kernels through a stage-local ``Cores``,
        which chunk-streams its per-lane H2D/D2H exactly like the main
        scheduler (core/cores._run_streamed) — stage feeds stop paying
        the monolithic upload-before-first-launch fence.  True iff every
        multi-chip stage has it on (single-chip stages keep values
        device-resident and have no partition transfers to stream)."""
        cores = [st._cores for st in self.stages if st._cores is not None]
        return bool(cores) and all(c.streamed_transfers for c in cores)

    @streamed_transfers.setter
    def streamed_transfers(self, v: bool) -> None:
        for st in self.stages:
            if st._cores is not None:
                st._cores.streamed_transfers = bool(v)

    @property
    def stream_chunks(self) -> int:
        """Pinned chunk count for the stage-local schedulers (0 =
        autotune; the per-stage ``Cores.transfer_tuner`` learns each
        stage's own (lane, kernel, bytes) points independently)."""
        for st in self.stages:
            if st._cores is not None:
                return st._cores.stream_chunks
        return 0

    @stream_chunks.setter
    def stream_chunks(self, v: int) -> None:
        for st in self.stages:
            if st._cores is not None:
                st._cores.stream_chunks = max(0, int(v))

    def performance_report(self) -> str:
        lines = ["pipeline stages:"]
        for i, st in enumerate(self.stages):
            lines.append(
                f"  stage {i} [{st.device.name if st.device else '?'}]: "
                f"{st.elapsed_ms:8.3f} ms  kernels={' '.join(st.kernels)}"
            )
        return "\n".join(lines)

    def dispose(self) -> None:
        self._pool.shutdown(wait=False)
        for st in self.stages:
            if st._cores is not None:
                st._cores.dispose()
                st._cores = None
            for s in st._slots():
                s.value = None


class DevicePipeline(ClPipeline):
    """Single-chip N-stage pipeline (reference: SingleGPUPipeline.
    DevicePipeline, ClPipeline.cs:2357-3240) — same generation semantics,
    every stage on ONE chip; device-side concurrency comes from XLA async
    dispatch (replacing the reference's enqueue-mode queue rotation), and
    HOST-side overlap comes from the ``feed_async_begin``/``feed_async_end``
    pair: the device generation runs on a background thread while the
    caller prepares the next feed's data (reference: feedAsync /
    feedAsyncBegin/End, ClPipeline.cs:2598-2641).

    Array roles (:class:`ArrayRole`) map the reference's
    DevicePipelineArrayType semantics (ClPipeline.cs:3171-3206): INPUT is
    host-fed, OUTPUT host-read, INTERNAL device-resident, TRANSITION
    carries data stage→stage one generation later.
    """

    def __init__(self, stages: list[PipelineStage]):
        super().__init__(stages)
        self._async_future = None

    @classmethod
    def make(cls, stages: Sequence[PipelineStage], device: Device) -> "DevicePipeline":
        return super().make(stages, [device])

    def feed(self, data=None, results=None) -> bool:
        """Synchronous generation (reference: feed, ClPipeline.cs:2577-2593)."""
        return self.push(data, results)

    # -- async host-overlap feeds (reference: ClPipeline.cs:2598-2641) -------
    def _generation(self, snaps) -> None:
        """One device generation: upload snapshots, run every stage, switch
        links.  Runs on a background thread for the async feeds."""
        first = self.stages[0]
        if snaps is not None:
            for slot, host in zip(first.inputs, snaps):
                slot.value = jax.device_put(host, first.device.jax_device)
        for st in self.stages:
            st._run(st.kernels)
        self._switch()

    def feed_async_begin(self, data=None) -> None:
        """Kick off this generation on a background thread and return
        immediately — the host thread is free to prepare the next feed
        (the overlap the reference gets from async enqueue + Parallel.For
        host copies).  Input data is snapshotted NOW, so the caller may
        mutate its arrays right after this returns."""
        if self._async_future is not None:
            raise CekirdeklerError(
                "feed_async_begin called again before feed_async_end"
            )
        snaps = None
        if data is not None:
            datas = list(data) if isinstance(data, (list, tuple)) else [data]
            if len(datas) != len(self.stages[0].inputs):
                raise ComputeValidationError(
                    f"push data count {len(datas)} != stage-0 inputs "
                    f"{len(self.stages[0].inputs)}"
                )
            snaps = [
                np.array(d.host() if isinstance(d, ClArray) else d)
                for d in datas
            ]
        self._async_future = self._pool.submit(self._generation, snaps)

    def feed_async_end(self, results=None) -> bool:
        """Join the in-flight generation and read back the last stage's
        outputs.  Returns True once results are valid."""
        if self._async_future is None:
            raise CekirdeklerError("feed_async_end without feed_async_begin")
        fut, self._async_future = self._async_future, None
        fut.result()
        if results is not None:
            last = self.stages[-1]
            outs = list(results) if isinstance(results, (list, tuple)) else [results]
            if len(outs) != len(last.outputs):
                raise ComputeValidationError(
                    f"results count {len(outs)} != last-stage outputs {len(last.outputs)}"
                )
            for slot, r in zip(last.outputs, outs):
                target = r.host() if isinstance(r, ClArray) else r
                np.copyto(target, np.asarray(slot.value), casting="unsafe")
        self.push_count += 1
        return self.push_count >= len(self.stages)

    def feed_async(self, data=None, results=None) -> bool:
        """begin + end composed (reference: feedAsync)."""
        self.feed_async_begin(data)
        return self.feed_async_end(results)
