"""Balancer demonstration on the 8-device virtual CPU rig (VERDICT r2 #4).

Run as ``python -m cekirdekler_tpu.benchrig`` in a process whose env forces
``JAX_PLATFORMS=cpu`` + ``--xla_force_host_platform_device_count=8`` (bench.py
sets this up).  Prints ONE JSON line with:

- the classic per-call rebalance: mandelbrot over 8 devices, whose
  contiguous row split is NATURALLY skewed (rows crossing the set run the
  full escape loop; rows far from it exit immediately), so the first equal
  split is wrong and the balancer must move shares — the range trajectory
  and convergence iteration are the north-star metric (BASELINE.md);
- the enqueue-mode sync-point rebalance: ranges pinned between barriers,
  moved at them from fence-retire benches (core/cores.py barrier()).
"""

from __future__ import annotations

import json

import numpy as np


def convergence_sim(ndev: int = 8, step: int = 256) -> dict:
    """Deterministic convergence of the REAL ``load_balance`` implementation
    against the actual mandelbrot cost field.

    The live rig below shares ONE host core across 8 virtual devices, so
    its wall-time benches are scheduler-contention noise — fine for showing
    direction of movement, useless for a crisp convergence count.  Here the
    per-chip bench is the exact work in its contiguous slice (host-computed
    escape counts), which is what a chip's wall time measures on real
    isolated hardware.  Same code path as production: equal_split →
    load_balance with history smoothing + continuous carry."""
    from .core.balance import BalanceHistory, BalanceState, equal_split, load_balance
    from .workloads import _converged_at, mandelbrot_host

    w = h = 512
    max_iter = 128
    img = mandelbrot_host(w, h, -2.0, -1.25, 2.5 / w, 2.5 / h, max_iter)
    cost = img.astype(np.float64) + 2.0  # per-pixel work ∝ escape iters
    cum = np.concatenate([[0.0], np.cumsum(cost)])
    n = w * h

    def run(smooth: bool, adaptive: bool = True, cid: int = 0):
        """Same config Cores._ranges_for uses: adaptive BalanceState +
        recency-weighted history by default; adaptive=False is the
        reference-parity fixed-damping mode.  ``cid`` keys the decision
        provenance: the four configs are four INDEPENDENT chains (each
        resets to the equal split), and replay/what-if tooling chains
        records per cid — one shared id would splice them into a
        meaningless merged trajectory."""
        ranges = equal_split(n, ndev, step)
        hist = BalanceHistory(weighted=adaptive) if smooth else None
        state = BalanceState() if adaptive else None
        carry: list[float] | None = None if adaptive else []
        traj = [list(ranges)]
        for _ in range(48):
            offs = np.concatenate([[0], np.cumsum(ranges)]).astype(int)
            bench = [float(cum[offs[i + 1]] - cum[offs[i]]) for i in range(ndev)]
            ranges = load_balance(bench, ranges, n, step, hist,
                                  carry=carry, state=state, cid=cid)
            traj.append(list(ranges))
        return traj

    traj = run(smooth=True, cid=0)
    traj_ns = run(smooth=False, cid=1)
    traj_parity = run(smooth=True, adaptive=False, cid=2)
    traj_parity_ns = run(smooth=False, adaptive=False, cid=3)

    # balanced quality: max per-chip work / mean, at first vs final split
    def imbalance(r):
        offs = np.concatenate([[0], np.cumsum(r)]).astype(int)
        work = [cum[offs[i + 1]] - cum[offs[i]] for i in range(ndev)]
        return float(max(work) / (sum(work) / ndev))

    return {
        "n_devices": ndev,
        "iterations_run": len(traj) - 1,
        # smoothed/unsmoothed run the PRODUCTION config (adaptive damping);
        # the *_reference_parity keys rerun both with the fixed-0.3-damping
        # parity mode so cross-round comparisons against r3 numbers (which
        # predate adaptive damping) have a like-for-like column
        "convergence_iters_smoothed": _converged_at(traj, step),
        "convergence_iters_unsmoothed": _converged_at(traj_ns, step),
        "convergence_iters_smoothed_reference_parity": _converged_at(traj_parity, step),
        "convergence_iters_unsmoothed_reference_parity": _converged_at(traj_parity_ns, step),
        "imbalance_first": round(imbalance(traj[0]), 3),
        "imbalance_final": round(imbalance(traj[-1]), 3),
        "imbalance_final_unsmoothed": round(imbalance(traj_ns[-1]), 3),
        "ranges_first": traj[0],
        "ranges_final": traj[-1],
    }


def compute_path_proof(ndev: int = 8, iters: int = 49) -> dict:
    """Multi-chip scaling proxy for the flagship ``Cores.compute()`` path
    (VERDICT r3 #1): drive the REAL dispatch machinery — uploads, binary-
    ladder launches, async readbacks, per-call rebalance — over the
    ``ndev``-device rig for ``iters`` calls and record the four facts the
    "N devices as ONE device" claim rests on:

    1. converged ranges: the trajectory of the real per-call rebalance,
    2. per-chip work accounting at the final split (max work / mean work),
    3. compile-count invariance: distinct jitted launch geometries must
       stop growing after the ladder is warm, across ~48 distinct splits,
    4. dispatch concurrency: with lane tracing on, every active lane's
       async dispatch returns before the FIRST lane's readback completes —
       N chips genuinely in flight together.

    Bench injection: the rig's 8 virtual devices share ONE host core, so a
    chip's wall time measures scheduler contention, not its work.  On real
    isolated chips wall time ∝ work in the chip's slice; the proof feeds
    exactly that quantity through the same ``Worker.benchmarks`` channel
    the wall-clock bench uses (chips with zero range keep no bench — same
    as live).  Everything else is the production code path, and the final
    image is checked EXACTLY against the host reference."""
    import time as _time

    from .arrays.clarray import ClArray
    from .core.cruncher import NumberCruncher
    from .hardware import platforms
    from .workloads import MANDELBROT_SRC, _converged_at, mandelbrot_host

    w = h = 512
    max_iter = 96
    local = 256
    cid = 7200
    n = w * h
    devs = platforms().cpus().subset(ndev)
    img_ref = mandelbrot_host(w, h, -2.0, -1.25, 2.5 / w, 2.5 / h, max_iter)
    cost = img_ref.astype(np.float64) + 2.0
    cum = np.concatenate([[0.0], np.cumsum(cost)])

    def work_in(lo: int, hi: int) -> float:
        return float(cum[hi] - cum[lo])

    if iters < 2:
        raise ValueError("compute_path_proof needs iters >= 2")
    cr = NumberCruncher(devs, MANDELBROT_SRC)
    cores = cr.cores
    out = ClArray(n, np.float32, name="cp_out", read=False, write=True)
    vals = (-2.0, -1.25, 2.5 / w, 2.5 / h, w, max_iter)
    traj: list[list[int]] = []
    compile_at: dict[str, int] = {}
    # compile counts sampled after the first call, after the ladder is warm
    # (a few rebalances in), and at the end — invariance = warm == final
    warm_call = min(8, iters - 1)
    checkpoints = {1, warm_call, iters}
    t0 = _time.perf_counter()
    try:
        for k in range(iters):
            if k == iters - 1:
                cores.trace_lanes = True
            out.compute(cr, cid, "mandelbrot", n, local, values=vals)
            ranges = cores.ranges_of(cid)
            traj.append(ranges)
            # deterministic bench injection (see docstring)
            offs = np.concatenate([[0], np.cumsum(ranges)]).astype(int)
            for i, wk in enumerate(cores.workers):
                if ranges[i] > 0:
                    wk.benchmarks[cid] = work_in(offs[i], offs[i + 1])
            if k + 1 in checkpoints:
                compile_at[str(k + 1)] = cores.program.compiled_count
        elapsed = _time.perf_counter() - t0
        # scheduler exactness: the 8-chip assembled image must BIT-match a
        # single-chip run of the same lowering (no lost/duplicated/shifted
        # regions across 48 resharding moves).  The host numpy reference is
        # checked with a boundary tolerance only — XLA may contract the
        # orbit arithmetic into FMAs, legitimately moving a handful of
        # escape-boundary pixels by a few iterations.
        multi = np.asarray(out).copy()
        cr1 = NumberCruncher(devs.subset(1), MANDELBROT_SRC)
        out1 = ClArray(n, np.float32, name="cp_out1", read=False, write=True)
        try:
            out1.compute(cr1, cid, "mandelbrot", n, local, values=vals)
            np.testing.assert_array_equal(multi, np.asarray(out1))
        finally:
            cr1.dispose()
        boundary_mismatch = float(
            np.mean(multi != img_ref.astype(np.float32))
        )
        if boundary_mismatch >= 0.001:  # not assert: must survive python -O
            raise RuntimeError(
                f"host-reference mismatch {boundary_mismatch:.4f} exceeds "
                "the FMA escape-boundary tolerance"
            )

        final = traj[-1]
        offs = np.concatenate([[0], np.cumsum(final)]).astype(int)
        works = [work_in(offs[i], offs[i + 1]) for i in range(ndev)]
        mean_w = sum(works) / ndev

        def lane_concurrency() -> tuple[list, int]:
            tr = cores.lane_trace.get(cid, [])
            first_join = min((t for (_, _, t) in tr), default=0.0)
            return tr, sum(1 for (_, d, _) in tr if d <= first_join)

        # the dispatch-concurrency invariant is a TIMING property: on a
        # host with fewer cores than lanes the 8 dispatch threads cannot
        # all be scheduled before the first lane's readback completes —
        # that is the rig, not the scheduler.  Retry the traced call a
        # few times (best attempt counts: ONE witnessed all-in-flight
        # window proves the dispatch is concurrent), and report whether
        # this host can even express the property so callers gate the
        # assertion on capability instead of carrying a flake.
        import os as _os

        try:
            host_cpus = len(_os.sched_getaffinity(0))
        except AttributeError:  # non-Linux
            host_cpus = _os.cpu_count() or 1
        active_lanes = sum(1 for r in final if r > 0)
        lane_rig_capable = host_cpus >= active_lanes
        trace, lanes_in_flight = lane_concurrency()
        attempts = 1
        while (
            attempts < 3
            and not (lanes_in_flight == len(trace) == active_lanes)
        ):
            out.compute(cr, cid, "mandelbrot", n, local, values=vals)
            ranges = cores.ranges_of(cid)
            offs_r = np.concatenate([[0], np.cumsum(ranges)]).astype(int)
            for i, wk in enumerate(cores.workers):
                if ranges[i] > 0:
                    wk.benchmarks[cid] = work_in(offs_r[i], offs_r[i + 1])
            attempts += 1
            tr, lif = lane_concurrency()
            if lif > lanes_in_flight:
                trace, lanes_in_flight = tr, lif
        distinct_splits = len({tuple(r) for r in traj})
        return {
            "ok": True,
            "n_devices": ndev,
            "compute_calls": iters,
            "rebalances": iters - 1,
            "distinct_splits_seen": distinct_splits,
            "convergence_iters": _converged_at(traj, local),
            "ranges_first": traj[0],
            "ranges_final": final,
            "per_chip_workitems_final": final,
            "per_chip_work_final": [round(x, 0) for x in works],
            "work_imbalance_final": round(max(works) / mean_w, 3),
            "work_imbalance_first": round(
                max(
                    work_in(i * (n // ndev), (i + 1) * (n // ndev))
                    for i in range(ndev)
                )
                / (work_in(0, n) / ndev),
                3,
            ),
            "compile_count_after_calls": compile_at,
            "compile_count_invariant": (
                compile_at[str(iters)] == compile_at[str(warm_call)]
            ),
            "lanes_traced": len(trace),
            "lanes_dispatched_before_first_join": lanes_in_flight,
            "lane_trace_attempts": attempts,
            # capability, not verdict: False means this host has fewer
            # schedulable cores than active lanes, so the all-in-flight
            # timing property is unobservable HERE regardless of the
            # scheduler (tests gate the timing assertion on this)
            "lane_rig_capable": lane_rig_capable,
            "host_cpus": host_cpus,
            "all_lanes_in_flight_together": lanes_in_flight == len(trace)
            and len(trace) == sum(1 for r in final if r > 0),
            "image_exact_vs_single_chip": True,
            # the nonzero fraction next to an "exact" claim needs its
            # explanation IN the artifact (VERDICT r5 #5): exactness is
            # multi-chip vs SINGLE-CHIP (bit-identical, asserted above);
            # the residual here is vs the HOST numpy reference, where XLA
            # legitimately contracts the orbit arithmetic into FMAs and a
            # handful of escape-BOUNDARY pixels move by a few iterations
            # (the documented boundary contract, commit 0649b77).  The
            # bound is enforced — ≥ host_boundary_bound raises above.
            "host_boundary_mismatch_frac": boundary_mismatch,
            "host_boundary_bound": 0.001,
            "host_boundary_note": (
                "nonzero is NOT a scheduler defect: the 8-chip image is "
                "bit-exact vs the single-chip run (asserted); this frac "
                "is vs the HOST numpy reference and measures XLA's FMA "
                "contraction moving escape-boundary pixels (mixed-dtype "
                "boundary contract, commit 0649b77), bounded < 0.001"
            ),
            "elapsed_sec": round(elapsed, 1),
        }
    finally:
        cores.trace_lanes = False
        cr.dispose()


def _guard(fn) -> dict:
    """Artifact resilience: a section failure reports as that section's
    error, never an empty artifact."""
    try:
        return fn()
    except Exception as e:  # noqa: BLE001 - resilience boundary
        return {"ok": False, "error": f"{type(e).__name__}: {e}"[:500]}


def main() -> None:
    import jax

    from .utils.jsonsafe import dumps_safe

    if jax.default_backend() != "cpu" or len(jax.devices()) < 8:
        print(dumps_safe({
            "ok": False,
            "error": f"rig not available: backend={jax.default_backend()} "
                     f"n={len(jax.devices())}",
        }))
        return

    from .hardware import platforms
    from .workloads import run_mandelbrot

    devs = platforms().cpus().subset(8)

    # -- classic path: rebalance every call on measured per-chip times -----
    res = run_mandelbrot(
        devs, width=1024, height=1024, max_iter=128,
        iters=16, warmup=0, local_range=256,
    )
    traj = res.ranges_per_iter
    # sparse trajectory for the artifact: first 4 + last
    shown = {str(i): traj[i] for i in (0, 1, 2, 3, len(traj) - 1) if i < len(traj)}
    spread0 = max(traj[0]) - min(traj[0])
    spreadN = max(traj[-1]) - min(traj[-1])

    # -- enqueue mode: ranges move only at barriers -------------------------
    from .arrays.clarray import ClArray
    from .core.cruncher import NumberCruncher
    from .workloads import mandelbrot_pallas_kernel

    cr = NumberCruncher(devs, mandelbrot_pallas_kernel(interpret=True))
    n = 1024 * 1024
    out = ClArray(n, np.float32, name="rig_out", read=False, write=True)
    vals = (-2.0, -1.25, 2.5 / 1024, 2.5 / 1024, 1024, 128)
    cr.enqueue_mode = True
    enq_traj: list[list[int]] = []
    try:
        for k in range(12):
            out.compute(cr, 7101, "mandelbrot", n, 256, values=vals)
            enq_traj.append(cr.ranges_of(7101))
            if (k + 1) % 4 == 0:
                cr.barrier()  # measures per-chip retirement, arms rebalance
        cr.enqueue_mode = False  # flush
    finally:
        if cr.enqueue_mode:
            cr.enqueue_mode = False
        cr.dispose()
    # within a window ranges must hold still; across barriers they may move
    pinned_within = all(
        enq_traj[i] == enq_traj[i - 1]
        for i in range(1, 12)
        if i % 4 != 0
    )
    moved_at_sync = any(
        enq_traj[i] != enq_traj[i - 1] for i in (4, 8)
    )

    print(dumps_safe({
        "ok": True,
        "n_devices": len(devs),
        "live_convergence_iters": res.convergence_iters,
        "live_note": (
            "live rig shares 1 host core across 8 virtual devices — benches "
            "are contention-noisy; see convergence_sim for the deterministic "
            "measurement through the same load_balance code"
        ),
        "range_trajectory": shown,
        "range_spread_first": spread0,
        "range_spread_last": spreadN,
        "mpixels_per_sec_rig": round(res.mpixels_per_sec, 2),
        "convergence_sim": convergence_sim(),
        "compute_path": _guard(compute_path_proof),
        "enqueue_pinned_within_window": pinned_within,
        "enqueue_moved_at_sync": moved_at_sync,
        "enqueue_ranges_first": enq_traj[0],
        "enqueue_ranges_last": enq_traj[-1],
    }))


if __name__ == "__main__":
    main()
