"""Metric exports: Prometheus text format, JSON snapshot, and Perfetto
counter-track events for the Chrome-trace merge.

Three consumers, three formats, one source of truth (the registry):

- ``prometheus_text()`` — the ``text/plain; version=0.0.4`` exposition
  format every Prometheus-compatible scraper parses.  Counters render as
  one line per series, histograms as cumulative ``_bucket{le=...}``
  lines plus ``_sum``/``_count`` (standard ``le`` semantics).
- ``json_snapshot()`` — the deterministic dict `MetricsRegistry.snapshot`
  produces, ready to embed in bench artifacts (bench.py does).
- ``chrome_counter_events()`` — Chrome-trace ``ph: "C"`` counter events
  from sampled series, merged into the span export by
  ``trace.export.to_chrome_trace(..., counters=...)`` so balancer
  shares / queue depths / byte counters ride the SAME Perfetto timeline
  as the spans that explain them.
"""

from __future__ import annotations

from typing import Mapping, Sequence

from .registry import REGISTRY, MetricsRegistry

__all__ = [
    "prometheus_text",
    "prometheus_from_snapshot",
    "parse_prometheus_text",
    "json_snapshot",
    "chrome_counter_events",
]


def _fmt(v: float) -> str:
    if isinstance(v, float):
        # Prometheus exposition spells non-finite values +Inf/-Inf/NaN;
        # int(inf) raises, which used to 500 the whole /metrics page
        # over one inf gauge
        if v != v:
            return "NaN"
        if v in (float("inf"), float("-inf")):
            return "+Inf" if v > 0 else "-Inf"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)
    return str(v)


def _split_series(series: str) -> tuple[str, str]:
    """``name{labels}`` → (name, labels-without-braces)."""
    if "{" in series:
        name, rest = series.split("{", 1)
        return name, rest.rstrip("}")
    return series, ""


def _with_labels(name: str, labels: str, extra: str = "") -> str:
    inner = ",".join(x for x in (labels, extra) if x)
    return f"{name}{{{inner}}}" if inner else name


def prometheus_from_snapshot(snapshot: dict,
                             help_map: dict | None = None) -> str:
    """A :meth:`MetricsRegistry.snapshot` dict in Prometheus exposition
    format — THE renderer (``prometheus_text`` and the artifact replay
    in tools/metrics_dump.py both use it, so a live scrape and an
    artifact re-render are label-for-label identical).  Sorted, so
    equal snapshots produce byte-equal output."""
    help_map = help_map or {}
    lines: list[str] = []
    seen: set[str] = set()

    def header(name: str, kind: str) -> None:
        if name not in seen:
            seen.add(name)
            if help_map.get(name):
                lines.append(f"# HELP {name} {help_map[name]}")
            lines.append(f"# TYPE {name} {kind}")

    for kind_key, kind in (("counters", "counter"), ("gauges", "gauge")):
        block = snapshot.get(kind_key) or {}
        for series in sorted(block):
            name, labels = _split_series(series)
            header(name, kind)
            lines.append(f"{_with_labels(name, labels)} {_fmt(block[series])}")
    for series in sorted(snapshot.get("histograms") or {}):
        v = snapshot["histograms"][series]
        name, labels = _split_series(series)
        header(name, "histogram")
        cum = 0
        for ub, c in zip(v["buckets"], v["counts"]):
            cum += c
            le = 'le="%s"' % _fmt(ub)
            lines.append(f"{_with_labels(name + '_bucket', labels, le)} {cum}")
        cum += v["counts"][-1]
        le_inf = 'le="+Inf"'
        lines.append(
            f"{_with_labels(name + '_bucket', labels, le_inf)} {cum}")
        lines.append(f"{_with_labels(name + '_sum', labels)} {v['sum']}")
        lines.append(f"{_with_labels(name + '_count', labels)} {v['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def parse_prometheus_text(text: str) -> dict:
    """The exposition format back into ``{"types": {name: kind},
    "series": {series: value}}`` — the inverse the HTTP consumers need
    (``tools/metrics_dump.py --watch`` polling a live ``/metrics``
    endpoint, and the debug-server integration test's "parses as
    Prometheus text" gate).  Histogram ``_bucket``/``_sum``/``_count``
    lines ride as plain series.  Raises ``ValueError`` on a line that
    is neither a comment nor a ``series value`` pair — a scrape that
    half-parses must fail loudly, not render a half-table."""
    types: dict[str, str] = {}
    series: dict[str, float] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        # the series name ends at the close of its label block (or the
        # first bare space for label-less series — label VALUES may
        # contain spaces, so brace depth decides, not split()); what
        # follows is `value [timestamp]` per the exposition spec —
        # splitting at the LAST space would eat the optional timestamp
        # as the value and fold the real value into the series key
        depth = 0
        end = -1
        for i, ch in enumerate(line):
            if ch == "{":
                depth += 1
            elif ch == "}":
                depth -= 1
                if depth == 0:
                    end = i + 1
                    break
            elif ch == " " and depth == 0:
                end = i
                break
        if end < 0:
            raise ValueError(f"unparseable exposition line {lineno}: {line!r}")
        name = line[:end]
        rest = line[end:].split()
        if not rest or len(rest) > 2:  # value + optional timestamp only
            raise ValueError(f"unparseable exposition line {lineno}: {line!r}")
        try:
            series[name] = float(rest[0])
        except ValueError as e:
            raise ValueError(
                f"non-numeric sample on line {lineno}: {line!r}") from e
    return {"types": types, "series": series}


def prometheus_text(registry: MetricsRegistry | None = None) -> str:
    """The live registry in Prometheus exposition format (the snapshot
    renderer plus the registry's help strings)."""
    reg = registry if registry is not None else REGISTRY
    return prometheus_from_snapshot(
        reg.snapshot(), help_map={m.name: m.help for m in reg if m.help})


def json_snapshot(registry: MetricsRegistry | None = None) -> dict:
    """Deterministic JSON-able snapshot (bench artifacts embed this)."""
    reg = registry if registry is not None else REGISTRY
    return reg.snapshot()


def chrome_counter_events(
    series: Mapping[str, Sequence[tuple[float, float]]],
    t_base: float,
    pid: int = 1,
) -> list[dict]:
    """Chrome-trace counter events (``ph: "C"``) from sampled series.

    ``series`` is ``MetricsRegistry.counter_series()`` output; ``t_base``
    the perf_counter origin the span export used, so counter samples and
    spans land on one timeline.  Samples before ``t_base`` are dropped
    (they predate the window being exported)."""
    events: list[dict] = []
    for name in sorted(series):
        for t, v in series[name]:
            if t < t_base:
                continue
            events.append({
                "ph": "C",
                "name": name,
                "pid": pid,
                "ts": (t - t_base) * 1e6,
                "args": {"value": v},
            })
    return events
