"""Process-global metrics registry: always-on counters, gauges, and
fixed-bucket histograms for the runtime's steady-state health.

The tracer (``trace/spans.py``) answers "where did THIS window's time
go"; it is scoped, ring-buffered, and off by default.  Steady-state
counters — balancer shares, driver-queue occupancy, fused
engage/disengage, transfer bytes, DCN exchange traffic — used to live as
ad-hoc dicts (``Cores.fused_stats``, ``Worker.benchmarks``) with no
uniform export.  This registry gives every such number ONE home with
three exports (``metrics/export.py``): Prometheus text, a JSON snapshot
(embedded in bench artifacts), and Perfetto counter tracks merged into
the Chrome-trace export so metrics ride the same timeline as spans.

Design constraints, same discipline as the tracer:

1. **Disabled is one branch.**  ``REGISTRY.enabled = False`` turns every
   instrument site into an attribute read + falsy check; the marginal
   cost over an unavoidable Python method call is pinned < 100 ns by
   ``tests/test_metrics.py`` (the call itself is interpreter floor —
   ~120 ns on slow containers — which no registry design can remove).
2. **Enabled is a lock per update, and that is deliberate.**  Unlike the
   tracer's overwrite-tolerant ring, metric values are EXACT: N threads
   incrementing K times must snapshot to N·K (``x += n`` alone loses
   updates across bytecode boundaries).  An uncontended CPython lock is
   ~100 ns — fine for per-dispatch/per-transfer granularity; truly hot
   inner loops should aggregate locally and ``inc()`` once per batch.
3. **Snapshots are deterministic.**  ``snapshot()`` sorts series keys,
   so two snapshots of the same state serialize identically — the bench
   artifact diffing in ``tools/regress.py`` depends on it.

Label model: labels are fixed at metric creation
(``REGISTRY.counter("ck_upload_bytes_total", lane=0)``) and become part
of the series identity, Prometheus-style.  ``counter()`` / ``gauge()`` /
``histogram()`` are get-or-create: calling them again with the same
(name, labels) returns the SAME metric object, so instrument sites may
either cache the handle (static labels) or resolve per call (dynamic
labels like compute id — one dict lookup).
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Iterator

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "DEFAULT_BUCKETS",
    "series_name",
]

#: Default histogram upper bounds (seconds-flavored, Prometheus
#: convention): spans µs-scale dispatch costs through multi-second
#: fences.  The last implicit bucket is +Inf.
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def series_name(name: str, labels: tuple[tuple[str, str], ...]) -> str:
    """Prometheus-style series identity: ``name{k="v",...}`` with labels
    sorted — the deterministic snapshot/export key."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


class _Metric:
    """Shared identity/plumbing.  ``_series`` is the bounded
    (timestamp, value) sample ring feeding Perfetto counter tracks —
    populated only while ``REGISTRY.sampling`` is on (the tracing
    context enables both), so steady-state operation stores no
    history."""

    kind = "untyped"

    def __init__(self, reg: "MetricsRegistry", name: str,
                 labels: tuple[tuple[str, str], ...], help: str = ""):
        self._reg = reg
        self.name = name
        self.labels = labels
        self.help = help
        self._lock = threading.Lock()
        self._series: deque | None = None

    @property
    def series(self) -> str:
        return series_name(self.name, self.labels)

    def _sample(self, value: float) -> None:
        # callers invoke this INSIDE their update lock: appending after
        # release would let a preempted thread push a stale smaller
        # value behind a newer one, and the Perfetto counter track
        # would show a "monotonic" counter decreasing
        s = self._series
        if s is not None:
            s.append((time.perf_counter(), value))

    def samples(self) -> list[tuple[float, float]]:
        """Recorded (perf_counter, value) samples (sampling mode only).
        Copied under the metric lock: iterating a deque while an update
        thread appends raises RuntimeError."""
        with self._lock:
            s = self._series
            return list(s) if s is not None else []


class Counter(_Metric):
    """Monotonically increasing value (events, bytes)."""

    kind = "counter"

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._value = 0

    def inc(self, amount: int | float = 1) -> None:
        if not self._reg.enabled:
            return
        with self._lock:
            self._value += amount
            if self._reg.sampling:
                self._sample(self._value)

    @property
    def value(self):
        return self._value


class Gauge(_Metric):
    """Point-in-time value (queue depth, share)."""

    kind = "gauge"

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._value = 0.0

    def set(self, value: float) -> None:
        if not self._reg.enabled:
            return
        if self._reg.sampling:
            with self._lock:  # keeps the sample series in value order
                self._value = value
                self._sample(value)
        else:
            self._value = value  # single store: last-write-wins

    def inc(self, amount: float = 1) -> None:
        if not self._reg.enabled:
            return
        with self._lock:
            self._value += amount
            if self._reg.sampling:
                self._sample(self._value)

    def dec(self, amount: float = 1) -> None:
        self.inc(-amount)

    @property
    def value(self):
        return self._value


class Histogram(_Metric):
    """Fixed-bucket histogram.  ``buckets`` are ascending upper bounds;
    an implicit +Inf bucket catches the tail.  An observation lands in
    the FIRST bucket whose upper bound is >= the value (Prometheus
    ``le`` semantics: an observation exactly on a boundary belongs to
    that boundary's bucket — pinned by the bucket-boundary property test
    in tests/test_metrics.py)."""

    kind = "histogram"

    def __init__(self, reg, name, labels, help="",
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        super().__init__(reg, name, labels, help)
        b = tuple(float(x) for x in buckets)
        if list(b) != sorted(set(b)):
            raise ValueError(f"histogram buckets must be ascending: {b}")
        self.buckets = b
        self._counts = [0] * (len(b) + 1)  # + the +Inf bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        if not self._reg.enabled:
            return
        i = bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            if self._reg.sampling:
                self._sample(value)

    @property
    def value(self) -> dict:
        with self._lock:
            return {
                "buckets": list(self.buckets),
                "counts": list(self._counts),
                "sum": self._sum,
                "count": self._count,
            }


class MetricsRegistry:
    """One process-global instance (:data:`REGISTRY`).

    ``enabled`` ships True — the registry is ALWAYS-ON by design (the
    whole point is noticing regressions nobody was watching for); the
    off switch exists for overhead-sensitive measurement windows (the
    marker-overhead bench) and the budget test.  ``sampling`` (off by
    default) additionally records bounded per-metric time series for
    Perfetto counter tracks."""

    def __init__(self, sample_capacity: int = 4096):
        self.enabled = True
        self.sampling = False
        self._sample_cap = int(sample_capacity)
        self._lock = threading.Lock()
        self._metrics: dict[tuple, _Metric] = {}

    # -- get-or-create -------------------------------------------------------
    def _get(self, cls, name: str, help: str, labels: dict, **kw) -> _Metric:
        lab = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        key = (name, lab)
        m = self._metrics.get(key)  # lock-free fast path (GIL-safe read)
        if m is not None:
            if type(m) is not cls:
                raise TypeError(
                    f"metric {series_name(name, lab)} already registered "
                    f"as {m.kind}, requested {cls.kind}"
                )
            return m
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(self, name, lab, help, **kw)
                if self.sampling:
                    m._series = deque(maxlen=self._sample_cap)
                self._metrics[key] = m
        return m

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS,
                  **labels) -> Histogram:
        h = self._get(Histogram, name, help, labels, buckets=buckets)
        if h.buckets != tuple(float(x) for x in buckets):
            raise ValueError(
                f"metric {h.series} already registered with buckets "
                f"{h.buckets}, requested {buckets}"
            )
        return h

    # -- control -------------------------------------------------------------
    def enable_sampling(self, capacity: int | None = None) -> None:
        """Start recording per-metric (t, value) series for Perfetto
        counter tracks.  Existing metrics get fresh rings."""
        with self._lock:
            if capacity is not None:
                self._sample_cap = int(capacity)
            for m in self._metrics.values():
                m._series = deque(maxlen=self._sample_cap)
            self.sampling = True

    def disable_sampling(self, clear: bool = False) -> None:
        with self._lock:
            self.sampling = False
            if clear:
                for m in self._metrics.values():
                    m._series = None

    def reset(self) -> None:
        """Zero every registered metric IN PLACE (tests / process
        reuse).  The metric objects survive on purpose: instrument
        sites cache handles (Worker/Cores hold them for the hot paths),
        and dropping the dict would orphan those — they'd keep
        incrementing objects no future snapshot includes, while
        get-or-create sites re-register fresh ones, yielding an
        inconsistent health view with no error anywhere."""
        with self._lock:
            for m in self._metrics.values():
                with m._lock:
                    if isinstance(m, Histogram):
                        m._counts = [0] * (len(m.buckets) + 1)
                        m._sum = 0.0
                        m._count = 0
                    elif isinstance(m, Gauge):
                        m._value = 0.0
                    else:
                        m._value = 0
                    if m._series is not None:
                        m._series.clear()

    # -- inspection ----------------------------------------------------------
    def __iter__(self) -> Iterator[_Metric]:
        # copy under the lock: a scrape thread iterating while a worker
        # registers a first-ever series (new disengage reason, new lane)
        # must not hit "dictionary changed size during iteration"
        with self._lock:
            ms = list(self._metrics.values())
        return iter(sorted(ms, key=lambda m: m.series))

    def __len__(self) -> int:
        return len(self._metrics)

    def snapshot(self) -> dict:
        """Deterministic JSON-able state: series name → value, grouped by
        metric kind, keys sorted.  Two snapshots of identical state
        serialize identically (regress.py diffs depend on it)."""
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for m in self:
            if isinstance(m, Counter):
                out["counters"][m.series] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][m.series] = m.value
            elif isinstance(m, Histogram):
                out["histograms"][m.series] = m.value
        return out

    def counter_series(self) -> dict[str, list[tuple[float, float]]]:
        """Sampled time series per series name (sampling mode) — the
        input to the Perfetto counter-track export."""
        out: dict[str, list[tuple[float, float]]] = {}
        for m in self:
            s = m.samples()
            if s:
                out[m.series] = s
        return out


#: The process-global registry every built-in instrument site uses.
REGISTRY = MetricsRegistry()
