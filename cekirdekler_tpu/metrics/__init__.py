"""``cekirdekler_tpu.metrics`` — the always-on health registry.

Counters, gauges, and fixed-bucket histograms for every steady-state
number the runtime produces (balancer shares, driver-queue depth,
transfer bytes, fused engage/disengage, DCN exchange traffic), with
three exports: Prometheus text, a deterministic JSON snapshot (embedded
in bench artifacts), and Perfetto counter tracks merged into the
Chrome-trace span export.  See docs/OBSERVABILITY.md "Metrics &
aggregation".

Relationship to ``cekirdekler_tpu.trace``: the tracer answers "where did
this window's time go" (scoped, ring-buffered, off by default); the
registry answers "is the system healthy right now" (process-global,
always on, < 100 ns marginal cost when disabled — pinned by
tests/test_metrics.py).  ``trace.tracing(metrics=True)`` turns on
registry sampling for the window so both ride one timeline.

No jax imports at module level — reading a counter costs no backend
initialization.
"""

from .export import (
    chrome_counter_events,
    json_snapshot,
    parse_prometheus_text,
    prometheus_from_snapshot,
    prometheus_text,
)
from .registry import (
    DEFAULT_BUCKETS,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    series_name,
)

__all__ = [
    "Counter",
    "DEFAULT_BUCKETS",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "chrome_counter_events",
    "json_snapshot",
    "parse_prometheus_text",
    "prometheus_from_snapshot",
    "prometheus_text",
    "series_name",
]
