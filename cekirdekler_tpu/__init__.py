"""cekirdekler_tpu — a TPU-native multi-chip compute framework.

A from-scratch, TPU-first framework with the capabilities of the reference
C#/OpenCL Cekirdekler API: treat all chips of a TPU slice as one device for
user-supplied kernels.  Kernels (an OpenCL-C-like subset, Python functions,
or raw Pallas) are JIT-compiled via XLA and dispatched across chips with an
iterative, per-compute-id load balancer; host arrays stage through pinned
aligned buffers; transfer/compute overlap rides XLA async dispatch; pipeline
stages exchange data over ICI collectives; pools, a cluster tier, and
sequence/tensor parallel utilities sit on top.
"""

from .arrays import ClArray, FastArr, FloatArr, IntArr, ParameterGroup, TransferFlags, wrap
from .errors import (
    CekirdeklerError,
    ComputeValidationError,
    DeviceSelectionError,
    KernelCompileError,
    KernelLanguageError,
)
from .hardware import AcceleratorType, Device, Devices, Platform, Platforms, all_devices, platforms
from . import metrics  # always-on health registry (docs/OBSERVABILITY.md)
from . import obs  # live introspection plane (docs/OBSERVABILITY.md)
from . import trace  # span-based attribution (docs/OBSERVABILITY.md)

__version__ = "0.1.0"

__all__ = [
    "AcceleratorType",
    "CekirdeklerError",
    "ClArray",
    "ComputeValidationError",
    "Device",
    "DeviceSelectionError",
    "Devices",
    "FastArr",
    "FloatArr",
    "IntArr",
    "KernelCompileError",
    "KernelLanguageError",
    "ParameterGroup",
    "Platform",
    "Platforms",
    "TransferFlags",
    "all_devices",
    "platforms",
    "metrics",
    "trace",
    "wrap",
]
