"""Verdicts: kernel access summaries × declared transfer flags.

:func:`verify_launch` takes the flag-independent per-kernel summaries
(``interp.summarize_kernel``) plus the launch's declared
:class:`TransferFlags` rows and produces named findings in two
severities:

- **errors** — the launch is provably (or unprovably-and-therefore-
  presumed) unsafe to split: running it partitioned across lanes can
  produce results that differ from the unsplit run, or reads data the
  declared flags never upload.  ``CK_KERNEL_VERIFY=strict`` turns
  these into raised :class:`KernelVerifyError` / serve rejections.
- **advisories** — the launch is correct but wasteful (an over-broad
  full read on a gid-confined access pays H2D bytes every call), or
  outside the analyzable surface (``@kernel`` Python kernels).

The kind vocabulary is :data:`VERDICT_KINDS`; the table in
``docs/STATIC_ANALYSIS.md`` is cross-checked against it by test (the
``lint_obs`` two-way discipline).  Findings on lines carrying a
``// ckprove: ok`` comment (or directly below one) are suppressed —
annotation is documentation, not a mute button: say why.
"""

from __future__ import annotations

import hashlib
import math
from collections import namedtuple
from dataclasses import dataclass

from .interp import AV, KernelSummary

__all__ = [
    "VERDICT_KINDS", "ERROR_KINDS", "ADVISORY_KINDS",
    "Finding", "LaunchVerdict", "FlagRow",
    "classify", "flag_row", "structural_findings", "suppressed_lines",
    "verify_launch",
]

#: The declared verdict vocabulary (the ``DECISION_KINDS`` contract,
#: applied to kernel verification).  docs/STATIC_ANALYSIS.md carries
#: the human table; a drift between the two fails tier-1.
VERDICT_KINDS = (
    "off-partition-write",   # error: write provably outside the lane's slice
    "scatter-write",         # error: write at an unprovable (gathered) index
    "write-all-clipped",     # error: write_all discards non-owner partitions
    "partial-read-halo",     # error: partial_read but reads leave the window
    "partial-read-gather",   # error: partial_read but reads gather/roam
    "write-only-read",       # error: write_only but read-before-write
    "window-raw",            # error: cross-lane RAW hazard across the window
    "partial-safe",          # advisory: full read, provably gid-confined
    "unread-upload",         # advisory: read flag, never read
    "unwritten-writeback",   # advisory: write flag, never written
    "unverifiable",          # advisory: kernel outside the analyzable surface
)

ERROR_KINDS = VERDICT_KINDS[:7]
ADVISORY_KINDS = VERDICT_KINDS[7:]


@dataclass(frozen=True)
class Finding:
    """One verdict finding.  The fingerprint excludes the line number
    (the ckcheck ratchet rule: edits above a finding must not churn
    the baseline); ``where``+``kernel``+``param`` carry identity."""

    kind: str
    severity: str           # "error" | "advisory"
    where: str              # corpus file / "<compute>" / caller tag
    kernel: str
    param: str              # kernel parameter name ("*" = whole kernel)
    line: int               # 1-based line in the KERNEL SOURCE string
    message: str

    @property
    def fingerprint(self) -> str:
        raw = f"ckprove:{self.kind}:{self.where}:{self.kernel}:{self.param}"
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    @property
    def path(self) -> str:
        """Alias so the ckcheck baseline/ratchet machinery (which
        sorts findings by ``path``) applies unchanged."""
        return self.where

    def render(self) -> str:
        return (f"[{self.fingerprint}] {self.severity}/{self.kind} "
                f"{self.where}:{self.kernel}:{self.line}: {self.message}")

    def to_row(self) -> dict:
        return {
            "fingerprint": self.fingerprint,
            "kind": self.kind,
            "severity": self.severity,
            "path": self.where,
            "kernel": self.kernel,
            "param": self.param,
            "line": self.line,
            "message": self.message,
        }


@dataclass(frozen=True)
class LaunchVerdict:
    """All findings for one (kernel sequence, flags) launch shape."""

    findings: tuple = ()

    @property
    def errors(self) -> tuple:
        return tuple(f for f in self.findings if f.severity == "error")

    @property
    def advisories(self) -> tuple:
        return tuple(f for f in self.findings if f.severity == "advisory")

    @property
    def ok(self) -> bool:
        return not self.errors


#: The flag surface the verdict reads — a plain tuple so launch
#: verdicts cache on it and decision records serialize it.
FlagRow = namedtuple(
    "FlagRow",
    ["read", "partial_read", "write", "write_all", "read_only",
     "write_only", "epw"],
)


def flag_row(flags) -> FlagRow:
    """Project a :class:`TransferFlags` (duck-typed) into a hashable
    :class:`FlagRow`.

    Memoized on the flags instance: the runtime gate rebuilds rows on
    every per-call dispatch, which must not tax the host-dispatch
    floor the repo benchmarks.  Safe because the flag API replaces
    ``TransferFlags`` objects (``ClArray._set_flag``/``wrap`` go
    through ``dataclasses.replace``) rather than mutating them — a new
    flag combination is a new object with no cached row."""
    row = getattr(flags, "_ckprove_row", None)
    if row is None:
        row = FlagRow(
            read=bool(flags.read),
            partial_read=bool(flags.partial_read),
            write=bool(flags.write),
            write_all=bool(flags.write_all),
            read_only=bool(flags.read_only),
            write_only=bool(flags.write_only),
            epw=int(flags.elements_per_work_item),
        )
        try:
            flags._ckprove_row = row
        except Exception:  # noqa: BLE001 - frozen/slotted duck: skip
            pass
    return row


def classify(av: AV, epw: int = 1):
    """Classify one access index against the lane's per-item window.

    Returns ``(klass, halo_width)`` with klass one of

    - ``"confined"`` — ``epw·gid + [0, epw)``: lands inside the item's
      own elements for ANY split;
    - ``"halo"`` — gid-affine at the right stride but the offset leaves
      the window by a bounded ``halo_width`` elements;
    - ``"stride"`` — gid-affine at the WRONG stride (coef != epw);
    - ``"uniform"`` — identical across items (constants included):
      lane-relative position is unbounded under a split;
    - ``"gather"`` — not affine in gid (data-dependent / modular /
      unbounded offset): nothing provable.
    """
    if av.coef is None:
        return "gather", None
    if av.coef == 0:
        return "uniform", None
    if av.coef == float(epw):
        if 0 <= av.lo and av.hi <= epw - 1:
            return "confined", 0
        lo_over = max(0.0, 0 - av.lo)
        hi_over = max(0.0, av.hi - (epw - 1))
        width = max(lo_over, hi_over)
        if math.isfinite(width):
            return "halo", int(width)
        return "gather", None
    return "stride", None


def suppressed_lines(source: str) -> frozenset:
    """Re-export of the interp helper for callers that hold raw
    source (the CLI's per-file scan)."""
    from .interp import _suppressed_lines

    return _suppressed_lines(source)


def _covered_earlier(prior_sums, pos: int, epw: int) -> bool:
    """True when an EARLIER kernel in the sequence unconditionally
    writes parameter ``pos`` gid-confined — its device-local stores
    persist, so a later kernel's read-before-write is covered.  An
    unanalyzable predecessor MAY cover: stay silent (errors must be
    provable)."""
    for s in prior_sums:
        if s is None:
            return True
        if pos < len(s.array_params):
            pname = s.array_params[pos]
            for av in s.must_writes.get(pname, ()):
                if classify(av, epw)[0] == "confined":
                    return True
    return False


def _off_partition_reads(summary: KernelSummary, pname: str, epw: int):
    out = []
    for acc in summary.reads.get(pname, ()):
        klass, width = classify(acc.av, epw)
        if klass != "confined":
            out.append((acc, klass, width))
    return out


def verify_launch(
    summaries: dict,
    kernel_names,
    flag_rows,
    window: bool = False,
    where: str = "<compute>",
) -> LaunchVerdict:
    """Prove or refute split-safety and flag soundness for one launch.

    ``summaries`` maps kernel name → :class:`KernelSummary` (or None
    for kernels outside the analyzable surface — Python/Pallas
    kernels); ``flag_rows`` is the positional :class:`FlagRow` tuple of
    the call's parameter list (kernel k binds the first
    ``len(summary.array_params)`` rows, the dispatch contract).
    ``window=True`` additionally treats the kernel sequence as cyclic
    (enqueue windows / fused ladders repeat it), so a RAW hazard from
    kernel B's read back into kernel A's write across iterations is
    reported too.
    """
    findings: list[Finding] = []
    seen: set = set()

    def emit(kind, kernel, param, line, message, suppressed=frozenset()):
        if line in suppressed:
            return
        severity = "error" if kind in ERROR_KINDS else "advisory"
        key = (kind, kernel, param, line)
        if key in seen:
            return
        seen.add(key)
        findings.append(Finding(
            kind=kind, severity=severity, where=where, kernel=kernel,
            param=param, line=line, message=message))

    names = tuple(kernel_names)
    rows = tuple(flag_rows)
    sums: list[KernelSummary | None] = [summaries.get(n) for n in names]
    for ki, name in enumerate(names):
        s = sums[ki]
        if s is None:
            emit(
                "unverifiable", name, "*", 0,
                f"kernel {name!r} is outside the analyzable surface "
                "(Python/Pallas kernel or analysis bail-out) — flags "
                "and split-safety are unchecked")
            continue
        sup = s.suppressed
        for pos, pname in enumerate(s.array_params):
            if pos >= len(rows):
                break  # arg-count mismatch: compute() validation's job
            fl = rows[pos]
            epw = max(1, fl.epw)
            reads = s.reads.get(pname, ())
            writes = s.writes.get(pname, ())
            reads_flag = fl.read and not fl.write_only
            writes_back = fl.write and not fl.read_only

            if writes_back:
                for acc in writes:
                    klass, width = classify(acc.av, epw)
                    if klass == "confined":
                        if fl.write_all:
                            emit(
                                "write-all-clipped", name, pname, acc.line,
                                f"{name}: write_all on {pname!r} whose "
                                "writes are gid-confined — the owner lane "
                                "writes back the WHOLE array, discarding "
                                "every other lane's partition on any "
                                ">1-lane split", sup)
                        continue
                    if klass == "gather":
                        emit(
                            "scatter-write", name, pname, acc.line,
                            f"{name}: write to {pname}[…] at a gathered/"
                            "indirect index — cannot prove the store lands "
                            "inside the caller's partition; a split lane "
                            "drops every off-partition store at readback",
                            sup)
                    else:
                        detail = (
                            f"halo offset {width} outside the per-item "
                            f"window" if klass == "halo" else
                            f"stride {acc.av.coef:g} != elements/item "
                            f"{epw}" if klass == "stride" else
                            "uniform index (same element from every item)")
                        emit(
                            "off-partition-write", name, pname, acc.line,
                            f"{name}: write to {pname}[…] provably leaves "
                            f"the caller's partition ({detail}) — "
                            "off-partition stores are silently dropped at "
                            "the lane's sliced readback", sup)

            if reads_flag and fl.partial_read:
                for acc, klass, width in _off_partition_reads(s, pname, epw):
                    if klass == "halo":
                        emit(
                            "partial-read-halo", name, pname, acc.line,
                            f"{name}: partial_read on {pname!r} but the "
                            f"kernel reads a halo of {width} element(s) "
                            "beyond the item's window — each lane only "
                            "receives its own slice, halo elements arrive "
                            "as zeros", sup)
                    else:
                        emit(
                            "partial-read-gather", name, pname, acc.line,
                            f"{name}: partial_read on {pname!r} but the "
                            f"kernel reads it at a {klass} index — lanes "
                            "only receive their own slice; declare a full "
                            "read", sup)

            if fl.write_only and pname in s.rbw and \
                    not _covered_earlier(sums[:ki], pos, epw):
                emit(
                    "write-only-read", name, pname, s.rbw[pname],
                    f"{name}: write_only on {pname!r} but the kernel reads "
                    "it before any covering write — write_only arrays are "
                    "never uploaded, the read sees zeros, not host data",
                    sup)

    # launch-level waste advisories aggregate over the whole SEQUENCE:
    # an upload is unread only if NO kernel in the sequence reads it,
    # and a full read is partial-eligible only if EVERY kernel's reads
    # of that position are gid-confined.  Skipped when any kernel is
    # unanalyzable — it may touch the array in ways we cannot see.
    if sums and all(s is not None for s in sums):
        n_pos = min(len(rows), max(len(s.array_params) for s in sums))
        for pos in range(n_pos):
            fl = rows[pos]
            epw = max(1, fl.epw)
            users = [s for s in sums if pos < len(s.array_params)]
            if not users:
                continue
            reads_all = [
                (s, a) for s in users
                for a in s.reads.get(s.array_params[pos], ())]
            writes_all = [
                (s, a) for s in users
                for a in s.writes.get(s.array_params[pos], ())]
            reads_flag = fl.read and not fl.write_only
            writes_back = fl.write and not fl.read_only
            pname = users[0].array_params[pos]
            if reads_flag and not fl.partial_read and reads_all and all(
                    classify(a.av, epw)[0] == "confined"
                    for _s, a in reads_all):
                s0, a0 = reads_all[0]
                emit(
                    "partial-safe", s0.name, s0.array_params[pos], a0.line,
                    f"every read of {pname!r} across the sequence is "
                    "gid-confined — partial_read=True would upload only "
                    "each lane's slice (free H2D reduction)",
                    s0.suppressed)
            if reads_flag and not reads_all:
                emit(
                    "unread-upload", users[0].name, pname, users[0].line,
                    f"{pname!r} is uploaded (read flag) but no kernel in "
                    "the sequence reads it — H2D bytes wasted every call",
                    users[0].suppressed)
            if writes_back and not writes_all:
                emit(
                    "unwritten-writeback", users[0].name, pname,
                    users[0].line,
                    f"{pname!r} is written back (write flag) but no "
                    "kernel in the sequence writes it — D2H bytes wasted "
                    "every call", users[0].suppressed)

    # cross-kernel window hazards: A writes p, B reads p off-partition.
    # Device-local writes persist across the window whether or not the
    # flags write them back, so ANY write counts as a hazard source.
    writers: dict[int, list] = {}
    off_readers: dict[int, list] = {}
    for ki, (name, s) in enumerate(zip(names, sums)):
        if s is None:
            continue
        for pos, pname in enumerate(s.array_params):
            if pos >= len(rows):
                break
            epw = max(1, rows[pos].epw)
            if s.writes.get(pname):
                writers.setdefault(pos, []).append((ki, name))
            for acc, klass, width in _off_partition_reads(s, pname, epw):
                off_readers.setdefault(pos, []).append(
                    (ki, name, pname, acc.line, klass, s.suppressed))
    for pos, ws in writers.items():
        for wi, wname in ws:
            for ri, rname, pname, line, klass, sup in \
                    off_readers.get(pos, ()):
                ordered = ri >= wi  # same kernel: chunk-ladder order
                if not (ordered or window):
                    continue
                how = ("across window iterations"
                       if window and not ordered else "within the sequence")
                emit(
                    "window-raw", rname, pname, line,
                    f"{wname} writes parameter #{pos} and {rname} reads "
                    f"it {klass}-indexed ({how}) — a lane reads elements "
                    "another lane wrote, which never left that lane's "
                    "device: cross-lane RAW hazard under any >1-lane "
                    "split", sup)

    return LaunchVerdict(findings=tuple(findings))


def structural_findings(
    summary: KernelSummary, where: str, epw: int = 1,
) -> list:
    """Flag-independent findings for the CLI's repo-corpus scan, where
    no :class:`TransferFlags` exist statically: split-safety of the
    write set (assuming the default one element per work item).  Read
    classifications surface in the CLI's ``--json`` report as facts,
    not findings — whether a halo read is an error depends on flags
    only the call site knows."""
    v = verify_launch(
        {summary.name: summary}, (summary.name,),
        (FlagRow(True, False, True, False, False, False, epw),)
        * len(summary.array_params),
        window=False, where=where)
    keep = ("off-partition-write", "scatter-write")
    return [f for f in v.findings if f.kind in keep]
