"""Abstract interpretation of kernel ASTs: index provenance tracking.

The domain is the gid-affine interval lattice

    AV(coef, lo, hi)  ≡  { coef·gid + c : c ∈ [lo, hi] }

with three distinguished shapes:

- ``coef == 0`` — **uniform**: the value is identical across work
  items (constants have ``lo == hi``; a value parameter or a loop
  counter bounded by one is uniform with an unknown interval);
- ``coef != 0`` (finite) — **gid-affine**: the value moves with the
  work-item id at a fixed stride (``a[i]`` is coef 1 offset 0;
  ``a[i+2]`` coef 1 offset 2; ``a[2*i+1]`` coef 2 offset 1);
- ``coef is None`` — **top**: gid-dependent but not affine (``i % w``,
  a value loaded from an array, ``get_local_id``) — a gather/indirect
  index when used at an access site.

Everything is deliberately *under*-approximate toward safety: any
operation the transfer rules above cannot model exactly produces TOP,
never a fabricated affine form — a missed proof surfaces as an
advisory or a named error the user can suppress, a wrong proof would
let a corrupting split through.

Loops run to an interval fixpoint (3 join rounds, then widening to
±inf on the moving bound), and access sites inside the loop are
recorded in one final pass over the stabilized environment — so
``for (j = 0; j < n; j++) acc += x[j];`` records ONE uniform read of
``x``, not a parade of transient constants.

Helper functions (scalar-only by the language contract) are inlined
abstractly at call sites, exactly as the codegen inlines them.

Pure ``lang`` + stdlib — no jax, no numpy: this module must run on
rigs where the runtime is broken (the ckcheck discipline).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from ..kernel import lang

__all__ = ["AV", "Access", "KernelSummary", "summarize_kernel"]

INF = float("inf")

#: Work-item queries that are uniform across the chunk.
_UNIFORM_FUNCS = {
    "get_global_size", "get_local_size", "get_num_groups",
    "get_global_offset", "get_work_dim",
}
#: Work-item queries that are gid-dependent but NOT affine in gid.
_NONAFFINE_FUNCS = {"get_local_id", "get_group_id"}


@dataclass(frozen=True)
class AV:
    """One abstract value: ``coef·gid + [lo, hi]`` (see module doc)."""

    coef: float | None
    lo: float
    hi: float

    @staticmethod
    def const(v) -> "AV":
        return AV(0.0, float(v), float(v))

    @property
    def is_const(self) -> bool:
        return self.coef == 0 and self.lo == self.hi and math.isfinite(self.lo)


TOP = AV(None, -INF, INF)
UNIFORM = AV(0.0, -INF, INF)
GID = AV(1.0, 0.0, 0.0)


def _add(a: AV, b: AV) -> AV:
    if a.coef is None or b.coef is None:
        return TOP
    return AV(a.coef + b.coef, a.lo + b.lo, a.hi + b.hi)


def _neg(a: AV) -> AV:
    if a.coef is None:
        return TOP
    return AV(-a.coef, -a.hi, -a.lo)


def _scale(a: AV, k: float) -> AV:
    if a.coef is None:
        return TOP
    if k == 0:
        return AV.const(0)
    lo, hi = sorted((a.lo * k, a.hi * k))
    return AV(a.coef * k, lo, hi)


def _mul(a: AV, b: AV) -> AV:
    if a.is_const:
        return _scale(b, a.lo)
    if b.is_const:
        return _scale(a, b.lo)
    if a.coef == 0 and b.coef == 0:
        return UNIFORM
    return TOP


def _uniform_combine(a: AV, b: AV) -> AV:
    """Result of an op the domain cannot model (/, %, >>, &, |, ^,
    comparisons): uniform when both operands are, else top."""
    if a.coef == 0 and b.coef == 0:
        return UNIFORM
    return TOP


def _join(a: AV, b: AV) -> AV:
    if a == b:
        return a
    if a.coef is None or b.coef is None or a.coef != b.coef:
        if a.coef == 0 and b.coef == 0:
            return AV(0.0, min(a.lo, b.lo), max(a.hi, b.hi))
        return TOP
    return AV(a.coef, min(a.lo, b.lo), max(a.hi, b.hi))


def _widen(old: AV, new: AV) -> AV:
    if old == new:
        return old
    if old.coef is None or new.coef is None or old.coef != new.coef:
        if old.coef == 0 and new.coef == 0:
            return UNIFORM
        return TOP
    return AV(
        old.coef,
        old.lo if new.lo >= old.lo else -INF,
        old.hi if new.hi <= old.hi else INF,
    )


@dataclass(frozen=True)
class Access:
    """One recorded array access site."""

    param: str
    av: AV
    line: int
    is_write: bool
    conditional: bool


@dataclass
class KernelSummary:
    """Per-array access summary for one kernel (flag-independent —
    verdicts against declared flags are ``verdict.verify_launch``'s
    business, so one summary serves every flag combination)."""

    name: str
    array_params: tuple = ()
    value_params: tuple = ()
    reads: dict = field(default_factory=dict)    # param -> [Access]
    writes: dict = field(default_factory=dict)   # param -> [Access]
    rbw: dict = field(default_factory=dict)      # param -> first RBW line
    # param -> tuple[AV]: patterns written UNCONDITIONALLY (every work
    # item, every path) — the cross-kernel read-before-write witness
    must_writes: dict = field(default_factory=dict)
    suppressed: frozenset = frozenset()          # // ckprove: ok lines
    line: int = 0


class _Interp:
    """One abstract execution of one kernel body."""

    _INT_TYPES = {"bool", "char", "uchar", "short", "ushort", "int",
                  "uint", "long", "ulong"}

    def __init__(self, kernel: lang.KernelDef):
        self.kernel = kernel
        self.pointer_params = tuple(
            p.name for p in kernel.params if p.is_pointer)
        self.value_params = tuple(
            p.name for p in kernel.params if not p.is_pointer)
        self.env: dict[str, AV] = {
            name: UNIFORM for name in self.value_params}
        self.priv: dict[str, AV] = {}
        self.written: dict[str, list[AV]] = {}   # must-written patterns
        self.accesses: list[Access] = []
        self._seen: set = set()
        self.rbw: dict[str, int] = {}
        self.recording = True
        self.cond_depth = 0
        self.saw_return = False
        self._helper_depth = 0

    # -- access recording ----------------------------------------------------
    def _record(self, base: str, av: AV, line: int, write: bool) -> None:
        if base in self.priv:
            return  # private scratch: not a transfer surface
        if base not in self.pointer_params or not self.recording:
            return
        cond = self.cond_depth > 0 or self.saw_return
        key = (base, av, line, write, cond)
        if key not in self._seen:
            self._seen.add(key)
            self.accesses.append(Access(base, av, line, write, cond))
        if write:
            if not cond:
                self.written.setdefault(base, []).append(av)
        else:
            if base not in self.rbw and not self._covered(base, av):
                self.rbw[base] = line

    def _covered(self, base: str, av: AV) -> bool:
        if av.coef is None:
            return False
        for w in self.written.get(base, ()):
            if w.coef == av.coef and w.lo <= av.lo and av.hi <= w.hi:
                return True
        return False

    # -- expressions ---------------------------------------------------------
    def eval(self, node) -> AV:
        if node is None:
            return UNIFORM
        if isinstance(node, lang.Num):
            return AV.const(node.value)
        if isinstance(node, lang.Var):
            if node.name in self.env:
                return self.env[node.name]
            return TOP
        if isinstance(node, lang.Index):
            idx = self.eval(node.index)
            if node.base in self.priv:
                return self.priv[node.base]
            self._record(node.base, idx, node.line, write=False)
            # a value loaded from a buffer is data-dependent: using it
            # as an index later is a gather by definition
            return TOP
        if isinstance(node, lang.UnOp):
            v = self.eval(node.operand)
            if node.op == "+":
                return v
            if node.op == "-":
                return _neg(v)
            return UNIFORM if v.coef == 0 else TOP
        if isinstance(node, lang.Cast):
            v = self.eval(node.operand)
            if node.ctype in self._INT_TYPES and v.coef is not None:
                lo = math.floor(v.lo) if math.isfinite(v.lo) else v.lo
                hi = math.ceil(v.hi) if math.isfinite(v.hi) else v.hi
                return AV(v.coef, lo, hi)
            return v
        if isinstance(node, lang.Ternary):
            self.eval(node.cond)
            return _join(self.eval(node.then), self.eval(node.other))
        if isinstance(node, lang.BinOp):
            a = self.eval(node.left)
            b = self.eval(node.right)
            op = node.op
            if op == "+":
                return _add(a, b)
            if op == "-":
                return _add(a, _neg(b))
            if op == "*":
                return _mul(a, b)
            if op == "<<" and b.is_const and b.lo >= 0 and \
                    float(b.lo).is_integer():
                return _scale(a, float(1 << int(b.lo)))
            return _uniform_combine(a, b)
        if isinstance(node, lang.Call):
            return self._call(node)
        return TOP

    def _call(self, node: lang.Call) -> AV:
        name = node.name
        helpers = self.kernel.helpers or {}
        if name in helpers:
            args = [self.eval(a) for a in node.args]
            return self._inline_helper(helpers[name], args)
        if name.startswith(("native_", "half_")):
            name = name.split("_", 1)[1]
        args = [self.eval(a) for a in node.args]
        if name == "get_global_id":
            return GID
        if name in _UNIFORM_FUNCS:
            return UNIFORM
        if name in _NONAFFINE_FUNCS:
            return TOP
        # math builtins and anything unknown: uniform in -> uniform out
        if all(a.coef == 0 for a in args) and args:
            return UNIFORM
        return TOP

    def _inline_helper(self, fdef: lang.FuncDef, args: list) -> AV:
        if self._helper_depth >= 8:
            return TOP
        saved_env, saved_priv = self.env, self.priv
        self.env = {p.name: v for p, v in zip(fdef.params, args)}
        self.priv = {}
        self._helper_depth += 1
        try:
            self.exec_block(fdef.body[:-1])
            ret = fdef.body[-1]
            if isinstance(ret, lang.ReturnValue):
                return self.eval(ret.value)
            return TOP
        finally:
            self._helper_depth -= 1
            self.env, self.priv = saved_env, saved_priv

    # -- statements ----------------------------------------------------------
    def exec_block(self, stmts) -> None:
        for s in stmts:
            self.exec_stmt(s)

    def _store(self, target, value: AV) -> None:
        if isinstance(target, lang.Var):
            self.env[target.name] = value
            return
        if isinstance(target, lang.Index):
            idx = self.eval(target.index)
            if target.base in self.priv:
                self.priv[target.base] = _join(self.priv[target.base], value)
                return
            self._record(target.base, idx, target.line, write=True)

    def exec_stmt(self, s) -> None:
        if isinstance(s, lang.Decl):
            for name, init in s.names:
                if name in s.arrays:
                    self.priv[name] = AV.const(0)
                else:
                    self.env[name] = self.eval(init) if init is not None \
                        else AV.const(0)
            return
        if isinstance(s, lang.Assign):
            if s.target is None:
                self.eval(s.value)
                return
            rhs = self.eval(s.value)
            if s.op != "=":
                cur = self.eval(s.target)  # compound: records the read
                op = s.op[:-1]
                if op == "+":
                    rhs = _add(cur, rhs)
                elif op == "-":
                    rhs = _add(cur, _neg(rhs))
                elif op == "*":
                    rhs = _mul(cur, rhs)
                else:
                    rhs = _uniform_combine(cur, rhs)
            self._store(s.target, rhs)
            return
        if isinstance(s, lang.CrementStmt):
            cur = self.eval(s.target)
            one = AV.const(1) if s.op == "++" else AV.const(-1)
            self._store(s.target, _add(cur, one))
            return
        if isinstance(s, lang.If):
            self.eval(s.cond)
            if isinstance(s.cond, lang.Num) and s.cond.value == 1 \
                    and not s.other:
                # the parser's bare-block encoding: not a real branch
                self.exec_block(s.then)
                return
            env0 = dict(self.env)
            priv0 = dict(self.priv)
            self.cond_depth += 1
            try:
                self.exec_block(s.then)
                env1, priv1 = self.env, self.priv
                self.env, self.priv = env0, priv0
                self.exec_block(s.other)
            finally:
                self.cond_depth -= 1
            self.env = self._join_env(env1, self.env)
            self.priv = self._join_env(priv1, self.priv)
            return
        if isinstance(s, lang.For):
            if s.init is not None:
                self.exec_stmt(s.init)
            self._loop(s.cond, s.body, s.step)
            return
        if isinstance(s, lang.While):
            self._loop(s.cond, s.body, None)
            return
        if isinstance(s, lang.DoWhile):
            self._loop(s.cond, s.body, None)
            return
        if isinstance(s, lang.Return):
            self.saw_return = True
            return
        if isinstance(s, lang.ReturnValue):
            self.eval(s.value)
            return
        if isinstance(s, (lang.Break, lang.Continue)):
            return
        raise AssertionError(
            f"interp: unhandled statement {type(s).__name__}")

    @staticmethod
    def _join_env(a: dict, b: dict) -> dict:
        out = {}
        for k in set(a) | set(b):
            va, vb = a.get(k), b.get(k)
            out[k] = va if vb is None else vb if va is None else _join(va, vb)
        return out

    def _loop(self, cond, body, step) -> None:
        # silent fixpoint: iterate join/widen on the env without
        # recording accesses (transient first-iteration constants must
        # not masquerade as precise access sites)
        saved_recording = self.recording
        self.recording = False
        self.cond_depth += 1
        try:
            for round_ in range(4):
                pre_env = dict(self.env)
                pre_priv = dict(self.priv)
                self.eval(cond)
                self.exec_block(body)
                if step is not None:
                    self.exec_stmt(step)
                merge = _widen if round_ >= 2 else _join
                new_env = {
                    k: merge(pre_env[k], v) if k in pre_env else v
                    for k, v in self._join_env(pre_env, self.env).items()
                }
                new_priv = {
                    k: merge(pre_priv[k], v) if k in pre_priv else v
                    for k, v in self._join_env(pre_priv, self.priv).items()
                }
                stable = new_env == pre_env and new_priv == pre_priv
                self.env, self.priv = new_env, new_priv
                if stable:
                    break
            # one recording pass over the stabilized environment
            self.recording = saved_recording
            self.eval(cond)
            self.exec_block(body)
            if step is not None:
                self.exec_stmt(step)
        finally:
            self.recording = saved_recording
            self.cond_depth -= 1


def _suppressed_lines(source: str) -> frozenset:
    """1-based line numbers covered by a ``// ckprove: ok`` comment —
    the marked line and the line directly below it (annotation-above
    style), mirroring ckcheck's suppression reach."""
    out = set()
    for i, text in enumerate(source.splitlines(), 1):
        if "ckprove: ok" in text:
            out.add(i)
            out.add(i + 1)
    return frozenset(out)


def summarize_kernel(kernel: lang.KernelDef) -> KernelSummary:
    """Abstractly execute ``kernel`` and summarize every array access.

    Raises nothing by contract of the callers (they wrap); any lattice
    gap inside produces TOP values, not exceptions.
    """
    it = _Interp(kernel)
    it.exec_block(kernel.body)
    reads: dict[str, list] = {}
    writes: dict[str, list] = {}
    for acc in it.accesses:
        (writes if acc.is_write else reads).setdefault(
            acc.param, []).append(acc)
    return KernelSummary(
        name=kernel.name,
        array_params=it.pointer_params,
        value_params=it.value_params,
        reads=reads,
        writes=writes,
        rbw=dict(it.rbw),
        must_writes={k: tuple(v) for k, v in it.written.items()},
        suppressed=_suppressed_lines(kernel.source or ""),
        line=kernel.line,
    )
