"""ckmodel — bounded exhaustive model checking of the pure controller
state machines, against the invariants each machine declares.

Every controller bug found so far (the PR 12 probation↔quarantine
flapping, the r10 SectionScheduler starvation violation, the r8
fused-window mode-change break) was found BY HAND from a specific
reproduction, after it shipped.  The controllers are now pure,
deterministic, replay-verified functions — exactly the shape
explicit-state model checking (SPIN, TLC) was built for — so their
"never flaps / never starves / never leaks share / eventually
converges" folklore can be CHECKED properties instead.

Design rules:

1. **The real functions, no re-modeling.**  Each machine imports and
   drives the SAME pure controller functions ``ckreplay verify``
   re-executes — :func:`~..obs.drain.drain_transition` /
   :func:`~..obs.drain.apply_quarantine`,
   :class:`~..cluster.elastic.Membership` (a real instance, driven
   under the decision log's :meth:`~..obs.decisions.DecisionLog.capture`
   scratch-ring seam), :func:`~..serve.admission.admit_decision`,
   :func:`~..serve.coalescer.plan_coalesce`, and
   :func:`~..core.balance.load_balance`.  A checker that re-models the
   transition relation drifts from the code it claims to verify; this
   one cannot.
2. **Properties live next to the machines.**  Each controller module
   declares its ``MODEL_INVARIANTS`` (``(id, kind, statement)`` rows);
   the machine classes here implement exactly that list (asserted at
   construction, the ``_REPLAYERS`` discipline) — an invariant cannot
   be declared and silently unchecked, or checked and undeclared.
3. **Exhaustive under small bounds.**  Breadth-first search over the
   product state space with canonical state hashing; balancer
   trajectories (deterministic per rate/knob config) explore a
   quantized rate alphabet × knob grid to an exact fixpoint, limit
   cycle, or horizon.  Tier-1 bounds finish in seconds; the
   :data:`DEPTH_ENV` (``CK_MODEL_DEPTH``) knob deepens on the bench
   rig.
4. **Violations are decision-log traces.**  A counterexample is a
   minimal (BFS-shortest) sequence of records in the
   ``obs/decisions.py`` row schema — balance/membership steps are the
   REAL records the live emission sites produced during exploration —
   so ``ckreplay explain`` renders it, ``ckreplay verify`` re-executes
   it through the live code path, and a failing trace pins a
   regression test with no translation layer.

Exploration runs with the decision log captured into a scratch ring
and the flight recorder disabled (the replay "quiesced" discipline):
like replay-verify, it re-executes emission sites that also touch
``ck_balance_*``/``ck_member_*`` counters, so run it at sync points —
bench runs it in ``finalize_result`` after the metrics snapshot.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
from collections import deque
from contextlib import contextmanager

from ..obs.decisions import DECISIONS
from ..utils.jsonsafe import json_safe

__all__ = [
    "ModelViolation",
    "MachineBase",
    "DrainMachine",
    "ElasticMachine",
    "RouterMachine",
    "AdmissionMachine",
    "CoalesceMachine",
    "BalanceMachine",
    "BreakerMachine",
    "ShedMachine",
    "RetryMachine",
    "BlockMachine",
    "MACHINE_NAMES",
    "build_machines",
    "check_machine",
    "check_all",
    "tier1_check",
    "DEPTH_ENV",
]

#: CLI/bench machine vocabulary: ``serve`` groups the admission and
#: coalesce sub-machines (one serving tier, two pure planners);
#: ``resilience`` groups the breaker, brownout-shed and retry-budget
#: machines (``serve/resilience.py``); ``block`` explores the tile
#: autotuner's choice transition (``core/blocktuner.py``); ``router``
#: explores the serving fabric's consistent-hash placement
#: (``serve/fabric.py``).
MACHINE_NAMES = ("drain", "elastic", "serve", "balance", "resilience",
                 "block", "router")

#: Deepen-on-the-bench-rig knob: a positive integer scales the bounds
#: (balancer horizon, starvation caps, rate alphabet) beyond tier-1.
DEPTH_ENV = "CK_MODEL_DEPTH"

#: Violation-detail caps (the scan never stops early; only the
#: retained counterexamples are bounded — the verify_records rule).
#: The per-invariant cap keeps one noisy invariant from evicting every
#: other invariant's counterexamples out of the report.
MAX_VIOLATIONS = 64
PER_INVARIANT_VIOLATIONS = 4


@contextmanager
def _captured():
    """Exploration harness: decisions into a scratch ring (so machines
    can harvest the REAL records the live sites emit), flight recorder
    off (thousands of synthetic barriers must not evict a live ring)."""
    from ..obs.flight import FLIGHT

    saved = FLIGHT.enabled
    FLIGHT.enabled = False
    try:
        with DECISIONS.capture():
            yield
    finally:
        FLIGHT.enabled = saved


def _last_seq() -> int:
    snap = DECISIONS.snapshot()
    return snap[-1].seq if snap else 0


def _harvest(mark: int) -> list[dict]:
    """Records emitted since ``mark`` (the scratch ring under
    :func:`_captured`), as plain rows."""
    return [r.to_row() for r in DECISIONS.snapshot() if r.seq > mark]


class ModelViolation:
    """One invariant violation with its minimal counterexample trace.

    Duck-typed to the ckcheck baseline contract (``fingerprint`` /
    ``path`` / ``line`` / ``to_row`` / ``render``) so
    ``tools/ckcheck/baseline.py``'s ratchet applies unchanged.  The
    fingerprint hashes (machine, invariant, terminal canonical state)
    — line-free and stable across exploration-order changes."""

    def __init__(self, machine: str, invariant: str, kind: str,
                 message: str, state_doc: dict, trace: list[dict]):
        self.machine = machine
        self.invariant = invariant
        self.kind = kind
        self.message = message
        self.state_doc = state_doc
        # minimal counterexample: rows in the DecisionRecord schema,
        # seq renumbered 1..n (order preserved — verify sorts by seq)
        self.trace = [
            dict(row, seq=i) for i, row in enumerate(trace, start=1)
        ]
        self.path = f"model:{machine}"
        self.line = 0
        payload = json.dumps(
            json_safe([machine, invariant, state_doc]),
            sort_keys=True, default=str, allow_nan=False)
        self.fingerprint = hashlib.sha1(
            payload.encode()).hexdigest()[:12]

    def to_row(self) -> dict:
        return {
            "fingerprint": self.fingerprint,
            "path": self.path,
            "line": self.line,
            "machine": self.machine,
            "invariant": self.invariant,
            "kind": self.kind,
            "message": self.message,
            "state": self.state_doc,
            "trace_len": len(self.trace),
        }

    def render(self) -> str:
        return (f"[{self.fingerprint}] {self.machine}: "
                f"{self.invariant} ({self.kind}) VIOLATED — "
                f"{self.message} (trace: {len(self.trace)} step(s))")


class MachineBase:
    """One bounded controller machine.

    Graph machines implement ``initial_states`` / ``actions`` /
    ``check_state`` / ``check_action`` (+ optional ``check_liveness``)
    over hashable canonical states; trajectory machines (the balancer)
    override :meth:`explore` wholesale.  ``invariants`` is the owning
    module's ``MODEL_INVARIANTS``; the constructor asserts the
    implemented check ids cover it exactly."""

    name = "?"
    invariants: tuple = ()
    #: invariant ids the implementation checks — must equal the
    #: declared list (asserted in __init__)
    checks: tuple = ()

    def __init__(self):
        declared = {row[0] for row in self.invariants}
        implemented = set(self.checks)
        assert declared == implemented, (
            f"{self.name}: declared MODEL_INVARIANTS "
            f"{sorted(declared)} != implemented checks "
            f"{sorted(implemented)}")
        self._exercised: dict[str, int] = {row[0]: 0 for row in
                                           self.invariants}

    # -- graph-machine protocol ----------------------------------------------
    def initial_states(self) -> list:
        raise NotImplementedError

    def actions(self, state) -> list:
        """``[(label, rows, next_state), ...]`` — rows are decision-
        record dicts for this edge (the counterexample vocabulary)."""
        raise NotImplementedError

    def canon(self, state):
        return state

    def state_doc(self, state) -> dict:
        return {"state": repr(state)}

    def check_state(self, state) -> list:
        """``[(invariant_id, message), ...]`` violated AT ``state``."""
        return []

    def check_action(self, state, label, rows, nxt) -> list:
        return []

    def check_liveness(self, state) -> list:
        """``[(invariant_id, message, extra_rows), ...]`` — bounded
        eventually-properties probed from ``state`` under a fair
        schedule; ``extra_rows`` extend the counterexample past the
        reachable prefix."""
        return []

    def _hit(self, inv_id: str) -> None:
        self._exercised[inv_id] += 1

    # -- the explorer ---------------------------------------------------------
    def explore(self, max_depth: int = 256,
                max_states: int = 500_000) -> dict:
        """Bounded exhaustive BFS with canonical state hashing.
        Returns the machine report (states/transitions/violations/
        exercised counts).  The scan is never cut short by violations;
        only retained counterexamples are capped."""
        violations: list[ModelViolation] = []
        seen: dict = {}
        parents: dict = {}  # canon -> (parent_canon, rows)
        depth_of: dict = {}
        queue: deque = deque()
        transitions = 0
        truncated = False

        def _trace(c) -> list[dict]:
            rows: list[dict] = []
            while c is not None:
                ent = parents.get(c)
                if ent is None:
                    break
                c, step_rows = ent
                rows[:0] = step_rows
            return rows

        vio_counts: dict[str, int] = {}

        def _violate(inv_id, msg, c, state, extra_rows=()):
            self._hit(inv_id)
            if len(violations) >= MAX_VIOLATIONS or \
                    vio_counts.get(inv_id, 0) >= PER_INVARIANT_VIOLATIONS:
                return
            vio_counts[inv_id] = vio_counts.get(inv_id, 0) + 1
            kind = next(k for i, k, _d in self.invariants if i == inv_id)
            violations.append(ModelViolation(
                self.name, inv_id, kind, msg, self.state_doc(state),
                _trace(c) + list(extra_rows)))

        with _captured():
            for s0 in self.initial_states():
                c0 = self.canon(s0)
                if c0 in seen:
                    continue
                seen[c0] = s0
                depth_of[c0] = 0
                queue.append(c0)
            while queue:
                c = queue.popleft()
                state = seen[c]
                for inv_id, msg in self.check_state(state):
                    _violate(inv_id, msg, c, state)
                for inv_id, msg, extra in self.check_liveness(state):
                    _violate(inv_id, msg, c, state, extra)
                if depth_of[c] >= max_depth:
                    truncated = True
                    continue
                for label, rows, nxt in self.actions(state):
                    transitions += 1
                    for inv_id, msg in self.check_action(
                            state, label, rows, nxt):
                        _violate(inv_id, msg, c, state, rows)
                    cn = self.canon(nxt)
                    if cn in seen:
                        continue
                    if len(seen) >= max_states:
                        truncated = True
                        continue
                    seen[cn] = nxt
                    parents[cn] = (c, rows)
                    depth_of[cn] = depth_of[c] + 1
                    queue.append(cn)
        return {
            "machine": self.name,
            "states_explored": len(seen),
            "transitions": transitions,
            "max_depth_reached": max(depth_of.values(), default=0),
            "truncated": truncated,
            "violations": violations,
            "invariants": {
                i: {"kind": k, "statement": d,
                    "exercised": self._exercised[i]}
                for i, k, d in self.invariants
            },
        }


# ---------------------------------------------------------------------------
# drain: verdict sequences × hold/grace/confirm knobs (obs/drain.py)
# ---------------------------------------------------------------------------

class DrainMachine(MachineBase):
    """Product of :func:`drain_transition` (per-lane state × every
    verdict assignment per barrier) and :func:`apply_quarantine` (the
    share mask checked at every reachable state).

    ``transition``/``masker`` are injectable seams so the test suite's
    deliberately-broken fixture machines produce counterexamples for
    every declared invariant."""

    name = "drain"
    checks = ("availability-floor", "share-conservation",
              "quarantine-masked", "action-visibility",
              "eventual-readmission", "no-silent-flap")

    VERDICTS = ("ok", "suspect", "degraded")

    def __init__(self, lanes: int = 3, hold_barriers: int = 2,
                 confirm_clear: int = 2, probe_grace: int = 2,
                 step: int = 4, transition=None, masker=None):
        from ..obs import drain as D

        self.invariants = D.MODEL_INVARIANTS
        super().__init__()
        self.D = D
        self.lanes = int(lanes)
        self.hold_barriers = int(hold_barriers)
        self.confirm_clear = int(confirm_clear)
        self.probe_grace = int(probe_grace)
        self.step = int(step)
        # a realistic raw table: step-quantized equal split (the shape
        # Cores._ranges_for masks — non-step tables are unreachable)
        self.raw = [2 * self.step] * self.lanes
        self.transition = transition or D.drain_transition
        self.masker = masker or D.apply_quarantine

    def initial_states(self):
        return [tuple((self.D.LANE_ACTIVE, 0, 0)
                      for _ in range(self.lanes))]

    def canon(self, state):
        # quotient dead variables: hold/streak are overwritten on every
        # entry into the states that read them, so an active lane's
        # residues cannot affect any future transition
        out = []
        for st, hold, streak in state:
            if st == self.D.LANE_ACTIVE:
                out.append((st, 0, 0))
            elif st == self.D.LANE_QUARANTINED:
                out.append((st, hold, 0))
            else:
                out.append((st, hold, streak))
        return tuple(out)

    def state_doc(self, state):
        return {
            "lanes": {
                str(i): {"state": st, "hold": hold, "streak": streak}
                for i, (st, hold, streak) in enumerate(state)
            },
        }

    # -- the transition -------------------------------------------------------
    def _dicts(self, state):
        states = {str(i): st for i, (st, _h, _s) in enumerate(state)}
        hold = {str(i): h for i, (_st, h, _s) in enumerate(state)}
        streak = {str(i): s for i, (_st, _h, s) in enumerate(state)}
        return states, hold, streak

    def _step(self, state, verdicts: dict):
        """One barrier under ``verdicts``: run the transition, build
        the decision rows the live ``DrainController.evaluate`` site
        records (same schema; a pure-tick barrier gets one row too so
        every counterexample edge replays)."""
        states, hold, streak = self._dicts(state)
        inputs = {
            "verdicts": dict(verdicts), "states": dict(states),
            "hold": dict(hold), "clear_streak": dict(streak),
            "hold_barriers": self.hold_barriers,
            "confirm_clear": self.confirm_clear,
            "probe_grace": self.probe_grace,
        }
        res = self.transition(
            verdicts, states, hold, streak, self.hold_barriers,
            self.confirm_clear, probe_grace=self.probe_grace)
        rows = []
        kinds = (["drain-apply"] if res["drained"] else []) + \
            (["readmit"] if res["readmitted"] else [])
        for kind in (kinds or ["drain-apply"]):
            rows.append({"kind": kind, "inputs": dict(inputs),
                         "outputs": res})
        nxt = tuple(
            (res["states"].get(str(i), self.D.LANE_ACTIVE),
             int(res["hold"].get(str(i), 0)),
             int(res["clear_streak"].get(str(i), 0)))
            for i in range(self.lanes))
        return res, rows, nxt

    def actions(self, state):
        out = []
        n = self.lanes
        combo = [0] * n
        while True:
            verdicts = {str(i): self.VERDICTS[combo[i]]
                        for i in range(n)}
            _res, rows, nxt = self._step(state, verdicts)
            out.append((f"verdicts={','.join(verdicts.values())}",
                        rows, nxt))
            i = 0
            while i < n:
                combo[i] += 1
                if combo[i] < len(self.VERDICTS):
                    break
                combo[i] = 0
                i += 1
            if i == n:
                return out

    # -- invariants -----------------------------------------------------------
    def _sets(self, state):
        drained = {i for i, (st, _h, _s) in enumerate(state)
                   if st == self.D.LANE_QUARANTINED}
        probation = {i for i, (st, _h, _s) in enumerate(state)
                     if st == self.D.LANE_PROBATION}
        return drained, probation

    def check_state(self, state):
        bad = []
        drained, probation = self._sets(state)
        self._hit("availability-floor")
        if len(drained) + len(probation) >= self.lanes:
            bad.append((
                "availability-floor",
                f"no active lane left: {len(drained)} quarantined + "
                f"{len(probation)} probation of {self.lanes}"))
        masked = self.masker(list(self.raw), self.step, drained,
                             probation)
        self._hit("share-conservation")
        if sum(masked) != sum(self.raw):
            bad.append((
                "share-conservation",
                f"masked table {masked} sums to {sum(masked)}, raw "
                f"total is {sum(self.raw)} (mask leaked share)"))
        # the mask contract only binds while an active lane exists (the
        # no-active state is itself an availability-floor violation)
        if len(drained) + len(probation) < self.lanes:
            self._hit("quarantine-masked")
            for i in drained:
                if masked[i] != 0:
                    bad.append((
                        "quarantine-masked",
                        f"quarantined lane {i} holds {masked[i]} "
                        "items, expected 0"))
            for i in probation:
                if masked[i] != self.step:
                    bad.append((
                        "quarantine-masked",
                        f"probation lane {i} holds {masked[i]} items, "
                        f"expected exactly one step ({self.step})"))
        return bad

    def check_action(self, state, label, rows, nxt):
        bad = []
        self._hit("action-visibility")
        res = rows[0]["outputs"]
        acted = set(res["drained"]) | set(res["readmitted"]) | \
            set(res["probed"])
        for i in range(self.lanes):
            if state[i][0] != nxt[i][0] and str(i) not in acted:
                bad.append((
                    "action-visibility",
                    f"lane {i} moved {state[i][0]} -> {nxt[i][0]} "
                    f"under {label} without appearing in any action "
                    "list (silent transition)"))
        return bad

    def check_liveness(self, state):
        """Fairness schedule: the lane genuinely recovered — drive
        all-ok verdicts and demand (a) full readmission within
        hold + confirm + 1 barriers, (b) zero drain actions on the
        way (an all-ok barrier that drains is silent flapping)."""
        if all(st == self.D.LANE_ACTIVE for st, _h, _s in state):
            return []
        bad = []
        # the probe runs EXACTLY the declared bound — any slack here
        # would let a regression that slips one extra barrier past the
        # MODEL_INVARIANTS statement go unflagged (worst reachable
        # chain today: hold + confirm barriers, strictly inside it)
        bound = self.hold_barriers + self.confirm_clear + 1
        ok = {str(i): "ok" for i in range(self.lanes)}
        cur = state
        extra: list[dict] = []
        drained_on_ok = None
        for _ in range(bound):
            res, rows, cur = self._step(cur, ok)
            extra.extend(rows)
            if res["drained"] and drained_on_ok is None:
                drained_on_ok = list(res["drained"])
            if all(st == self.D.LANE_ACTIVE for st, _h, _s in cur):
                break
        self._hit("no-silent-flap")
        if drained_on_ok is not None:
            bad.append((
                "no-silent-flap",
                f"lanes {drained_on_ok} were re-drained on an all-ok "
                "barrier (flap without degraded evidence)", extra))
        self._hit("eventual-readmission")
        stuck = [i for i, (st, _h, _s) in enumerate(cur)
                 if st != self.D.LANE_ACTIVE]
        if stuck:
            bad.append((
                "eventual-readmission",
                f"lanes {stuck} still not active after {bound} "
                f"all-ok barriers (the declared bound: hold "
                f"{self.hold_barriers} + confirm {self.confirm_clear} "
                "+ 1)", extra))
        return bad


# ---------------------------------------------------------------------------
# elastic: leave/join/timeout interleavings × epoch (cluster/elastic.py)
# ---------------------------------------------------------------------------

class ElasticMachine(MachineBase):
    """Every roster→roster reconciliation over a small member alphabet
    (ids chosen to exercise the length-then-lex order), driving a REAL
    :class:`~..cluster.elastic.Membership` under the decision log's
    scratch-ring capture — the checked rows are the records the live
    site emitted, not a re-model."""

    name = "elastic"
    checks = ("epoch-monotone", "resplit-conservation",
              "resplit-quantized", "sync-converges",
              "deterministic-order")

    def __init__(self, member_ids=("p0", "p2", "p10"),
                 steps=(2, 3), total: int = 12, membership_cls=None):
        from ..cluster import elastic as E

        self.invariants = E.MODEL_INVARIANTS
        super().__init__()
        self.E = E
        self.member_ids = tuple(member_ids)
        self.steps = tuple(int(s) for s in steps)
        self.total = int(total)
        self.membership_cls = membership_cls or E.Membership

    def _rosters(self):
        out = []

        def rec(i, cur):
            if i == len(self.member_ids):
                if cur:
                    out.append(tuple(sorted(cur.items())))
                return
            rec(i + 1, cur)
            for s in self.steps:
                nxt = dict(cur)
                nxt[self.member_ids[i]] = s
                rec(i + 1, nxt)

        rec(0, {})
        return out

    def initial_states(self):
        return self._rosters()

    def state_doc(self, state):
        return {"roster": {m: s for m, s in state}}

    def _drive(self, current: dict, target: dict):
        """establish(current) → sync(target): the captured rows and
        the post-sync snapshot."""
        m = self.membership_cls()
        m.establish(dict(current))
        mark = _last_seq()
        m.sync(dict(target), total=self.total)
        return _harvest(mark), m.snapshot()

    def actions(self, state):
        current = dict(state)
        out = []
        for target_t in self._rosters():
            target = dict(target_t)
            if target == current:
                continue
            rows, _snap = self._drive(current, target)
            out.append((f"sync->{target}", rows, target_t))
        return out

    def check_action(self, state, label, rows, nxt):
        bad = []
        current, target = dict(state), dict(nxt)
        # sync-converges: re-drive (BFS may have harvested rows from a
        # prior expansion) and compare the realized roster
        rows2, snap = self._drive(current, target)
        self._hit("sync-converges")
        if snap["members"] != target:
            bad.append((
                "sync-converges",
                f"sync({target}) from {current} left the roster at "
                f"{snap['members']}"))
        seen_join = False
        for r in rows:
            if r["kind"] == "member-join":
                seen_join = True
            elif r["kind"] == "member-leave" and seen_join:
                bad.append((
                    "sync-converges",
                    "a departure was recorded AFTER an arrival — the "
                    "leaves-then-joins order is the re-split safety "
                    "contract"))
                break
        # deterministic-order: the same diff replayed twice must
        # record the identical transition sequence
        self._hit("deterministic-order")
        sig = [(r["kind"], r["inputs"].get("member")) for r in rows]
        sig2 = [(r["kind"], r["inputs"].get("member")) for r in rows2]
        if sig != sig2:
            bad.append((
                "deterministic-order",
                f"two drives of the same diff recorded {sig} then "
                f"{sig2}"))
        # epoch-monotone: +1 per transition, chained
        self._hit("epoch-monotone")
        prev_after = None
        for r in rows:
            before = r["inputs"].get("epoch_before")
            after = r["outputs"].get("epoch_after")
            if after != (before or 0) + 1:
                bad.append((
                    "epoch-monotone",
                    f"{r['kind']}({r['inputs'].get('member')}) moved "
                    f"epoch {before} -> {after} (must bump by exactly "
                    "one)"))
            if prev_after is not None and before != prev_after:
                bad.append((
                    "epoch-monotone",
                    f"epoch chain broke: record started at {before} "
                    f"after the previous ended at {prev_after}"))
            prev_after = after
        # resplit conservation + quantization on every record that
        # carried a total
        self._hit("resplit-conservation")
        self._hit("resplit-quantized")
        for r in rows:
            ranges = r["outputs"].get("ranges")
            if ranges is None:
                continue
            lcm = int(r["outputs"].get("lcm", 1))
            if sum(ranges) != self.total:
                bad.append((
                    "resplit-conservation",
                    f"{r['kind']} re-split {ranges} sums to "
                    f"{sum(ranges)}, total is {self.total}"))
            for i, v in enumerate(ranges):
                if v < 0 or (i > 0 and v % lcm != 0):
                    bad.append((
                        "resplit-quantized",
                        f"{r['kind']} member {i} share {v} is not a "
                        f"non-negative LCM({lcm}) multiple"))
        return bad


# ---------------------------------------------------------------------------
# router: roster × health-view interleavings (serve/fabric.py)
# ---------------------------------------------------------------------------

class RouterMachine(MachineBase):
    """Every (roster subset × unhealthy subset) over a small member
    alphabet, driving a REAL :class:`~..serve.fabric.ShardRouter` over
    a real :class:`~..cluster.elastic.Membership` for a fixed key set
    at every transition — the checked rows are the ``route`` records
    the live site emitted, not a re-model.

    ``route`` is the injectable placement seam (the pure
    ``route_decision`` by default) so the test suite's deliberately-
    broken fixtures produce counterexamples for every declared
    invariant: a flip-flopping fn breaks placement-deterministic, a
    modulo (non-consistent) hash breaks minimal-reshuffle, a fixed
    off-roster target breaks routes-to-members, a silent diverter
    breaks diversion-named."""

    name = "router"
    checks = ("placement-deterministic", "minimal-reshuffle",
              "routes-to-members", "diversion-named")

    #: the fixed (tenant, key) probe set — few enough that every edge
    #: stays cheap, spread enough that a 3-member ring places them on
    #: more than one owner
    KEYS = (("tA", "k1"), ("tA", "k2"), ("tB", "k1"), ("tB", "k3"),
            ("tC", "k4"))

    def __init__(self, member_ids=("p0", "p2", "p10"), route=None,
                 keys=None):
        from ..serve import fabric as F

        self.invariants = F.MODEL_INVARIANTS
        super().__init__()
        self.F = F
        self.member_ids = tuple(member_ids)
        self.route_fn = route  # None = ShardRouter's real pure default
        if keys is not None:
            self.KEYS = tuple(keys)

    def initial_states(self):
        # every non-empty roster, all-healthy (empty rosters and sick
        # shards are reached through leave/mark edges)
        ids = self.member_ids
        out = []
        for mask in range(1, 1 << len(ids)):
            roster = tuple(
                ids[i] for i in range(len(ids)) if mask >> i & 1)
            out.append((roster, ()))
        return out

    def state_doc(self, state):
        return {"roster": list(state[0]), "unhealthy": list(state[1])}

    def _drive(self, roster, unhealthy):
        """Route every probe key through a real router at this
        roster/health view; returns ``(outs, rows)`` — the verdicts by
        key and the harvested ``route`` records."""
        from ..cluster.elastic import Membership

        m = Membership()
        m.establish({mm: 1 for mm in roster})
        router = self.F.ShardRouter(m, route=self.route_fn)
        for u in unhealthy:
            router.mark(u)
        mark = _last_seq()
        outs = {}
        for tenant, key in self.KEYS:
            outs[(tenant, key)] = router.route(tenant, key)
        return outs, _harvest(mark)

    def actions(self, state):
        roster, unhealthy = state
        rset, uset = set(roster), set(unhealthy)
        edges = []
        for m in self.member_ids:
            if m in rset:
                edges.append((
                    f"leave:{m}",
                    (tuple(x for x in roster if x != m),
                     tuple(x for x in unhealthy if x != m))))
                if m not in uset:
                    edges.append((
                        f"mark:{m}",
                        (roster, tuple(sorted(uset | {m})))))
            else:
                edges.append((f"join:{m}",
                              (tuple(sorted(rset | {m})), unhealthy)))
            if m in uset:
                edges.append((
                    f"clear:{m}",
                    (roster, tuple(x for x in unhealthy if x != m))))
        out = []
        for label, nxt in edges:
            _outs, rows = self._drive(*nxt)
            out.append((label, rows, nxt))
        return out

    def check_action(self, state, label, rows, nxt):
        bad = []
        F = self.F
        route_fn = self.route_fn or F.route_decision
        outs2, rows2 = self._drive(*nxt)
        # placement-deterministic: the same (roster, health view)
        # driven twice records bit-identical verdicts, and every
        # recorded output re-derives from its recorded inputs (the
        # ckreplay contract, checked in the explorer)
        self._hit("placement-deterministic")
        sig = [(r["inputs"]["tenant"], r["inputs"]["key"],
                r["outputs"]) for r in rows]
        sig2 = [(r["inputs"]["tenant"], r["inputs"]["key"],
                 r["outputs"]) for r in rows2]
        if sig != sig2:
            bad.append((
                "placement-deterministic",
                f"two drives of {nxt} recorded different placements"))
        for r in rows:
            inp, outp = r["inputs"], r["outputs"]
            re = route_fn(inp["tenant"], inp["key"],
                          list(inp["members"]),
                          tuple(inp["unhealthy"]),
                          int(inp["epoch"]))
            if dict(re) != dict(outp):
                bad.append((
                    "placement-deterministic",
                    f"route({inp['tenant']},{inp['key']}) recorded "
                    f"{outp} but re-derives to {re}"))
        # routes-to-members: never a non-member target; a refusal only
        # with no healthy member, and then with the named reason
        self._hit("routes-to-members")
        for r in rows:
            inp, o = r["inputs"], r["outputs"]
            members = set(inp["members"])
            healthy = members - set(inp["unhealthy"])
            shard = o.get("shard")
            if shard is None:
                if o.get("reason") != F.REJECT_SHARD:
                    bad.append((
                        "routes-to-members",
                        f"refusal without the named {F.REJECT_SHARD} "
                        f"reason (got {o.get('reason')!r})"))
                if healthy:
                    bad.append((
                        "routes-to-members",
                        f"refused while healthy members {sorted(healthy)} "
                        "existed"))
            elif shard not in members:
                bad.append((
                    "routes-to-members",
                    f"routed to {shard!r}, not in the epoch's roster "
                    f"{sorted(members)}"))
        # diversion-named: off-owner placement is flagged with hops,
        # and a healthy owner is never diverted away from
        self._hit("diversion-named")
        for r in rows:
            inp, o = r["inputs"], r["outputs"]
            if o.get("shard") is None:
                continue
            if o["shard"] != o.get("owner"):
                if not o.get("diverted") or int(o.get("hops") or 0) < 1:
                    bad.append((
                        "diversion-named",
                        f"route landed on {o['shard']} away from owner "
                        f"{o.get('owner')} without the diverted flag / "
                        "hop count — a silent diversion"))
                if o.get("owner") not in set(inp["unhealthy"]):
                    bad.append((
                        "diversion-named",
                        f"diverted away from HEALTHY owner "
                        f"{o.get('owner')}"))
            elif o.get("diverted"):
                bad.append((
                    "diversion-named",
                    "owner placement flagged as diverted"))
        # minimal-reshuffle on membership edges: a key's ring OWNER
        # (health-blind) may move only when the departed member owned
        # it (leave) or the joiner captured it (join)
        kind, _, member = label.partition(":")
        if kind in ("leave", "join"):
            self._hit("minimal-reshuffle")
            before, _r = self._drive(*state)
            for k in self.KEYS:
                ob = before[k].get("owner")
                oa = outs2[k].get("owner")
                if ob == oa:
                    continue
                if kind == "leave" and ob != member:
                    bad.append((
                        "minimal-reshuffle",
                        f"leave({member}) moved key {k} owned by "
                        f"{ob} (to {oa}) — only the departed member's "
                        "keys may move"))
                if kind == "join" and oa != member:
                    bad.append((
                        "minimal-reshuffle",
                        f"join({member}) moved key {k} from {ob} to "
                        f"{oa} — only keys the joiner captures may "
                        "move"))
        return bad


# ---------------------------------------------------------------------------
# serve: admission (tenants × queue × health) — serve/admission.py
# ---------------------------------------------------------------------------

class AdmissionMachine(MachineBase):
    """Product of per-tenant in-flight counts × queue depth × health /
    breaker / brownout flips, driving
    :func:`~..serve.admission.admit_decision` at every submit with the
    frontend's own accounting (admit → in-flight+1 and queue+1;
    dispatch → queue−1; complete → in-flight−1)."""

    name = "serve/admission"
    checks = ("quota-exact", "queue-bounded", "reject-order",
              "retry-hint", "admit-iff")

    EST_BATCH = (0.0, 0.1)  # 0.0 exercises the retry-after floor

    def __init__(self, tenants=("a", "b", "c"), quota: int = 3,
                 max_queue_depth: int = 4, decide=None):
        from ..serve import admission as A

        self.invariants = A.MODEL_INVARIANTS
        super().__init__()
        self.A = A
        self.tenants = tuple(tenants)
        self.quota = int(quota)
        self.shed_quota = A.brownout_share(quota)
        self.max_queue_depth = int(max_queue_depth)
        self.decide = decide or A.admit_decision

    def initial_states(self):
        return [(tuple(0 for _ in self.tenants), 0, True, False, False)]

    def state_doc(self, state):
        inflight, queue, healthy, breaker, brownout = state
        return {
            "inflight": {t: n for t, n in zip(self.tenants, inflight)},
            "queue_depth": queue,
            "healthy": healthy,
            "breaker_open": breaker,
            "brownout": brownout,
        }

    def _submit(self, state, ti: int, est: float, unsafe: bool):
        inflight, queue, healthy, breaker, brownout = state
        dec = self.decide(
            tenant_inflight=inflight[ti], quota=self.quota,
            queue_depth=queue, max_queue_depth=self.max_queue_depth,
            healthy=healthy, est_batch_s=est, kernel_unsafe=unsafe,
            kernel_finding="scatter-write" if unsafe else None,
            breaker_open=breaker, breaker_retry_after_s=0.25,
            brownout=brownout, shed_quota=self.shed_quota, priority=1)
        row = {"kind": "admission", "inputs": {
            "tenant": self.tenants[ti],
            "tenant_inflight": inflight[ti],
            "quota": self.quota,
            "queue_depth": queue,
            "max_queue_depth": self.max_queue_depth,
            "healthy": healthy,
            "est_batch_s": est,
            "kernel_unsafe": unsafe,
            "kernel_finding": "scatter-write" if unsafe else None,
            "breaker_open": breaker,
            "breaker_retry_after_s": 0.25,
            "brownout": brownout,
            "shed_quota": self.shed_quota,
            "priority": 1,
        }, "outputs": dict(dec)}
        if dec.get("admit"):
            inflight = tuple(
                n + 1 if i == ti else n for i, n in enumerate(inflight))
            queue += 1
        return dec, row, (inflight, queue, healthy, breaker, brownout)

    def actions(self, state):
        inflight, queue, healthy, breaker, brownout = state
        out = []
        for ti in range(len(self.tenants)):
            for est in self.EST_BATCH:
                dec, row, nxt = self._submit(state, ti, est, False)
                out.append((f"submit({self.tenants[ti]},est={est})",
                            [row], nxt))
        # a kernel-verifier-refuted job (strict gate at the frontend)
        dec, row, nxt = self._submit(state, 0, 0.1, True)
        out.append(("submit(a,unsafe)", [row], nxt))
        if queue > 0:
            out.append(("dispatch", [],
                        (inflight, queue - 1, healthy, breaker,
                         brownout)))
        for ti, n in enumerate(inflight):
            if n > 0:
                nf = tuple(v - 1 if i == ti else v
                           for i, v in enumerate(inflight))
                out.append((f"complete({self.tenants[ti]})", [],
                            (nf, queue, healthy, breaker, brownout)))
        out.append(("health-flip", [],
                    (inflight, queue, not healthy, breaker, brownout)))
        out.append(("breaker-flip", [],
                    (inflight, queue, healthy, not breaker, brownout)))
        out.append(("brownout-flip", [],
                    (inflight, queue, healthy, breaker, not brownout)))
        return out

    def check_state(self, state):
        inflight, queue, _healthy, _breaker, _brownout = state
        bad = []
        self._hit("quota-exact")
        for t, n in zip(self.tenants, inflight):
            if n > self.quota:
                bad.append((
                    "quota-exact",
                    f"tenant {t} reached {n} in-flight with quota "
                    f"{self.quota}"))
        self._hit("queue-bounded")
        if queue > self.max_queue_depth:
            bad.append((
                "queue-bounded",
                f"queue depth {queue} exceeds the bound "
                f"{self.max_queue_depth}"))
        return bad

    def check_action(self, state, label, rows, nxt):
        if not rows:
            return []
        bad = []
        inp, out = rows[0]["inputs"], rows[0]["outputs"]
        unsafe, healthy = inp["kernel_unsafe"], inp["healthy"]
        queue_full = inp["queue_depth"] >= inp["max_queue_depth"]
        over_quota = inp["tenant_inflight"] >= inp["quota"]
        shed = (inp["brownout"]
                and inp["tenant_inflight"] >= inp["shed_quota"])
        expected = (
            self.A.REJECT_KERNEL if unsafe else
            self.A.REJECT_HEALTH if not healthy else
            self.A.REJECT_BREAKER if inp["breaker_open"] else
            self.A.REJECT_QUEUE if queue_full else
            self.A.REJECT_BROWNOUT if shed else
            self.A.REJECT_QUOTA if over_quota else None)
        self._hit("admit-iff")
        if out.get("admit") != (expected is None):
            bad.append((
                "admit-iff",
                f"{label}: admit={out.get('admit')} but the gates say "
                f"{'admit' if expected is None else 'reject'}"))
        self._hit("reject-order")
        if out.get("reason") != expected:
            bad.append((
                "reject-order",
                f"{label}: reason {out.get('reason')!r}, first failing "
                f"gate is {expected!r}"))
        self._hit("retry-hint")
        retry = out.get("retry_after_s")
        if out.get("admit"):
            if retry is not None:
                bad.append(("retry-hint",
                            f"{label}: admitted with retry hint {retry}"))
        elif out.get("reason") == self.A.REJECT_KERNEL:
            if retry != 0.0:
                bad.append((
                    "retry-hint",
                    f"{label}: kernel-unsafe retry hint {retry}, must "
                    "be exactly 0.0"))
        elif retry is None or retry < self.A._RETRY_FLOOR_S:
            bad.append((
                "retry-hint",
                f"{label}: rejection carries retry hint {retry} below "
                f"the floor {self.A._RETRY_FLOOR_S}"))
        return bad


# ---------------------------------------------------------------------------
# serve: coalesce (groups × deadlines × starvation) — serve/coalescer.py
# ---------------------------------------------------------------------------

class CoalesceMachine(MachineBase):
    """Every arrival/desertion/deadline interleaving over a small
    group alphabet, with the dispatcher's own starvation bookkeeping
    (``ServeFrontend._dispatch_cycle``: picked → streak 0, unpicked
    pending → +1, empty group leaves the table), checked against the
    capacity-aware starvation bound."""

    name = "serve/coalesce"
    checks = ("promoted-are-starved", "plan-complete",
              "plan-deterministic", "bounded-starvation")

    #: fixed per-key ages/deadlines: the EDF and age tie-breaks are
    #: exercised without making time part of the state
    AGES = {"ga": 3.0, "gb": 2.0, "gc": 1.0}
    DEADLINES = {"ga": 2.5, "gb": 0.5, "gc": 1.5}

    def __init__(self, keys=("ga", "gb", "gc"), max_picks: int = 1,
                 starve_cap_extra: int = 2, plan=None):
        from ..serve import coalescer as C

        self.invariants = C.MODEL_INVARIANTS
        super().__init__()
        self.C = C
        self.keys = tuple(keys)
        self.max_picks = int(max_picks)
        self.plan = plan or C.plan_coalesce
        # one CLI machine runs several CoalesceMachine configs —
        # per-instance names keep their reports from colliding in
        # check_machine's sub_machines map
        self.name = f"serve/coalesce(mp={self.max_picks})"
        # the declared capacity-aware bound (see MODEL_INVARIANTS)
        g = len(self.keys)
        self.bound = (C.STARVE_ROUNDS if self.max_picks >= g - 1
                      else C.STARVE_ROUNDS + (g - 1))
        # explore a little past the bound so a broken planner shows a
        # growing streak instead of an unbounded frontier
        self.starve_cap = self.bound + int(starve_cap_extra)
        # round_idx only matters modulo the streak size; lcm(1..g)
        self.round_mod = 1
        for k in range(1, g + 1):
            self.round_mod = self.round_mod * k // math.gcd(
                self.round_mod, k)

    def initial_states(self):
        # (per-group starved or None when absent, round)
        return [(tuple(0 for _ in self.keys), 0)]

    def canon(self, state):
        starved, rnd = state
        return starved, rnd % self.round_mod

    def state_doc(self, state):
        starved, rnd = state
        return {
            "groups": {k: ("absent" if s is None else {"starved": s})
                       for k, s in zip(self.keys, starved)},
            "round": rnd % self.round_mod,
            "max_picks": self.max_picks,
        }

    def _summary(self, starved, deadlines: bool):
        rows = []
        for k, s in zip(self.keys, starved):
            if s is None:
                continue
            rows.append({
                "key": k, "pending": 1,
                "deadline_in_s": self.DEADLINES[k] if deadlines else None,
                "oldest_age_s": self.AGES[k],
                "starved_rounds": s,
            })
        rows.sort(key=lambda r: r["key"])
        return rows

    def actions(self, state):
        starved, rnd = state
        rnd = rnd % self.round_mod
        out = []
        n = len(self.keys)
        for mask in range(1, 1 << n):
            # presence pattern this cycle: arrivals start at streak 0,
            # deserters leave the table (streak forgotten — the
            # frontend's empty-group rule)
            present = tuple(
                (starved[i] if starved[i] is not None else 0)
                if mask & (1 << i) else None
                for i in range(n))
            for deadlines in (False, True):
                summary = self._summary(present, deadlines)
                plan = self.plan(summary, rnd, self.max_picks)
                row = {"kind": "coalesce", "inputs": {
                    "groups": summary, "round": rnd,
                    "max_picks": self.max_picks,
                }, "outputs": dict(plan)}
                picked = set(plan.get("picked") or ())
                nxt = tuple(
                    None if present[i] is None else
                    (0 if self.keys[i] in picked
                     else min(present[i] + 1, self.starve_cap + 1))
                    for i in range(n))
                out.append((
                    f"cycle(mask={mask:03b},edf={deadlines})",
                    [row], (nxt, (rnd + 1) % self.round_mod)))
        return out

    def check_state(self, state):
        starved, _rnd = state
        bad = []
        self._hit("bounded-starvation")
        for k, s in zip(self.keys, starved):
            if s is not None and s > self.bound:
                bad.append((
                    "bounded-starvation",
                    f"group {k} starved {s} consecutive cycles "
                    f"(bound {self.bound} at max_picks="
                    f"{self.max_picks} over {len(self.keys)} groups)"))
        return bad

    def check_action(self, state, label, rows, nxt):
        bad = []
        inp, out = rows[0]["inputs"], rows[0]["outputs"]
        pending_keys = {r["key"] for r in inp["groups"]}
        order = list(out.get("order") or ())
        picked = list(out.get("picked") or ())
        promoted = list(out.get("promoted") or ())
        self._hit("plan-complete")
        if sorted(order) != sorted(pending_keys):
            bad.append((
                "plan-complete",
                f"{label}: order {order} is not a permutation of the "
                f"pending groups {sorted(pending_keys)}"))
        want = order[:self.max_picks] if self.max_picks > 0 else order
        if picked != want:
            bad.append((
                "plan-complete",
                f"{label}: picked {picked} is not the max_picks prefix "
                f"{want}"))
        self._hit("promoted-are-starved")
        streak = {r["key"] for r in inp["groups"]
                  if r["starved_rounds"] >= self.C.STARVE_ROUNDS}
        extra = [k for k in promoted if k not in streak]
        if extra:
            bad.append((
                "promoted-are-starved",
                f"{label}: promoted {extra} without a "
                f"{self.C.STARVE_ROUNDS}-round starve streak"))
        self._hit("plan-deterministic")
        again = self.plan(
            [dict(r) for r in inp["groups"]], inp["round"],
            inp["max_picks"])
        if again != out:
            bad.append((
                "plan-deterministic",
                f"{label}: replanning the same snapshot changed the "
                "plan"))
        return bad


# ---------------------------------------------------------------------------
# balance: freeze/jump over rate-consistent trajectories (core/balance.py)
# ---------------------------------------------------------------------------

class BalanceMachine(MachineBase):
    """Deterministic :func:`~..core.balance.load_balance` trajectories
    over a quantized per-item rate alphabet × knob grid, each run to an
    exact fixpoint, a limit cycle (a "converges" violation — revisiting
    a non-fixpoint canonical state in a deterministic system is a
    proof of divergence), or the horizon.  Rate-consistent feedback is
    the whatif simulator's own model: ``bench_i = rate_i ·
    max(range_i, step)``.  Records are the REAL ``load-balance``
    decisions the live emission site produced under capture — a
    counterexample trace renders in ``ckreplay explain`` and replays
    in ``ckreplay verify`` with no translation.

    The ``prior`` knob (ISSUE 20) seeds a trajectory's first split from
    ``prior_split`` with effective-rate-true priors (the transfer floor
    folded in, exactly the information the floor hands the balancer)
    instead of the equal split, and the rate alphabet carries a
    100x-skew kind pair — the TPU-vs-host-CPU shape.  The
    ``prior-seeded-jump-within-one-step`` invariant then demands every
    prior-seeded iteration stay within one quantization step of the
    rate-implied split: the seed is already right, so no re-shard
    churn is ever legal."""

    name = "balance"
    checks = ("range-conservation", "range-quantized", "jump-one-shot",
              "freeze-legal", "converges",
              "prior-seeded-jump-within-one-step")

    #: Consecutive no-move iterations that close a trajectory as
    #: converged — the observable-decision settle rule (the whatif
    #: simulator's SETTLE).  The hidden continuous state approaches
    #: its own fixpoint only asymptotically (cont/prev_delta shrink
    #: geometrically), so exact-state repetition is NOT the
    #: convergence criterion; stable ranges are.
    SETTLE = 6

    def __init__(self, rate_alphabet=(1.0, 2.0, 5.0, 8.0, 100.0),
                 lane_counts=(2, 3), total: int = 3072, step: int = 128,
                 horizon: int = 48, balance=None, seeder=None):
        from ..core import balance as B

        self.invariants = B.MODEL_INVARIANTS
        super().__init__()
        self.B = B
        self.rates = tuple(float(r) for r in rate_alphabet)
        self.lane_counts = tuple(int(n) for n in lane_counts)
        self.total = int(total)
        self.step = int(step)
        self.horizon = int(horizon)
        self.balance = balance or B.load_balance
        #: the prior-on first-split function (the broken-fixture seam:
        #: an equal-split seeder is "prior seeding filed off")
        self.seeder = seeder or B.prior_split
        # one CLI machine runs one BalanceMachine per lane-count band
        # at tier-1 — per-instance names keep their reports from
        # colliding in check_machine's sub_machines map
        self.name = "balance(lanes={})".format(
            ",".join(str(n) for n in self.lane_counts))

    def configs(self):
        out = []
        for n in self.lane_counts:
            combos = [[]]
            for _ in range(n):
                combos = [c + [r] for c in combos for r in self.rates]
            for rates in combos:
                for jump in (False, True):
                    for smooth in (False, True):
                        for floor in (False, True):
                            for prior in (False, True):
                                out.append({
                                    "rates": tuple(rates), "jump": jump,
                                    "smooth": smooth, "floor": floor,
                                    "prior": prior,
                                })
        return out

    def _densities(self, cfg):
        """Effective per-item cost densities: the transfer floor
        doubles lane 0's density (its link is 2x its compute in this
        model), so prior/implied math sees the same wall the balancer
        does."""
        return [cfg["rates"][i] * (2.0 if cfg["floor"] and i == 0
                                   else 1.0)
                for i in range(len(cfg["rates"]))]

    def _benches(self, cfg, ranges):
        return [cfg["rates"][i] * max(ranges[i], self.step)
                for i in range(len(ranges))]

    def _transfer(self, cfg, ranges):
        if not cfg["floor"]:
            return None
        # lane 0's link is 2x slower than its compute: the floor binds
        t = [0.0] * len(ranges)
        t[0] = 2.0 * cfg["rates"][0] * max(ranges[0], self.step)
        return t

    def _canon(self, cfg_idx, ranges, state, hist):
        return (
            cfg_idx, tuple(ranges), tuple(state.cont),
            tuple(state.prev_delta), tuple(state.damp),
            state.jumped, state.warm,
            tuple(tuple(r) for r in hist.rows) if hist else None,
        )

    def explore(self, max_depth: int = 256,
                max_states: int = 500_000) -> dict:
        B = self.B
        violations: list[ModelViolation] = []
        vio_counts: dict[str, int] = {}
        seen_total = 0
        transitions = 0
        truncated = False
        horizon = self.horizon

        def _violate(inv_id, msg, doc, trace):
            self._hit(inv_id)
            if len(violations) >= MAX_VIOLATIONS or \
                    vio_counts.get(inv_id, 0) >= PER_INVARIANT_VIOLATIONS:
                return
            vio_counts[inv_id] = vio_counts.get(inv_id, 0) + 1
            kind = next(k for i, k, _d in self.invariants
                        if i == inv_id)
            violations.append(ModelViolation(
                self.name, inv_id, kind, msg, doc, trace))

        with _captured():
            for cfg_idx, cfg in enumerate(self.configs()):
                n = len(cfg["rates"])
                trace: list[dict] = []
                dens = self._densities(cfg)
                inv_d = [1.0 / d for d in dens]
                implied = [self.total * inv_d[i] / sum(inv_d)
                           for i in range(n)]
                if cfg["prior"]:
                    mark = _last_seq()
                    ranges = self.seeder(self.total, self.step,
                                         list(inv_d), cid=cfg_idx)
                    trace.extend(_harvest(mark))
                else:
                    ranges = B.equal_split(self.total, n, self.step)
                state = B.BalanceState()
                state.reset(ranges, B.DAMPING)
                hist = (B.BalanceHistory(weighted=True)
                        if cfg["smooth"] else None)
                seen = {self._canon(cfg_idx, ranges, state, hist): 0}
                last_change = 0
                settled = False
                aborted = False
                jumps = 0
                doc = {"config": {k: (list(v) if isinstance(v, tuple)
                                      else v) for k, v in cfg.items()},
                       "total": self.total, "step": self.step}
                for it in range(1, horizon + 1):
                    transitions += 1
                    mark = _last_seq()
                    new = self.balance(
                        self._benches(cfg, ranges), list(ranges),
                        self.total, self.step, hist,
                        state=state,
                        transfer_ms=self._transfer(cfg, ranges),
                        jump_start=cfg["jump"], cid=cfg_idx,
                        rate_prior=(list(inv_d) if cfg["prior"]
                                    else None))
                    rows = _harvest(mark)
                    trace.extend(rows)
                    row = rows[-1] if rows else {"outputs": {}}
                    action = row["outputs"].get("action")
                    self._hit("range-conservation")
                    if sum(new) != self.total:
                        _violate(
                            "range-conservation",
                            f"iteration {it} ranges {new} sum to "
                            f"{sum(new)}, total is {self.total}",
                            dict(doc, ranges=list(new)), trace)
                        aborted = True
                        break
                    self._hit("range-quantized")
                    if any(r < 0 or r % self.step for r in new):
                        _violate(
                            "range-quantized",
                            f"iteration {it} ranges {new} are not "
                            f"non-negative step({self.step}) "
                            "multiples",
                            dict(doc, ranges=list(new)), trace)
                        aborted = True
                        break
                    self._hit("jump-one-shot")
                    if action == "jump":
                        jumps += 1
                    if jumps > 1 or (action == "jump" and it == 1):
                        _violate(
                            "jump-one-shot",
                            f"iteration {it} jumped "
                            + ("again after the one-shot was consumed"
                               if jumps > 1 else
                               "on first-window benches (the arming "
                               "iteration must run damped)"),
                            dict(doc, ranges=list(new)), trace)
                        aborted = True
                        break
                    self._hit("freeze-legal")
                    if action == "freeze" and (
                            list(new) != list(ranges)
                            or any(r % self.step for r in ranges)):
                        _violate(
                            "freeze-legal",
                            f"iteration {it} froze a moved or "
                            f"unaligned split {ranges} -> {new}",
                            dict(doc, ranges=list(new)), trace)
                        aborted = True
                        break
                    if cfg["prior"]:
                        self._hit("prior-seeded-jump-within-one-step")
                        off = [i for i in range(n)
                               if abs(new[i] - implied[i]) > self.step]
                        if off:
                            _violate(
                                "prior-seeded-jump-within-one-step",
                                f"iteration {it} moved lane(s) {off} "
                                f"beyond one step ({self.step}) of the "
                                f"rate-implied split "
                                f"{[round(x, 1) for x in implied]}: "
                                f"{new} (rates {cfg['rates']}, "
                                f"floor={cfg['floor']}) — the prior "
                                "seed was already right; this is the "
                                "re-shard churn it exists to prevent",
                                dict(doc, ranges=list(new)), trace)
                            aborted = True
                            break
                    if new != list(ranges):
                        last_change = it
                    ranges = new
                    c = self._canon(cfg_idx, ranges, state, hist)
                    self._hit("converges")
                    if c in seen:
                        # deterministic revisit: an exact cycle.  A
                        # cycle that moved ranges is a limit cycle —
                        # convergence is impossible; a stationary one
                        # is a (frozen) fixpoint.
                        if last_change > seen[c]:
                            _violate(
                                "converges",
                                f"limit cycle of period "
                                f"{it - seen[c]} entered at iteration "
                                f"{seen[c]} moves the split forever "
                                f"(rates {cfg['rates']})",
                                dict(doc, ranges=list(ranges)), trace)
                        settled = True
                        break
                    seen[c] = it
                    if it - last_change >= self.SETTLE:
                        settled = True  # observable decision stable
                        break
                if not settled and not aborted:
                    self._hit("converges")
                    _violate(
                        "converges",
                        f"split still moving at iteration {horizon} "
                        f"(last move: {last_change}; rates "
                        f"{cfg['rates']}, jump={cfg['jump']}, "
                        f"smooth={cfg['smooth']}, "
                        f"floor={cfg['floor']})",
                        dict(doc, ranges=list(ranges)), trace)
                    truncated = True
                seen_total += len(seen)
        return {
            "machine": self.name,
            "states_explored": seen_total,
            "transitions": transitions,
            "max_depth_reached": horizon,
            "truncated": truncated,
            "violations": violations,
            "invariants": {
                i: {"kind": k, "statement": d,
                    "exercised": self._exercised[i]}
                for i, k, d in self.invariants
            },
        }


# ---------------------------------------------------------------------------
# resilience: breaker × shed × retry (serve/resilience.py)
# ---------------------------------------------------------------------------

class BreakerMachine(MachineBase):
    """Every outcome/admit/tick interleaving of the circuit breaker
    (:func:`~..serve.resilience.breaker_transition` ×
    :func:`~..serve.resilience.breaker_admit`) over integer ticks
    (``now`` is an input to the pure functions, so the model clock is
    exact).  The model carries its own GROUND-TRUTH consecutive-failure
    counter, independent of the implementation's ``failures`` field —
    a broken transition cannot hide its own evidence."""

    name = "resilience/breaker"
    checks = ("breaker-half-open-one-probe", "breaker-opens-on-threshold",
              "breaker-honest-hint", "breaker-open-times-out",
              "breaker-recovers-on-ok")

    def __init__(self, threshold: int = 2, open_ticks: int = 3,
                 transition=None, admit=None):
        from ..serve import resilience as R

        self.invariants = R.BREAKER_INVARIANTS
        super().__init__()
        self.R = R
        self.threshold = int(threshold)
        self.open_ticks = int(open_ticks)
        self.transition = transition or R.breaker_transition
        self.admit = admit or R.breaker_admit

    def initial_states(self):
        # (real breaker state dict as a tuple, tick, ground consecutive
        # failures) — canon replaces the absolute clock with the age
        return [(self._freeze(self.R.breaker_init()), 0, 0)]

    @staticmethod
    def _freeze(st: dict) -> tuple:
        return (st["state"], int(st["failures"]),
                bool(st["probe_inflight"]),
                None if st["opened_t"] is None else float(st["opened_t"]))

    @staticmethod
    def _thaw(frozen: tuple) -> dict:
        return {"state": frozen[0], "failures": frozen[1],
                "probe_inflight": frozen[2], "opened_t": frozen[3]}

    def canon(self, state):
        frozen, tick, ground = state
        st, fails, probe, opened_t = frozen
        age = None
        if opened_t is not None:
            age = min(int(tick - opened_t), self.open_ticks + 1)
        return (st, min(fails, self.threshold), probe, age,
                min(ground, self.threshold))

    def state_doc(self, state):
        frozen, tick, ground = state
        return {"breaker": self._thaw(frozen), "tick": tick,
                "ground_consecutive_failures": ground,
                "threshold": self.threshold,
                "open_ticks": self.open_ticks}

    def _row(self, op: str, st: dict, out: dict, now: float,
             event: str | None = None) -> dict:
        inputs = {"key": "model", "state": dict(st), "now": float(now),
                  "threshold": self.threshold,
                  "open_s": float(self.open_ticks), "op": op}
        if event is not None:
            inputs["event"] = event
        outputs = {"state": dict(out["state"]),
                   "action": out.get("action")}
        if op == "admit":
            outputs.update({"allow": out["allow"], "probe": out["probe"],
                            "retry_after_s": out["retry_after_s"]})
        return {"kind": "breaker", "inputs": inputs, "outputs": outputs}

    def actions(self, state):
        frozen, tick, ground = state
        st = self._thaw(frozen)
        out = []
        for event in ("success", "failure"):
            res = self.transition(st, event, float(tick), self.threshold,
                                  float(self.open_ticks))
            if st["state"] == self.R.BREAKER_CLOSED:
                g2 = 0 if event == "success" else ground + 1
            elif st["state"] == self.R.BREAKER_HALF_OPEN:
                g2 = 0 if event == "success" else self.threshold
            else:
                g2 = ground  # stale outcome against an open breaker
            out.append((
                f"outcome-{event}",
                [self._row("transition", st, res, tick, event)],
                (self._freeze(res["state"]), tick + 1, g2)))
        adm = self.admit(st, float(tick), float(self.open_ticks))
        out.append((
            "admit",
            [self._row("admit", st, adm, tick)],
            (self._freeze(adm["state"]), tick + 1, ground)))
        return out

    def check_action(self, state, label, rows, nxt):
        frozen, tick, ground = state
        st = self._thaw(frozen)
        inp, out = rows[0]["inputs"], rows[0]["outputs"]
        bad = []
        if inp["op"] == "admit":
            self._hit("breaker-half-open-one-probe")
            if st["state"] == self.R.BREAKER_HALF_OPEN \
                    and st["probe_inflight"] and out["allow"]:
                bad.append((
                    "breaker-half-open-one-probe",
                    "half-open admitted a SECOND probe while one was "
                    "in flight"))
            self._hit("breaker-honest-hint")
            if not out["allow"]:
                hint = out["retry_after_s"]
                remaining = None
                if st["state"] == self.R.BREAKER_OPEN \
                        and st["opened_t"] is not None:
                    remaining = (float(self.open_ticks)
                                 - (tick - st["opened_t"]))
                if hint is None or hint <= 0.0 \
                        or hint > float(self.open_ticks):
                    bad.append((
                        "breaker-honest-hint",
                        f"refused admit carries hint {hint!r}, outside "
                        f"(0, open_s={self.open_ticks}]"))
                elif remaining is not None and remaining > 0.005 \
                        and abs(hint - remaining) > 1e-9:
                    bad.append((
                        "breaker-honest-hint",
                        f"open breaker hinted {hint}, the remaining "
                        f"window is {remaining}"))
        else:
            self._hit("breaker-opens-on-threshold")
            opened = out["action"] == "opened"
            if st["state"] == self.R.BREAKER_CLOSED:
                consec = (ground + 1 if inp["event"] == "failure" else 0)
                if opened and consec < self.threshold:
                    bad.append((
                        "breaker-opens-on-threshold",
                        f"opened after only {consec} consecutive "
                        f"failure(s) (threshold {self.threshold})"))
                if not opened and consec >= self.threshold:
                    bad.append((
                        "breaker-opens-on-threshold",
                        f"{consec} consecutive failures reached the "
                        f"threshold ({self.threshold}) but the breaker "
                        "stayed closed"))
        return bad

    def check_liveness(self, state):
        frozen, tick, ground = state
        st = self._thaw(frozen)
        bad = []
        if st["state"] == self.R.BREAKER_OPEN:
            # open-times-out: keep admitting; within open_ticks + 1
            # admits one must be granted as the probe
            self._hit("breaker-open-times-out")
            cur, t = dict(st), float(tick)
            extra, granted = [], False
            for _ in range(self.open_ticks + 1):
                adm = self.admit(cur, t, float(self.open_ticks))
                extra.append(self._row("admit", cur, adm, t))
                cur, t = dict(adm["state"]), t + 1
                if adm["allow"]:
                    granted = adm["probe"]
                    break
            if not granted:
                bad.append((
                    "breaker-open-times-out",
                    f"open breaker granted no probe within "
                    f"{self.open_ticks + 1} admits of opening", extra))
        if st["state"] != self.R.BREAKER_CLOSED:
            # recovers-on-ok, at EXACTLY the declared bound (slack here
            # would let a one-extra-step regression slip past the
            # MODEL_INVARIANTS statement — the drain-machine rule): an
            # all-success schedule delivers the in-flight probe's
            # success when one exists, else admits; worst reachable
            # chain = open_ticks denied admits + the probe admit + its
            # success = open_ticks + 2 steps, exactly the bound.
            self._hit("breaker-recovers-on-ok")
            bound = self.open_ticks + 2
            cur, t = dict(st), float(tick)
            extra = []
            for _ in range(bound):
                if cur["state"] == self.R.BREAKER_CLOSED:
                    break
                if cur["state"] == self.R.BREAKER_HALF_OPEN \
                        and cur["probe_inflight"]:
                    res = self.transition(
                        cur, "success", t, self.threshold,
                        float(self.open_ticks))
                    extra.append(self._row(
                        "transition", cur, res, t, "success"))
                    cur, t = dict(res["state"]), t + 1
                    continue
                adm = self.admit(cur, t, float(self.open_ticks))
                extra.append(self._row("admit", cur, adm, t))
                cur, t = dict(adm["state"]), t + 1
            if cur["state"] != self.R.BREAKER_CLOSED:
                bad.append((
                    "breaker-recovers-on-ok",
                    f"breaker still {cur['state']} after {bound} "
                    "all-success steps (the declared bound: open_s + "
                    "2; permanent open under all-ok inputs)", extra))
        return bad


class ShedMachine(MachineBase):
    """Every pressure-signal sequence through
    :func:`~..serve.resilience.brownout_transition` (queue depth ×
    open breakers × drained lanes per evaluation), plus the
    ``admit_decision`` brownout gate at every active state over
    in-flight × priority — the starvation floor is checked where the
    shed actually happens."""

    name = "resilience/shed"
    checks = ("shed-pressure-gated", "shed-quota-floor",
              "shed-named-hint", "shed-releases")

    QUEUE_LEVELS = (0, 2, 4)  # clear, at clear-mark, at watermark
    WATERMARK = 4
    CLEAR_MARK = 2

    def __init__(self, engage_streak: int = 2, quota: int = 2,
                 transition=None, decide=None):
        from ..serve import admission as A
        from ..serve import resilience as R

        self.invariants = R.SHED_INVARIANTS
        super().__init__()
        self.R, self.A = R, A
        self.engage_streak = int(engage_streak)
        self.quota = int(quota)
        self.transition = transition or R.brownout_transition
        self.decide = decide or A.admit_decision

    def initial_states(self):
        return [(False, 0)]

    def canon(self, state):
        active, streak = state
        return (bool(active), min(int(streak), self.engage_streak))

    def state_doc(self, state):
        return {"active": state[0], "streak": state[1],
                "engage_streak": self.engage_streak}

    def actions(self, state):
        active, streak = state
        out = []
        for qd in self.QUEUE_LEVELS:
            for ob in (0, 1):
                for dl in (0, 1):
                    res = self.transition(
                        {"active": active, "streak": streak}, qd,
                        self.WATERMARK, self.CLEAR_MARK, ob, dl,
                        engage_streak=self.engage_streak)
                    row = {"kind": "shed", "inputs": {
                        "state": {"active": active, "streak": streak},
                        "queue_depth": qd,
                        "watermark": self.WATERMARK,
                        "clear_mark": self.CLEAR_MARK,
                        "open_breakers": ob, "drained_lanes": dl,
                        "engage_streak": self.engage_streak,
                    }, "outputs": dict(res)}
                    out.append((
                        f"eval(qd={qd},ob={ob},dl={dl})", [row],
                        (bool(res["active"]), int(res["streak"]))))
        return out

    def check_action(self, state, label, rows, nxt):
        active, streak = state
        inp, out = rows[0]["inputs"], rows[0]["outputs"]
        bad = []
        self._hit("shed-pressure-gated")
        pressured = bool(
            inp["queue_depth"] >= inp["watermark"]
            or ((inp["open_breakers"] > 0 or inp["drained_lanes"] > 0)
                and inp["queue_depth"] >= inp["clear_mark"]))
        if out["changed"] and out["active"]:
            if not pressured or streak < self.engage_streak - 1:
                bad.append((
                    "shed-pressure-gated",
                    f"brownout engaged at streak {streak} under "
                    f"{'un' if not pressured else ''}pressured inputs "
                    f"({label}) — the {self.engage_streak}-evaluation "
                    "hysteresis was skipped"))
        return bad

    def check_state(self, state):
        active, streak = state
        bad = []
        if not active:
            return bad
        # the shed gate itself, at every active state: in-flight ×
        # priority over a clear-other-gates admit
        self._hit("shed-quota-floor")
        self._hit("shed-named-hint")
        shed_quota = self.A.brownout_share(self.quota)
        for inflight in (0, 1, self.quota):
            for priority in (0, 1):
                dec = self.decide(
                    tenant_inflight=inflight, quota=self.quota,
                    queue_depth=0, max_queue_depth=64, healthy=True,
                    est_batch_s=0.01, brownout=True,
                    shed_quota=shed_quota, priority=priority)
                if inflight == 0 and not dec["admit"]:
                    bad.append((
                        "shed-quota-floor",
                        f"brownout shed a tenant with ZERO requests in "
                        f"flight (priority {priority}) — the "
                        "starvation floor is broken"))
                if not dec["admit"]:
                    if dec["reason"] != self.A.REJECT_BROWNOUT:
                        bad.append((
                            "shed-named-hint",
                            f"brownout rejection named {dec['reason']!r}"
                            f", expected {self.A.REJECT_BROWNOUT!r}"))
                    if (dec["retry_after_s"] or 0.0) < 0.005:
                        bad.append((
                            "shed-named-hint",
                            f"brownout rejection hint "
                            f"{dec['retry_after_s']!r} is below the "
                            "anti-busy-loop floor"))
        return bad

    def check_liveness(self, state):
        active, streak = state
        if not active:
            return []
        self._hit("shed-releases")
        cur = {"active": True, "streak": int(streak)}
        extra = []
        for _ in range(self.engage_streak):
            res = self.transition(
                cur, 0, self.WATERMARK, self.CLEAR_MARK, 0, 0,
                engage_streak=self.engage_streak)
            extra.append({"kind": "shed", "inputs": {
                "state": dict(cur), "queue_depth": 0,
                "watermark": self.WATERMARK,
                "clear_mark": self.CLEAR_MARK,
                "open_breakers": 0, "drained_lanes": 0,
                "engage_streak": self.engage_streak,
            }, "outputs": dict(res)})
            cur = {"active": res["active"], "streak": res["streak"]}
            if not cur["active"]:
                return []
        return [(
            "shed-releases",
            f"brownout still active after {self.engage_streak} "
            "all-clear evaluations (sticky degraded mode)", extra)]


class RetryMachine(MachineBase):
    """Every (attempt × budget × deadline × jitter) point of
    :func:`~..serve.resilience.retry_decision`, with the budget's
    spend/refill accounting driven alongside — proves retries can
    never outrun the budget or the backoff bounds."""

    name = "resilience/retry"
    checks = ("retry-budget-bounded", "retry-backoff-bounded")

    JITTER = (0.0, 0.999)
    DEADLINES = (None, 0.001, 10.0)
    BASE_S = 0.01
    CAP_S = 0.04

    def __init__(self, max_attempts: int = 2, budget_cap: int = 2,
                 decide=None):
        from ..serve import resilience as R

        self.invariants = R.RETRY_INVARIANTS
        super().__init__()
        self.R = R
        self.max_attempts = int(max_attempts)
        self.budget_cap = int(budget_cap)
        self.decide = decide or R.retry_decision

    def initial_states(self):
        return [(0, self.budget_cap)]

    def state_doc(self, state):
        return {"attempt": state[0], "tokens": state[1],
                "max_attempts": self.max_attempts}

    def actions(self, state):
        attempt, tokens = state
        out = []
        for u in self.JITTER:
            for dl in self.DEADLINES:
                rd = self.decide(attempt, self.max_attempts,
                                 float(tokens), dl, self.BASE_S,
                                 self.CAP_S, u)
                row = {"kind": "retry", "inputs": {
                    "attempt": attempt,
                    "max_attempts": self.max_attempts,
                    "tokens": float(tokens),
                    "deadline_left_s": dl,
                    "base_s": self.BASE_S, "cap_s": self.CAP_S,
                    "jitter_u": u,
                }, "outputs": dict(rd)}
                nxt = ((min(attempt + 1, self.max_attempts + 1),
                        max(0, tokens - 1))
                       if rd["retry"] else (attempt, tokens))
                out.append((f"retry?(u={u},dl={dl})", [row], nxt))
        out.append(("refill", [],
                    (attempt, min(self.budget_cap, tokens + 1))))
        if attempt > 0:
            out.append(("fresh-request", [], (0, tokens)))
        return out

    def check_action(self, state, label, rows, nxt):
        if not rows:
            return []
        attempt, tokens = state
        inp, out = rows[0]["inputs"], rows[0]["outputs"]
        bad = []
        self._hit("retry-budget-bounded")
        if out["retry"]:
            if inp["tokens"] < 1.0:
                bad.append((
                    "retry-budget-bounded",
                    f"retry granted with {inp['tokens']} budget "
                    "tokens — the budget cannot bound a storm"))
            if inp["attempt"] >= inp["max_attempts"]:
                bad.append((
                    "retry-budget-bounded",
                    f"retry granted at attempt {inp['attempt']} with "
                    f"max_attempts {inp['max_attempts']}"))
        elif out.get("reason") not in (
                "attempts-exhausted", "budget-exhausted", "deadline"):
            bad.append((
                "retry-budget-bounded",
                f"refused retry names no reason ({out.get('reason')!r})"))
        self._hit("retry-backoff-bounded")
        if out["retry"]:
            delay = out["delay_s"]
            if delay is None or delay < 0.0 \
                    or delay > 1.5 * inp["cap_s"] + 1e-12:
                bad.append((
                    "retry-backoff-bounded",
                    f"granted delay {delay!r} outside "
                    f"[0, 1.5*cap={1.5 * inp['cap_s']}]"))
            dl = inp["deadline_left_s"]
            if dl is not None and delay is not None and delay >= dl:
                bad.append((
                    "retry-backoff-bounded",
                    f"granted delay {delay} overshoots the remaining "
                    f"deadline {dl}"))
        return bad


class BlockMachine(MachineBase):
    """Every reachable (engaged choice × measured-wall set) point of
    the block autotuner's pure transition
    (:func:`~..core.blocktuner.block_transition`), walls drawn from a
    small quantized level alphabet that straddles the hysteresis
    fraction (1.05/1.00 sits inside the 8% band, 2.00 far outside) —
    proves the engaged choice is always a legal tile, noise can never
    flap it, and no choice change goes unrecorded.

    Seams: ``decide`` (default: the real ``block_transition``) and
    ``emit`` (default: identity — the row a change would record).  The
    broken fixtures in tests/test_ckmodel.py replace each to prove the
    checker catches an illegal chooser, a hysteresis-free chooser, and
    a silent retune."""

    name = "block/choice"
    checks = ("choice-legality", "hysteresis-bound", "retune-visibility")

    def __init__(self, tq: int = 256, tk: int = 256,
                 wall_levels=(1.0, 1.05, 2.0), max_measured: int = 2,
                 decide=None, emit=None):
        from ..core import blocktuner as BT

        self.invariants = BT.MODEL_INVARIANTS
        super().__init__()
        self.BT = BT
        self.tq, self.tk = int(tq), int(tk)
        self.grid = BT.legal_block_grid(self.tq, self.tk)
        self.wall_levels = tuple(float(w) for w in wall_levels)
        self.max_measured = int(max_measured)
        self.decide = decide or BT.block_transition
        self.emit = emit if emit is not None else (lambda row: [row])

    def initial_states(self):
        return [(None, ())]  # unengaged, nothing measured

    def state_doc(self, state):
        current, walls = state
        return {"current": current,
                "walls": [[list(p), self.wall_levels[i]]
                          for p, i in walls],
                "grid": [list(p) for p in self.grid]}

    def _wall_list(self, walls):
        return [(p, self.wall_levels[i]) for p, i in walls]

    def _decide_at(self, current, walls):
        return self.decide(current, self._wall_list(walls), self.grid,
                           hysteresis=self.BT.HYSTERESIS_FRAC)

    def actions(self, state):
        current, walls = state
        wd = dict(walls)
        out = []
        for pair in self.grid:
            if len(wd) >= self.max_measured and pair not in wd:
                continue  # bounded measured set
            for li in range(len(self.wall_levels)):
                nwd = dict(wd)
                nwd[pair] = li
                nwalls = tuple(sorted(nwd.items()))
                choice, why = self._decide_at(current, nwalls)
                changed = choice is not None and choice != (
                    None if current is None else tuple(current))
                rows = []
                if changed:
                    rows = list(self.emit({
                        "kind": "block-retune",
                        "inputs": {
                            "tq": self.tq, "tk": self.tk,
                            "grid": [list(p) for p in self.grid],
                            "walls": [[list(p), w] for p, w in
                                      self._wall_list(nwalls)],
                            "current": (None if current is None
                                        else list(current)),
                            "seed": None, "fallback": None,
                            "hysteresis": self.BT.HYSTERESIS_FRAC,
                        },
                        "outputs": {"block_q": choice[0],
                                    "block_k": choice[1], "why": why},
                    }))
                nxt = (choice if changed else current, nwalls)
                out.append(
                    (f"measure({pair[0]}x{pair[1]}@L{li})", rows, nxt))
        if current is not None or walls:
            out.append(("invalidate", [], (None, ())))
        return out

    def check_action(self, state, label, rows, nxt):
        if label == "invalidate":
            return []
        current, _ = state
        _ncur, nwalls = nxt
        # re-derive the edge's decision from the post-measure walls —
        # deterministic, so the checks see exactly what actions() saw
        choice, why = self._decide_at(current, nwalls)
        changed = choice is not None and choice != (
            None if current is None else tuple(current))
        bad = []
        self._hit("choice-legality")
        if choice is not None and tuple(choice) not in set(self.grid):
            bad.append((
                "choice-legality",
                f"engaged choice {choice} is not in the legal grid "
                f"for (tq={self.tq}, tk={self.tk})"))
        if choice is None and why not in ("no-legal-grid", "cold"):
            bad.append((
                "choice-legality",
                f"None choice carries why {why!r} — an unnamed dense "
                "fallback"))
        self._hit("hysteresis-bound")
        if changed and current is not None and why != "measuring":
            # "measuring" is the one exempt change: the incumbent had
            # no measured wall, so there is no band to defend
            wd = dict(self._wall_list(nwalls))
            cur_w = wd.get(tuple(current))
            best_w = wd.get(tuple(choice)) if choice is not None else None
            if cur_w is not None and (
                    best_w is None
                    or best_w >= cur_w * (1.0 - self.BT.HYSTERESIS_FRAC)
                    - 1e-12):
                bad.append((
                    "hysteresis-bound",
                    f"choice moved {current}->{choice} on walls "
                    f"best={best_w} vs incumbent={cur_w}: inside the "
                    f"{self.BT.HYSTERESIS_FRAC:.0%} band — noise can "
                    "flap the choice"))
        self._hit("retune-visibility")
        if changed:
            visible = any(
                r.get("kind") == "block-retune"
                and r.get("outputs", {}).get("block_q") == choice[0]
                and r.get("outputs", {}).get("block_k") == choice[1]
                for r in rows)
            if not visible:
                bad.append((
                    "retune-visibility",
                    f"choice changed {current}->{choice} with no "
                    "matching block-retune row — a silent retune"))
        return bad


# ---------------------------------------------------------------------------
# assembly, reports, and the counterexample bridge
# ---------------------------------------------------------------------------

def _depth_scale() -> int:
    """``CK_MODEL_DEPTH``: 1 = tier-1 bounds; larger deepens."""
    try:
        return max(1, int(os.environ.get(DEPTH_ENV, "") or 1))
    except ValueError:
        return 1


def build_machines(name: str, quick: bool = False,
                   scale: int | None = None) -> list:
    """The sub-machine list for one CLI machine name, at tier-1 bounds
    scaled by ``CK_MODEL_DEPTH`` (or ``scale``).  ``quick`` is the
    bench-epilogue profile: the same machines under the smallest
    honest bounds, sub-second."""
    scale = _depth_scale() if scale is None else max(1, int(scale))
    if name == "drain":
        if quick:
            return [DrainMachine(lanes=2, hold_barriers=1,
                                 confirm_clear=1, probe_grace=1)]
        return [DrainMachine(hold_barriers=2 + scale,
                             confirm_clear=2 + scale,
                             probe_grace=1 + 2 * scale)]
    if name == "elastic":
        if quick:
            return [ElasticMachine(member_ids=("p0", "p2"))]
        ids = ("p0", "p2", "p10") if scale == 1 else \
            ("p0", "p2", "p10", "p3")[:3 + min(scale - 1, 1)]
        return [ElasticMachine(member_ids=ids, steps=(2, 3, 4))]
    if name == "serve":
        if quick:
            return [AdmissionMachine(tenants=("a", "b"), quota=2,
                                     max_queue_depth=2),
                    CoalesceMachine(keys=("ga", "gb"))]
        return [
            AdmissionMachine(quota=2 + scale,
                             max_queue_depth=4 + scale),
            CoalesceMachine(max_picks=1,
                            starve_cap_extra=1 + scale),
            CoalesceMachine(max_picks=2),
        ]
    if name == "balance":
        if quick:
            return [BalanceMachine(rate_alphabet=(1.0, 5.0),
                                   lane_counts=(2,), horizon=32)]
        rates = (1.0, 1.5, 2.0, 5.0, 8.0, 100.0) if scale == 1 else \
            (1.0, 1.5, 2.0, 3.0, 5.0, 8.0, 100.0)
        # full pairwise alphabet on 2 lanes; the 3-lane machine keeps
        # the closest tie-band pair (1.0/1.5) and the 100x hetero skew
        # but drops the mid rates — the prior knob doubled the config
        # space and triple-lane combos dominate the wall otherwise
        tri = (1.0, 1.5, 2.0, 100.0) if scale == 1 else \
            (1.0, 1.5, 2.0, 8.0, 100.0)
        return [BalanceMachine(rate_alphabet=rates, lane_counts=(2,),
                               horizon=32 * scale),
                BalanceMachine(rate_alphabet=tri, lane_counts=(3,),
                               horizon=32 * scale)]
    if name == "resilience":
        if quick:
            return [BreakerMachine(threshold=2, open_ticks=2),
                    ShedMachine(engage_streak=1),
                    RetryMachine(max_attempts=1, budget_cap=1)]
        return [BreakerMachine(threshold=2 + (scale - 1),
                               open_ticks=2 + scale),
                ShedMachine(engage_streak=1 + scale),
                RetryMachine(max_attempts=1 + scale,
                             budget_cap=1 + scale)]
    if name == "router":
        if quick:
            return [RouterMachine(member_ids=("p0", "p2"))]
        ids = ("p0", "p2", "p10") if scale == 1 else \
            ("p0", "p2", "p10", "p3")[:3 + min(scale - 1, 1)]
        return [RouterMachine(member_ids=ids)]
    if name == "block":
        if quick:
            return [BlockMachine(tq=256, tk=256,
                                 wall_levels=(1.0, 1.05),
                                 max_measured=2)]
        return [BlockMachine(tq=512, tk=512,
                             wall_levels=(1.0, 1.05, 2.0),
                             max_measured=2 + min(scale - 1, 1))]
    raise ValueError(
        f"unknown machine {name!r}; machines: {MACHINE_NAMES}")


def check_machine(name: str, quick: bool = False,
                  scale: int | None = None,
                  machines: list | None = None) -> dict:
    """Explore one CLI machine (all its sub-machines) and merge."""
    subs = machines if machines is not None else build_machines(
        name, quick=quick, scale=scale)
    reports = [m.explore() for m in subs]
    return {
        "machine": name,
        "states_explored": sum(r["states_explored"] for r in reports),
        "transitions": sum(r["transitions"] for r in reports),
        "truncated": any(r["truncated"] for r in reports),
        "violations": [v for r in reports for v in r["violations"]],
        "sub_machines": {r["machine"]: {
            "states_explored": r["states_explored"],
            "transitions": r["transitions"],
            "invariants": r["invariants"],
        } for r in reports},
    }


def check_all(names=None, quick: bool = False,
              scale: int | None = None) -> dict:
    """The full report over every machine: the CLI gate's engine and
    the bench artifact's ``model`` block."""
    names = tuple(names) if names else MACHINE_NAMES
    per = {n: check_machine(n, quick=quick, scale=scale) for n in names}
    violations = [v for r in per.values() for v in r["violations"]]
    return {
        "ok": not violations,
        "states_explored": sum(
            r["states_explored"] for r in per.values()),
        "transitions": sum(r["transitions"] for r in per.values()),
        "machines": per,
        "violations": violations,
    }


def tier1_check(quick: bool = True) -> dict:
    """The bench-epilogue view: jsonable, violation rows not objects."""
    rep = check_all(quick=quick)
    return {
        "ok": rep["ok"],
        "states_explored": rep["states_explored"],
        "machines": {
            n: {"states_explored": r["states_explored"],
                "violations": len(r["violations"])}
            for n, r in rep["machines"].items()
        },
        "violations": [v.to_row() for v in rep["violations"][:4]],
    }


