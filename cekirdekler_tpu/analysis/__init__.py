"""ckprove — kernel partition-safety & flag-soundness verification.

The framework's single riskiest user contract is invisible to every
runtime check: a kernel plus its per-array transfer flags
(``arrays/clarray.py`` ``TransferFlags``) is *assumed* safe to split
across lanes.  A mis-declared flag (``partial_read`` on an array the
kernel gathers from; ``write_only`` on an array it reads first) or a
non-gid-confined access (a write landing outside the caller's
partition) silently corrupts results or wastes H2D bytes — the exact
failure mode the reference's ``partialRead`` hints carry, and one the
serving tier now accepts from untrusted tenants.

This package is a pure-AST abstract interpreter over the kernel
language's parse tree (``kernel/lang.py`` nodes — **no jax import**,
the ckcheck run-anywhere discipline): it tracks index provenance from
``get_global_id(0)`` through arithmetic, loops, branches and helper
calls to every ``Index`` read/write site, summarizes each array's
access pattern (gid-affine interval with halo width / uniform /
gather / read-before-write), and proves or refutes split-safety and
flag soundness against the declared :class:`TransferFlags`.

Three consumers:

- :class:`~cekirdekler_tpu.kernel.registry.KernelProgram` summarizes
  once per source and caches launch verdicts;
- ``Cores.compute`` gates on the verdict (advisory by default;
  ``CK_KERNEL_VERIFY=strict`` raises
  :class:`~cekirdekler_tpu.errors.KernelVerifyError` with the named
  finding and source line);
- serve admission rejects unsafe jobs with a named ``ServeRejected``
  reason, recorded replayably (``ckreplay verify``).

The CLI is ``python -m tools.ckprove`` (ratcheted baseline, ``--json``,
``--explain``, ``// ckprove: ok`` source suppressions).  The
correctness anchor is the differential oracle in
``tests/kernel_corpus.py``: every verdict is checked against ground
truth by running each corpus kernel split across virtual lanes vs
unsplit and comparing bit-exactly.

The package's other analyzer, :mod:`.model` (``tools/ckmodel``), is
deliberately NOT imported here: it is the bounded exhaustive model
checker for the pure controller state machines, and it imports the
LIVE runtime (driving the real `drain_transition`/`Membership`/
`admit_decision`/`plan_coalesce`/`load_balance` is its whole point) —
keeping it out of this namespace preserves ckprove's jax-free
stub-load path.
"""

from .interp import AV, Access, KernelSummary, summarize_kernel
from .verdict import (
    ADVISORY_KINDS,
    ERROR_KINDS,
    VERDICT_KINDS,
    Finding,
    LaunchVerdict,
    classify,
    flag_row,
    structural_findings,
    suppressed_lines,
    verify_launch,
)

__all__ = [
    "AV",
    "Access",
    "ADVISORY_KINDS",
    "ERROR_KINDS",
    "Finding",
    "KernelSummary",
    "LaunchVerdict",
    "VERDICT_KINDS",
    "classify",
    "flag_row",
    "structural_findings",
    "summarize_kernel",
    "suppressed_lines",
    "verify_launch",
]
