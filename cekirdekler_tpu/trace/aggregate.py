"""Cluster-wide trace/metrics aggregation: N DCN processes, ONE timeline.

The tracer and the metrics registry are process-local; a
``DistributedAccelerator`` job runs N processes whose spans could never
be read on one timeline — each process's ``time.perf_counter`` has its
own arbitrary epoch.  This module closes that gap the way Dapper-style
aggregation pipelines do (PAPERS.md): worker processes ship their span
batches and metric snapshots to one logical collector, with per-process
**clock-offset estimation** so a collective that happened once appears
once, simultaneously, on every process's track of the merged Perfetto
trace.

Clock model (RTT-symmetric probe, the NTP midpoint argument):

- Every process wraps the SAME blocking collective (a tiny all-gather)
  in ``t_before``/``t_after`` local readings.  The collective completes
  at one global instant ``G``; each process's ``[t_before, t_after]``
  window contains ``G``, so the midpoint ``m_i = (t_before+t_after)/2``
  estimates ``G`` on clock *i* with error bounded by half that
  process's window width (the RTT-symmetry assumption — the same one
  NTP makes).
- A second all-gather ships the midpoints; ``offset_i = m_i - m_0``
  maps clock *i* onto process 0's clock: ``t_global = t_local -
  offset_i``.
- The probe repeats ``rounds`` times and takes the per-process MEDIAN
  offset — one garbage-collection pause during one round must not skew
  the alignment.

``skew_s`` on the probe/shipping entry points is a deterministic test
seam (same convention as ``DistributedAccelerator.timing_hook``): it
adds a constant to every LOCAL clock reading this module takes on this
process — simulating processes whose monotonic epochs genuinely differ,
which loopback test rigs (one machine, one CLOCK_MONOTONIC) cannot
produce naturally.  The estimator must recover and cancel exactly that
constant; ``tests/_dcn_worker.py`` injects per-process skews of seconds
and asserts the merged trace stays collective-consistent to
milliseconds.
"""

from __future__ import annotations

import json
import time
from typing import Sequence

import numpy as np

from .spans import TRACER, Span

__all__ = [
    "estimate_clock_offsets",
    "gather_cluster",
    "merged_chrome_trace",
    "collective_consistency",
    "ClusterSnapshot",
]


def _now(skew_s: float) -> float:
    return time.perf_counter() + skew_s


def estimate_clock_offsets(
    acc, rounds: int = 5, skew_s: float = 0.0
) -> list[float]:
    """Per-process clock offsets onto process 0's clock (seconds).

    ``acc`` is a live :class:`~cekirdekler_tpu.cluster.dcn.
    DistributedAccelerator` (its ``_allgather`` is the probe transport —
    the measurement rides the same DCN path it will be used to align).
    SPMD: every process must call this at the same point; every process
    returns the SAME offset table.  ``offsets[i]`` subtracted from
    process *i*'s timestamps maps them onto process 0's timeline."""
    probe = np.zeros(1, np.float64)
    per_round: list[np.ndarray] = []
    for _ in range(max(1, rounds)):
        t_before = _now(skew_s)
        acc._allgather(probe)  # the shared global instant G
        t_after = _now(skew_s)
        mid = (t_before + t_after) / 2.0
        mids = acc._allgather(np.asarray([mid], np.float64)).reshape(-1)
        per_round.append(mids - mids[0])
    stacked = np.stack(per_round)  # [rounds, nproc]
    return [float(x) for x in np.median(stacked, axis=0)]


class ClusterSnapshot(dict):
    """The merged result of :func:`gather_cluster` — a dict with keys

    - ``offsets``: per-process clock offsets (seconds, process 0 = 0.0)
    - ``spans``: per-process span lists ALIGNED to process 0's clock
    - ``metrics``: per-process registry snapshots
    - ``health``: per-process lane-health reports
      (``Cores.health_report()`` shape; ``{}`` for a process that
      shipped none) — feed
      :func:`cekirdekler_tpu.obs.health.cluster_health_table` for the
      job-wide verdict table
    - ``serving``: per-process serving stats (``{}`` for a process
      that shipped none) — per-shard ``ServeFrontend.stats()`` docs
      keyed by member; feed
      :func:`cekirdekler_tpu.serve.fabric.merge_shard_serving` for the
      job-wide serving totals
    - ``reqtrace``: per-process request-lifecycle event rows
      (``[]`` for a process that shipped none) — ``obs.reqtrace``
      ``(t, rid, kind, fields)`` rows on WALL-clock stamps (epoch
      seconds, cross-process comparable on one host without the offset
      table); concatenate across processes and feed
      :func:`cekirdekler_tpu.obs.reqtrace.fold_phases` so a rid whose
      chain hopped shards reads as ONE record
    - ``nproc``

    (a dict subclass so it JSON-serializes untouched; spans are listed
    as plain dicts)."""


def _spans_to_rows(spans: Sequence[Span]) -> list[dict]:
    return [
        {"kind": s.kind, "t0": s.t0, "t1": s.t1, "cid": s.cid,
         "lane": s.lane, "tag": s.tag}
        for s in spans
    ]


def _rows_to_spans(rows: Sequence[dict], offset: float) -> list[Span]:
    return [
        Span(r["kind"], r["t0"] - offset, r["t1"] - offset,
             r.get("cid"), r.get("lane"), r.get("tag"))
        for r in rows
    ]


def gather_cluster(
    acc,
    spans: Sequence[Span] | None = None,
    metrics_snapshot: dict | None = None,
    rounds: int = 5,
    skew_s: float = 0.0,
    health: dict | None = None,
    serving: dict | None = None,
    reqtrace: Sequence | None = None,
) -> ClusterSnapshot:
    """Ship this process's spans + metrics + lane-health report to the
    cluster; return the merged, clock-aligned view (SPMD — every
    process receives the same merge; process 0 is the canonical
    collector that persists it).

    Payloads are JSON over the raw-byte all-gather (rectangularized by
    padding to the max length — the same shape rule the result exchange
    uses).  ``skew_s`` shifts this process's span timestamps AND its
    probe clock by the same constant, the deterministic end-to-end test
    of the estimator (see module docstring).  ``health`` defaults to
    the accelerator's own ``health_report()`` when it has one (the
    ``DistributedAccelerator`` passthrough to its local ``Cores``) —
    the DCN tier thereby sees every process's lane verdicts on one
    table (``obs.health.cluster_health_table``)."""
    from ..metrics.registry import REGISTRY

    if spans is None:
        spans = TRACER.snapshot()
    if metrics_snapshot is None:
        metrics_snapshot = REGISTRY.snapshot()
    if health is None:
        reporter = getattr(acc, "health_report", None)
        try:
            health = reporter() if callable(reporter) else {}
        except Exception:  # noqa: BLE001 - health must not sink the gather
            health = {}
    offsets = estimate_clock_offsets(acc, rounds=rounds, skew_s=skew_s)

    rows = _spans_to_rows(spans)
    if skew_s:
        for r in rows:
            r["t0"] += skew_s
            r["t1"] += skew_s
    from ..utils.jsonsafe import json_safe

    # json_safe: a numpy scalar in a caller-supplied metrics snapshot or
    # an inf ratio in a health report must not kill (or corrupt) the
    # whole cluster gather — every peer decodes this payload strictly
    # request-lifecycle rows ride as plain 4-lists; their stamps are
    # WALL clock (time.time) by the reqtrace contract, so — unlike the
    # spans — they need no per-process offset correction on one host
    req_rows = [
        [float(e[0]), str(e[1]), str(e[2]), dict(e[3] or {})]
        for e in (reqtrace or ())
    ]
    payload = json.dumps(
        json_safe(
            {"spans": rows, "metrics": metrics_snapshot, "health": health,
             "serving": serving or {}, "reqtrace": req_rows}
        ),
        allow_nan=False,
    ).encode()
    # rectangularize: exchange lengths first, pad to the max
    sizes = acc._allgather(np.asarray([len(payload)], np.int64)).reshape(-1)
    max_len = int(sizes.max())
    buf = np.zeros(max_len, np.uint8)
    buf[: len(payload)] = np.frombuffer(payload, np.uint8)
    gathered = acc._allgather(buf)

    per_proc_spans: list[list[Span]] = []
    per_proc_metrics: list[dict] = []
    per_proc_health: list[dict] = []
    per_proc_serving: list[dict] = []
    per_proc_reqtrace: list[list] = []
    for p in range(len(sizes)):
        decoded = json.loads(
            gathered[p, : int(sizes[p])].tobytes().decode()
        )
        per_proc_spans.append(_rows_to_spans(decoded["spans"], offsets[p]))
        per_proc_metrics.append(decoded["metrics"])
        # .get: a peer running a pre-health build ships no key — its
        # absence stays visible as {} in the table, never an implied ok
        per_proc_health.append(decoded.get("health") or {})
        # same rule for serving stats (pre-fabric peers ship no key)
        per_proc_serving.append(decoded.get("serving") or {})
        # and for request-lifecycle rows (pre-reqtrace peers ship none)
        per_proc_reqtrace.append(decoded.get("reqtrace") or [])
    return ClusterSnapshot(
        offsets=offsets,
        spans=per_proc_spans,
        metrics=per_proc_metrics,
        health=per_proc_health,
        serving=per_proc_serving,
        reqtrace=per_proc_reqtrace,
        nproc=len(sizes),
    )


def merged_chrome_trace(snapshot: ClusterSnapshot) -> dict:
    """One Chrome-trace/Perfetto dict for the whole job: one process
    block per DCN process, every block against process 0's clock, so
    cross-process causality (a collective's simultaneous appearance on
    every track) is visible in the viewer.

    When the snapshot carries ``reqtrace`` rows, every process's rows
    are CONCATENATED and rendered as one ``requests`` process (one
    thread per rid) — a rid whose lifecycle hopped shards after a
    member kill therefore appears as a single continuous track, its
    diverted → rerouted chain visible across the kill."""
    from .export import to_chrome_trace

    all_spans = [s for spans in snapshot["spans"] for s in spans]
    t_base = min((s.t0 for s in all_spans), default=0.0)
    events: list[dict] = []
    for p, spans in enumerate(snapshot["spans"]):
        block = to_chrome_trace(
            spans, process_name=f"dcn process {p}", pid=p + 1,
            t_base=t_base,
        )
        events.extend(block["traceEvents"])
    req_rows = [r for rows in snapshot.get("reqtrace") or [] for r in rows]
    if req_rows:
        from ..obs.reqtrace import request_chrome_events

        # one call over the concatenation — the shared epoch base and
        # the per-rid thread map are what fuse cross-shard chains
        events.extend(request_chrome_events(req_rows))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def collective_consistency(
    snapshot: ClusterSnapshot, kind: str = "dcn-exchange"
) -> float:
    """Cross-process monotonic-consistency margin of the merged trace,
    in seconds (the acceptance gate's number).

    A blocking collective cannot COMPLETE on any process before every
    process has ENTERED it.  For the k-th span of ``kind`` on each
    process (the SPMD contract makes the k-th collective the same
    collective everywhere), the aligned timeline must therefore satisfy
    ``min_i(end_i_k) >= max_i(start_i_k)`` up to alignment error.
    Returns the WORST margin ``min_i(end) - max_i(start)`` across all k
    — positive means every collective's spans mutually overlap after
    alignment; a negative value beyond the probe's error bound means the
    clock alignment is wrong."""
    per_proc = [
        [s for s in spans if s.kind == kind] for spans in snapshot["spans"]
    ]
    counts = [len(x) for x in per_proc]
    n_collectives = min(counts) if counts else 0
    if n_collectives == 0:
        # a vacuous pass here would report "perfectly aligned" with zero
        # supporting evidence (e.g. one process's tracer never enabled,
        # or its ring wrapped past every exchange span) — loud, not inf
        raise ValueError(
            f"no {kind!r} spans present on every process — nothing to "
            "check alignment against (tracer off on some process, or its "
            "ring wrapped?)"
        )
    if len(set(counts)) > 1:
        # SPMD makes every process record the same collective sequence;
        # unequal counts mean some process LOST spans (ring wrap drops
        # oldest-first), so index-pairing would compare collective k
        # against collective k+M and report a false seconds-scale
        # negative margin — the clocks would look broken when only the
        # ring was too small
        raise ValueError(
            f"unequal {kind!r} span counts across processes {counts} — "
            "index pairing would misalign collectives (ring wrapped on "
            "the busiest process? raise Tracer capacity)"
        )
    worst = float("inf")
    for k in range(n_collectives):
        starts = [p[k].t0 for p in per_proc]
        ends = [p[k].t1 for p in per_proc]
        worst = min(worst, min(ends) - max(starts))
    return worst
