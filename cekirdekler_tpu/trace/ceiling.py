"""The overlap ceiling, re-derived per rep — a ruler that bounds from
above.

VERDICT r5 #4: the round-5 artifact reported
``overlap_compute_bound_vs_ceiling: 1.15`` — achieved overlap EXCEEDED
the "ceiling", so the ceiling wasn't one.  Root causes, both fixed here:

1. **Cross-rep mixing.**  The old model computed one ceiling from the
   per-phase MEDIANS across all reps while the achieved overlap came
   from the same medians of DIFFERENT samples — on a link whose
   bandwidth drifts 2× within a measurement, the ceiling's duplex
   capacity and the achieved pipelined time were measured under
   different weather.  Here every rep carries its OWN complete sample
   (r, c, w, pipelined, h2d, d2h, duplex — all from the same
   interleaved round), the ratio is computed per rep, and the artifact
   reports the median WITH the per-rep spread.

2. **No witness clamp.**  A measured pipelined run is an existence
   proof: the best reachable pipelined time cannot exceed a time that
   was actually reached in the same rep.  The model's
   ``p_model = max(c, rw_eff) + (r + w)/blobs`` is a prediction built
   from probe measurements; when the engine beats it (duplex capacity
   probed low, fill/drain edge over-charged), the truth is that the
   model under-predicted — so the per-rep ceiling is
   ``p_best = min(p_model, p_measured)``.  This pins
   ``achieved_vs_ceiling ≤ 1.0`` STRUCTURALLY (a broken model shows up
   as the ratio saturating at 1.0 with ``model_beaten`` flagged, never
   as a ratio above 1), while a genuinely under-achieving engine still
   reads < 1 — the clamp only moves the ceiling DOWN to witnessed
   reality, never the achievement up.

Model terms (same physics as before, now same-rep): compute rides the
chip and overlaps transfers freely; reads and writes share the host
link and overlap each other only to the measured duplex degree
``dc = (h2d + d2h − duplex) / min(h2d, d2h)`` (clamped to [0, 1]); and
every blob schedule pays the fill/drain edge — the first blob's upload
runs before any compute and the last blob's download after its compute,
one blob's worth of r and of w that no schedule hides.
"""

from __future__ import annotations

from dataclasses import dataclass
from statistics import median as _median

__all__ = ["RepSample", "rep_ceiling", "ceiling_report"]


@dataclass
class RepSample:
    """One interleaved round's complete measurement (milliseconds)."""

    r: float          # read (H2D) phase
    c: float          # compute phase
    w: float          # write (D2H) phase
    p: float          # pipelined total
    h2d: float        # pure-H2D duplex probe
    d2h: float        # pure-D2H duplex probe
    dup: float        # simultaneous H2D ∥ D2H probe


def rep_ceiling(s: RepSample, blobs: int) -> dict:
    """One rep's ceiling + achieved + ratio, from that rep alone."""
    serial = s.r + s.c + s.w
    ideal = serial - max(s.r, s.c, s.w)
    dd = s.h2d + s.d2h - max(s.h2d, s.d2h)   # = min(h2d, d2h)
    dc = (s.h2d + s.d2h - s.dup) / dd if dd > 1e-9 else 0.0
    dc = min(max(dc, 0.0), 1.0)
    rw_eff = s.r + s.w - dc * min(s.r, s.w)
    p_model = max(s.c, rw_eff) + (s.r + s.w) / max(blobs, 1)
    p_best = min(p_model, s.p)  # witness clamp — see module docstring
    achieved = (serial - s.p) / ideal if ideal > 1e-9 else 0.0
    # the ratio divides by the RAW ceiling: p_best <= p makes
    # ceil_raw >= achieved, so achieved/ceil_raw <= 1 structurally even
    # when within-rep noise pushes both above 1 (the display value is
    # clamped to [0, 1], the ratio's denominator is not — clamping the
    # denominator first is exactly how a >1 "vs ceiling" escapes again).
    # The achieved NUMERATOR is floored at 0: a rep where pipelining ran
    # slower than serial (contention noise) achieved none of the
    # ceiling, and letting its negative ratio into the median would turn
    # "fraction of ceiling" into an unbounded-below quantity — the raw
    # achieved_overlap is returned alongside, and ceiling_report counts
    # such reps (negative_overlap_reps) so they are visible, not hidden
    ceil_raw = (serial - p_best) / ideal if ideal > 1e-9 else 0.0
    ratio = max(achieved, 0.0) / ceil_raw if ceil_raw > 1e-9 else None
    return {
        "duplex_capacity": dc,
        "p_model_ms": p_model,
        "p_best_ms": p_best,
        "model_beaten": s.p < p_model,   # the clamp fired this rep
        "achieved_overlap": achieved,
        "overlap_ceiling": min(max(ceil_raw, 0.0), 1.0),
        "achieved_vs_ceiling": ratio,
    }


def ceiling_report(samples: list[RepSample], blobs: int) -> dict:
    """Per-rep ceilings reduced to the artifact keys.

    ``achieved_vs_ceiling`` is the MEDIAN of the per-rep ratios (each
    structurally ≤ 1.0), ``achieved_vs_ceiling_spread`` the max−min of
    the per-rep ratios — the honesty channel: a large spread says the
    link drifted and the median should be read loosely."""
    reps = [rep_ceiling(s, blobs) for s in samples]
    if not reps:
        # degrade like the empty-ratios tail below, not a stdlib
        # StatisticsError from the first median
        return {
            "duplex_capacity": None, "overlap_ceiling": None,
            "model_beaten_reps": 0, "negative_overlap_reps": 0,
            "n_reps": 0, "per_rep_achieved_vs_ceiling": [],
            "achieved_vs_ceiling": None,
            "achieved_vs_ceiling_spread": None,
        }
    ratios = [r["achieved_vs_ceiling"] for r in reps
              if r["achieved_vs_ceiling"] is not None]
    out: dict = {
        "duplex_capacity": round(_median([r["duplex_capacity"] for r in reps]), 3),
        "overlap_ceiling": round(_median([r["overlap_ceiling"] for r in reps]), 4),
        "model_beaten_reps": sum(1 for r in reps if r["model_beaten"]),
        "negative_overlap_reps": sum(
            1 for r in reps if r["achieved_overlap"] < 0
        ),
        "n_reps": len(reps),
        "per_rep_achieved_vs_ceiling": [
            round(x, 3) for x in ratios
        ],
    }
    if ratios:
        out["achieved_vs_ceiling"] = round(_median(ratios), 3)
        out["achieved_vs_ceiling_spread"] = round(max(ratios) - min(ratios), 3)
    else:
        out["achieved_vs_ceiling"] = None
        out["achieved_vs_ceiling_spread"] = None
    return out
