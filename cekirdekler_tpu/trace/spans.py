"""Typed span recording: the host-side half of the attribution subsystem.

The reference's only observability is host-side stopwatches scattered
through the runtime (SURVEY §5.1; Worker.cs:753-807, Cores.cs:994-1063)
and its planned timeline-overlap query is a ``NotImplementedException``
(ClPipeline.cs:2391-2399).  This module replaces ad-hoc stopwatches with
ONE process-global :class:`Tracer`: every runtime layer (worker phases,
both cores pipeline engines, device pipelines, pools, the DCN tier)
records typed :class:`Span` records into a fixed-capacity ring buffer,
tagged with compute id and lane, so a lost millisecond anywhere in the
stack has a name.

Design constraints, in order:

1. **Disabled is free.**  The tracer ships enabled on no hot path by
   default; instrumentation sites pay two attribute reads and a falsy
   check (<1 µs per would-be span, measured by
   ``tests/test_trace.py::test_disabled_tracer_overhead``).  The
   convention at hot sites is the ``t0()``/``record()`` pair::

       t0 = TRACER.t0()          # 0.0 when disabled — no clock read
       ...work...
       TRACER.record("launch", t0, cid=cid, lane=self.index)

2. **Lock-free-ish.**  Recording is one ``itertools.count`` increment
   (atomic under the GIL) plus one list-slot store — concurrent worker
   threads never contend on a lock to record.  The ring overwrites the
   oldest spans when full; ``total_recorded`` keeps the true count so a
   wrapped buffer is detectable, never silent.

3. **Monotonic clocks.**  All timestamps are ``time.perf_counter()``
   seconds, comparable across threads within the process (the exchange
   rate to device-side Xprof events is handled by
   ``trace/attribution.py``, which reconciles totals, not timestamps).

Span kinds used by the built-in instrumentation (callers may add more):
``enqueue`` (a compute() dispatch), ``split`` (first range table),
``rebalance`` (the balancer moved shares), ``launch`` (kernel dispatch),
``fence`` (retirement wait), ``upload`` (H2D), ``download`` (D2H),
``upload-chunk`` / ``download-chunk`` (one ladder-aligned chunk of a
STREAMED partition transfer — the chunked double-buffered H2D/D2H path,
``Cores._run_streamed``; the monolithic kinds above stay for whole-range
transfers so the two paths are distinguishable in every report),
``pipeline-stage`` (one pipeline engine/stage body), ``pool-task``
(device-pool task), ``dcn-exchange`` (cross-host collective), ``fused``
(fused-iteration window flush — spans tag ``xK`` for a K-iteration
ladder dispatch; zero-duration instants tag ``disengage:<reason>`` when
the fused path falls back to per-iteration dispatch, so a silent perf
regression to the slow path is attributable), ``driver-error`` (a
dispatch-driver closure failed — the instant is recorded at failure
time, before the error surfaces at the caller's sync point, so a
postmortem's span ring names the failing dispatch).
"""

from __future__ import annotations

import itertools
import threading
import time
from contextlib import contextmanager
from typing import Iterable, NamedTuple

__all__ = ["Span", "Tracer", "TRACER", "SPAN_KINDS", "tracing"]

SPAN_KINDS = (
    "enqueue", "split", "rebalance", "launch", "fence",
    "upload", "download", "upload-chunk", "download-chunk",
    "pipeline-stage", "pool-task", "dcn-exchange",
    "fused", "driver-error",
)


class Span(NamedTuple):
    """One timed event.  ``t0``/``t1`` are perf_counter seconds; ``cid``
    is the compute id (None where no compute id applies), ``lane`` the
    worker/consumer index, ``tag`` a short free-form annotation."""

    kind: str
    t0: float
    t1: float
    cid: int | None = None
    lane: int | None = None
    tag: str | None = None

    @property
    def dur_ms(self) -> float:
        return (self.t1 - self.t0) * 1000.0


class Tracer:
    """Process-global span recorder (one instance: :data:`TRACER`).

    ``enabled`` is a plain attribute on purpose: the disabled fast path
    must be an attribute read, not a property call."""

    def __init__(self, capacity: int = 65536):
        self.enabled = False
        self._cap = max(16, int(capacity))
        self._buf: list[Span | None] = [None] * self._cap
        self._count = itertools.count()
        self._total = 0
        self._lock = threading.Lock()  # enable/clear only — never record()
        # ring-wrap losses already exported to the metrics registry
        # (ck_trace_dropped_spans_total) — the delta tracking that keeps
        # the counter monotonic across snapshots within one ring epoch
        self._dropped_reported = 0

    # -- recording (hot path) ------------------------------------------------
    def t0(self) -> float:
        """Span-open timestamp, or 0.0 when disabled (no clock read)."""
        return time.perf_counter() if self.enabled else 0.0

    def record(
        self,
        kind: str,
        t0: float,
        cid: int | None = None,
        lane: int | None = None,
        tag: str | None = None,
        t1: float | None = None,
    ) -> None:
        """Close and store a span opened at ``t0``.  No-op when disabled
        or when ``t0`` is the disabled sentinel (0.0) — a site that
        opened its span while the tracer was off records nothing even if
        the tracer was enabled mid-span."""
        if not self.enabled or not t0:
            return
        i = next(self._count)  # GIL-atomic slot claim — no lock
        buf = self._buf
        # index by the captured buffer's OWN length, not self._cap: a
        # concurrent enable(capacity=...) swaps buffer and cap in two
        # steps, and mixing one thread's buffer with the other's modulus
        # would IndexError inside instrumented real work
        buf[i % len(buf)] = Span(
            kind, t0, t1 if t1 is not None else time.perf_counter(),
            cid, lane, tag,
        )
        self._total = i + 1  # approximate under races; reporting only

    def instant(
        self,
        kind: str,
        cid: int | None = None,
        lane: int | None = None,
        tag: str | None = None,
    ) -> None:
        """Zero-duration marker (e.g. a rebalance decision)."""
        if not self.enabled:
            return
        t = time.perf_counter()
        self.record(kind, t, cid=cid, lane=lane, tag=tag, t1=t)

    @contextmanager
    def span(
        self,
        kind: str,
        cid: int | None = None,
        lane: int | None = None,
        tag: str | None = None,
    ):
        """Context-manager convenience for non-hot sites; records even
        when the body raises (the failing span is usually the one you
        want to see)."""
        t0 = self.t0()
        try:
            yield
        finally:
            self.record(kind, t0, cid=cid, lane=lane, tag=tag)

    # -- control -------------------------------------------------------------
    def enable(self, capacity: int | None = None, clear: bool = True) -> None:
        pending_drops = 0
        with self._lock:
            # export wrap losses BEFORE any reset below zeroes the
            # baseline: "raise Tracer capacity" (the report's own
            # advice) must not silently eat the losses that motivated it
            pending_drops = self._drop_delta_locked()
            if capacity is not None and capacity != self._cap:
                # resizing rebuilds the ring; with clear=False the newest
                # existing spans migrate so keep=True keeps its promise,
                # and the counters restart so total_recorded/ring-wrap
                # reporting describes the NEW buffer, not the old one
                keep = [] if clear else self._snapshot_locked_free()
                self._cap = max(16, int(capacity))
                self._clear_locked()
                for s in keep[-self._cap:]:
                    i = next(self._count)
                    self._buf[i % self._cap] = s
                    self._total = i + 1
            elif clear:
                self._clear_locked()
            self.enabled = True
        self._inc_dropped(pending_drops)

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            pending_drops = self._drop_delta_locked()
            self._clear_locked()
        self._inc_dropped(pending_drops)

    def _clear_locked(self) -> None:
        self._buf = [None] * self._cap
        self._count = itertools.count()
        self._total = 0
        self._dropped_reported = 0

    # -- inspection ----------------------------------------------------------
    @property
    def total_recorded(self) -> int:
        """Spans recorded since the last clear — exceeds ``capacity``
        when the ring wrapped (older spans were overwritten)."""
        return self._total

    @property
    def dropped_spans(self) -> int:
        """Spans LOST to ring wrap since the last clear (oldest-first
        overwrites) — the count every coverage report must carry:
        attribution totals silently undercount by exactly these spans."""
        return max(0, self._total - self._cap)

    def _sync_dropped_metric(self) -> None:
        """Export ring-wrap losses to ``ck_trace_dropped_spans_total``.
        Called from snapshot() (a cold path) rather than record(): the
        recording path's lock-free contract must not pay a registry
        lock per span once the ring wraps.  Delta-based so the counter
        stays monotonic across repeated snapshots; a clear() resets the
        baseline with the ring.  The delta read-modify-write runs under
        the tracer lock — two concurrent snapshots (the debug server's
        /tracez thread + an in-process report) would otherwise both see
        the same baseline and double-count the loss."""
        with self._lock:
            delta = self._drop_delta_locked()
        self._inc_dropped(delta)

    def _drop_delta_locked(self) -> int:
        """Unreported ring-wrap loss; advances the baseline.  Caller
        holds the tracer lock."""
        d = self.dropped_spans
        delta = d - self._dropped_reported
        if delta <= 0:
            return 0
        self._dropped_reported = d
        return delta

    @staticmethod
    def _inc_dropped(delta: int) -> None:
        if delta <= 0:
            return
        from ..metrics.registry import REGISTRY

        REGISTRY.counter(
            "ck_trace_dropped_spans_total",
            "spans lost to tracer ring wrap (attribution undercounts)",
        ).inc(delta)

    @property
    def capacity(self) -> int:
        return self._cap

    def _snapshot_locked_free(self) -> list[Span]:
        """The span copy alone — no metric sync, no lock.  enable()'s
        keep-path calls this while HOLDING the tracer lock (snapshot()
        there would deadlock on the non-reentrant lock via
        _sync_dropped_metric)."""
        buf = list(self._buf)  # one slice: consistent-enough view
        spans = [s for s in buf if s is not None]
        spans.sort(key=lambda s: s.t0)
        return spans

    def snapshot(self) -> list[Span]:
        """Recorded spans, oldest first.  Concurrent recording during
        the snapshot may drop/duplicate a span at the wrap edge — the
        snapshot is for reporting, not for synchronization."""
        self._sync_dropped_metric()
        return self._snapshot_locked_free()

    def spans_between(self, t_lo: float, t_hi: float) -> list[Span]:
        """Spans that overlap the window [t_lo, t_hi]."""
        return [s for s in self.snapshot() if s.t1 >= t_lo and s.t0 <= t_hi]


#: The process-global tracer every built-in instrumentation site uses.
TRACER = Tracer()


@contextmanager
def tracing(capacity: int | None = None, keep: bool = False,
            metrics: bool = False):
    """Scoped enable of the global tracer::

        with trace.tracing() as tr:
            ...instrumented work...
        report = attribution.window_report(tr.snapshot(), t0, t1)

    Disables on exit; spans survive (``keep`` preserves pre-existing
    spans instead of clearing on entry).  ``metrics=True`` additionally
    turns on registry sampling for the window
    (``metrics.REGISTRY.enable_sampling``), so the counter time series
    for Perfetto counter tracks cover exactly the traced window::

        with trace.tracing(metrics=True) as tr:
            ...work...
        trace.save_chrome_trace(
            tr.snapshot(), path,
            counters=metrics.REGISTRY.counter_series())
    """
    TRACER.enable(capacity=capacity, clear=not keep)
    if metrics:
        from ..metrics.registry import REGISTRY as _REG

        _REG.enable_sampling()
    try:
        yield TRACER
    finally:
        TRACER.disable()
        if metrics:
            from ..metrics.registry import REGISTRY as _REG

            _REG.disable_sampling()


def spans_by_kind(spans: Iterable[Span]) -> dict[str, list[Span]]:
    out: dict[str, list[Span]] = {}
    for s in spans:
        out.setdefault(s.kind, []).append(s)
    return out
