"""Device-timeline attribution: per-kernel Xprof profiles unified with
host spans, plus the roofline view and a persistent kernel-profile store.

The host-side attribution plane (spans, window reports, flight recorder)
names every lost *host* millisecond; device time was a black box
inferred from fences.  This module closes that gap on the
``jax.profiler`` capture seam (``utils/timeline.py``):

1. **Marks.**  Every ladder/chunk launch is tagged with a
   ``jax.profiler.TraceAnnotation`` named
   ``ck|k=<kernel>|c=<cid>|l=<lane>|s=<seq>`` (:data:`MARKS`,
   :meth:`DeviceMarks.begin` / :meth:`DeviceMarks.end` — the worker
   launch paths call them behind a plain ``.enabled`` check, the same
   disabled-is-free discipline as the tracer; the pair is a declared
   ckcheck hot root).  The same mark is recorded HOST-side with
   ``perf_counter`` timestamps, so every mark exists on both clocks.

2. **Capture.**  :class:`DeviceCapture` wraps a traced window: start
   the profiler (``timeline.start_profiler``), enable marks, run the
   window, stop, then parse the dump and correlate device ops back to
   marks.  Profiler-off and CPU-only rigs degrade to a NAMED absence
   (``report.absent`` carries the reason) — never a crash, and never a
   silently-partial number.

3. **Correlation contract** (:func:`correlate`), three tiers, each
   counted in the report so coverage is explicit:

   - *explicit*: a device op that carries the mark (``args`` with
     ``ck-seq``, or a ``ck|`` mark string in its name/args) attaches
     directly — the synthetic-Xprof fixture format, and what rigs with
     annotation propagation produce;
   - *kernel-name*: a device op whose name mentions a marked kernel
     attaches to the nearest preceding mark for that kernel (XLA
     module/op names usually embed the jitted function name);
   - *stream-order*: anything else attaches to the latest mark
     dispatched at or before the op's start — the same stream-order
     bound the per-cid fence split documents.  Ops matching no tier
     stay unattributed and count against ``coverage_frac``.

4. **Outputs.**  A :class:`DeviceWindowReport` (per-kernel device wall,
   op counts, inter-op idle gaps, per-lane busy, reconciled against the
   host window), :func:`roofline_row` (arithmetic intensity vs the
   machine roofline, Williams et al. 2009, from the flop/byte counts
   the workloads already compute), :func:`unified_chrome_trace` (device
   ops as per-lane device tracks beside the host span tracks on ONE
   clock — the mark pairs are the perf_counter↔trace-clock anchor), and
   :class:`ProfileStore` — an on-disk, append-only store keyed by
   (kernel signature, shape, blocks): the evidence base a block-shape
   autotuner reads instead of re-measuring.

Like the rest of ``trace/``, nothing here imports jax at module level.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, NamedTuple, Sequence

from .spans import Span
# interval-union reduction shared with the busy/span analyzer — one
# implementation (utils/timeline.py), two consumers, no drift
from ..utils.timeline import _merged_busy as _union_us

__all__ = [
    "DEVICE_SPAN_KINDS",
    "DeviceMarks",
    "MARKS",
    "Mark",
    "DeviceOp",
    "KernelDeviceProfile",
    "DeviceWindowReport",
    "DeviceCapture",
    "capture_device",
    "parse_trace_dump",
    "correlate",
    "roofline_row",
    "unified_chrome_trace",
    "split_unified_trace",
    "ProfileStore",
    "STORE",
    "profilez_payload",
    "last_report",
]

#: Event kinds the UNIFIED Perfetto export places on device tracks
#: (``cat: "ck-dev"``).  ``tools/lint_obs.py`` cross-checks this tuple
#: against the device-track kind table in docs/OBSERVABILITY.md, both
#: directions — the same contract as SPAN_KINDS / EVENT_KINDS.
#: ``device-op`` — one device op interval (name carries the attributed
#: kernel); ``device-mark`` — a launch mark replayed onto the device
#: process so the dispatch edge is visible next to the ops it explains.
DEVICE_SPAN_KINDS = ("device-op", "device-mark")

#: Mark-name prefix in the Xprof dump.  Format:
#: ``ck|k=<kernel>|c=<cid>|l=<lane>|s=<seq>`` (``c=-`` when no cid).
MARK_PREFIX = "ck|"

#: Store schema tag — bump on incompatible row changes.
STORE_SCHEMA = "ck-kernel-profile-v1"

#: Environment variable naming the persistent profile-store directory.
PROFILE_STORE_ENV = "CK_PROFILE_STORE"

#: Default machine roofline (TPU v5e public spec) — callers with a
#: different rig pass their own peaks to :func:`roofline_row`.
V5E_PEAK_BF16_TFLOPS = 197.0
V5E_HBM_GBPS = 819.0


# ---------------------------------------------------------------------------
# marks: the launch-side half of the correlation
# ---------------------------------------------------------------------------

class Mark(NamedTuple):
    """One annotated launch, host-clock side.  ``t0``/``t1`` are
    ``perf_counter`` seconds (``t1`` 0.0 until :meth:`DeviceMarks.end`
    closes it)."""

    seq: int
    kernel: str
    cid: int | None
    lane: int | None
    t0: float
    t1: float = 0.0


def _mark_name(kernel: str, cid: int | None, lane: int | None,
               seq: int) -> str:
    return (f"{MARK_PREFIX}k={kernel}"
            f"|c={'-' if cid is None else cid}"
            f"|l={'-' if lane is None else lane}|s={seq}")


def parse_mark_name(name: str) -> dict | None:
    """``ck|k=...|c=...|l=...|s=...`` → field dict, or None when the
    name is not a mark."""
    if not name.startswith(MARK_PREFIX):
        return None
    out: dict = {"kernel": "?", "cid": None, "lane": None, "seq": None}
    for part in name[len(MARK_PREFIX):].split("|"):
        if "=" not in part:
            continue
        k, v = part.split("=", 1)
        if k == "k":
            out["kernel"] = v
        elif k in ("c", "l", "s") and v not in ("-", ""):
            try:
                out[{"c": "cid", "l": "lane", "s": "seq"}[k]] = int(v)
            except ValueError:
                pass
    return out if out["seq"] is not None else None


class DeviceMarks:
    """Process-global launch annotator (one instance: :data:`MARKS`).

    ``enabled`` is a plain attribute — the tracer convention: the
    disabled fast path at a launch site is one attribute read plus a
    falsy check, nothing allocated, no clock read.  Enabled, each
    ``begin``/``end`` pair opens/closes a ``jax.profiler.TraceAnnotation``
    around the dispatch AND records the host-clock :class:`Mark` — the
    same (seq, kernel, cid, lane) on both clocks is what anchors the
    unified timeline.  Recording is one GIL-atomic ``deque.append``
    (the flight-recorder ring discipline); no lock is ever taken on the
    launch path."""

    def __init__(self, capacity: int = 65536):
        self.enabled = False
        self._ring: deque[Mark] = deque(maxlen=max(16, int(capacity)))
        self._seq = itertools.count(1)
        self._ann_cls = None  # jax.profiler.TraceAnnotation, cached on enable

    # -- hot path (declared ckcheck hot root) --------------------------------
    def begin(self, kernel_names, cid: int | None, lane: int | None):
        """Open a mark around a launch dispatch; returns an opaque token
        for :meth:`end`, or None when disabled (callers pass it back
        unconditionally — ``end(None)`` is a no-op)."""
        if not self.enabled:
            return None
        seq = next(self._seq)
        kernel = "+".join(kernel_names) if not isinstance(kernel_names, str) \
            else kernel_names
        ann = None
        if self._ann_cls is not None:
            try:
                ann = self._ann_cls(_mark_name(kernel, cid, lane, seq))
                ann.__enter__()
            except Exception:  # noqa: BLE001 - marking must never sink a launch
                ann = None
        return (ann, seq, kernel, cid, lane, time.perf_counter())

    def end(self, token) -> None:
        """Close a mark opened by :meth:`begin` (no-op on None)."""
        if token is None:
            return
        ann, seq, kernel, cid, lane, t0 = token
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:  # noqa: BLE001
                pass
        self._ring.append(
            Mark(seq, kernel, cid, lane, t0, time.perf_counter()))

    # -- control / inspection (cold) -----------------------------------------
    def enable(self, clear: bool = True) -> None:
        if clear:
            self._ring.clear()
        if self._ann_cls is None:
            try:
                import jax.profiler as _prof

                self._ann_cls = _prof.TraceAnnotation
            except Exception:  # noqa: BLE001 - host marks still work
                self._ann_cls = None
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def snapshot(self) -> list[Mark]:
        return sorted(self._ring, key=lambda m: m.seq)

    @property
    def total_recorded(self) -> int:
        return len(self._ring)


#: The process-global marker every launch site uses.
MARKS = DeviceMarks()


# ---------------------------------------------------------------------------
# dump parsing
# ---------------------------------------------------------------------------

class DeviceOp(NamedTuple):
    """One device-side op interval from the Xprof dump.  ``ts``/``dur``
    are the dump's microseconds (trace clock); ``kernel``/``seq`` are
    filled by :func:`correlate` (``kernel`` is ``"?"`` while
    unattributed), ``matched_by`` names the tier that attributed it."""

    device: str
    pid: int
    tid: int
    name: str
    ts: float
    dur: float
    args: dict
    kernel: str = "?"
    seq: int | None = None
    cid: int | None = None
    lane: int | None = None
    matched_by: str | None = None


@dataclass
class TraceDump:
    """Parsed view of one trace dir: device ops + the marks found in
    the dump (trace-clock side)."""

    path: str | None = None
    ops: list = field(default_factory=list)        # [DeviceOp]
    dump_marks: dict = field(default_factory=dict)  # seq -> {ts, dur, fields}
    devices: list = field(default_factory=list)
    n_events: int = 0


#: Device-track preference order: "XLA Ops" is the per-op track; "XLA
#: Modules" the per-executable fallback on dumps without op tracks
#: (counting both would double-count the same intervals).
_TRACK_PREFERENCE = ("XLA Ops", "XLA Modules")


def parse_trace_dump(trace_dir: str) -> TraceDump:
    """Parse the newest trace file under ``trace_dir`` into device ops
    and dump-side marks.  Real dumps and the synthetic-Xprof fixture
    format share the schema: ``M`` metadata events name device
    processes (``/device:...``) and their op tracks; ``X`` events on
    those tracks are device ops; ``X`` events named ``ck|...``
    (anywhere — host thread or device track) are marks."""
    from ..utils.timeline import load_trace_events

    path, events = load_trace_events(trace_dir)
    dump = TraceDump(path=path, n_events=len(events))
    if not events:
        return dump
    device_pids: dict[int, str] = {}
    tracks: dict[tuple[int, int], str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            name = e.get("args", {}).get("name", "")
            if "/device:" in name or name.startswith("device:"):
                device_pids[e["pid"]] = name
        elif e.get("name") == "thread_name":
            tracks[(e.get("pid"), e.get("tid"))] = \
                e.get("args", {}).get("name", "")
    # pick ONE track kind per device pid (preference order) so module-
    # and op-level views of the same interval never double-count
    use_tracks: set[tuple[int, int]] = set()
    for pid in device_pids:
        pid_tracks = {k: v for k, v in tracks.items() if k[0] == pid}
        chosen = None
        for pref in _TRACK_PREFERENCE:
            hit = {k for k, v in pid_tracks.items() if v == pref}
            if hit:
                chosen = hit
                break
        use_tracks |= chosen if chosen is not None else set(pid_tracks)
    for e in events:
        if e.get("ph") != "X":
            continue
        name = str(e.get("name", ""))
        args = e.get("args", {}) or {}
        if name.startswith(MARK_PREFIX):
            fields = parse_mark_name(name)
            if fields is not None:
                dump.dump_marks[fields["seq"]] = {
                    "ts": float(e.get("ts", 0.0)),
                    "dur": float(e.get("dur", 0.0)),
                    **fields,
                }
            continue
        pid = e.get("pid")
        if pid not in device_pids:
            continue
        key = (pid, e.get("tid"))
        if use_tracks and key not in use_tracks and \
                (pid, None) not in use_tracks:
            continue
        dump.ops.append(DeviceOp(
            device=device_pids[pid], pid=int(pid), tid=int(e.get("tid", 0)),
            name=name, ts=float(e.get("ts", 0.0)),
            dur=float(e.get("dur", 0.0)), args=dict(args),
        ))
    dump.ops.sort(key=lambda o: o.ts)
    dump.devices = sorted({o.device for o in dump.ops} | set(
        device_pids.values()))
    return dump


# ---------------------------------------------------------------------------
# correlation
# ---------------------------------------------------------------------------



@dataclass
class KernelDeviceProfile:
    """One kernel's device-side account inside a captured window.  All
    times in milliseconds of DEVICE wall (union of op intervals per
    device track, summed over tracks — concurrent lanes legitimately
    sum past the host wall; the per-track union never does)."""

    kernel: str
    device_ms: float = 0.0
    op_count: int = 0
    launches: int = 0            # distinct marks attributed to
    idle_ms: float = 0.0         # inter-op gaps inside this kernel's stream
    per_lane_ms: dict = field(default_factory=dict)   # lane -> busy ms
    cids: list = field(default_factory=list)
    matched_by: dict = field(default_factory=dict)    # tier -> op count

    def to_dict(self) -> dict:
        return {
            "kernel": self.kernel,
            "device_ms": round(self.device_ms, 3),
            "op_count": self.op_count,
            "launches": self.launches,
            "idle_ms": round(self.idle_ms, 3),
            "per_lane_ms": {
                str(k): round(v, 3) for k, v in sorted(
                    self.per_lane_ms.items(),
                    key=lambda kv: (kv[0] is None, kv[0]))
            },
            "cids": self.cids,
            "matched_by": dict(self.matched_by),
        }


@dataclass
class DeviceWindowReport:
    """The reconciled device-side account of one captured host window.

    The reconciliation contract (never silently partial): per-track
    device busy is a UNION (≤ the window wall per track), attribution
    is explicit (``coverage_frac`` = attributed / device busy, with the
    unattributed remainder carried as ``unattributed_ms``), and a
    report that could not be produced at all says why in ``absent``."""

    wall_ms: float = 0.0          # host window wall (0 when unknown)
    device_span_ms: float = 0.0   # first device event → last, on device
    device_busy_ms: float = 0.0   # union per track, summed over tracks
    attributed_ms: float = 0.0
    unattributed_ms: float = 0.0
    kernels: list = field(default_factory=list)   # [KernelDeviceProfile]
    per_lane_overlap: dict = field(default_factory=dict)  # lane -> busy/wall
    n_ops: int = 0
    n_marks: int = 0
    n_dump_marks: int = 0
    devices: list = field(default_factory=list)
    anchor: str | None = None     # "marks" | "capture-start" | None
    anchor_offset_s: float | None = None  # perf_counter s − trace ts s
    matched_by: dict = field(default_factory=dict)
    clipped_ops: int = 0
    trace_path: str | None = None
    absent: str | None = None     # the named-absence reason
    #: the window-clipped, attribution-tagged ops (NOT serialized by
    #: to_dict — the unified Perfetto export consumes them)
    ops: list = field(default_factory=list, repr=False)

    @property
    def coverage_frac(self) -> float:
        """Fraction of device-busy time attributed to a kernel — the
        number that must be read BEFORE any per-kernel row (a low
        coverage means the rows undercount, and the report says by
        exactly how much via ``unattributed_ms``)."""
        return (self.attributed_ms / self.device_busy_ms
                if self.device_busy_ms > 0 else 0.0)

    def kernel(self, name: str) -> KernelDeviceProfile | None:
        for k in self.kernels:
            if k.kernel == name:
                return k
        return None

    def to_dict(self) -> dict:
        return {
            "absent": self.absent,
            "wall_ms": round(self.wall_ms, 3),
            "device_span_ms": round(self.device_span_ms, 3),
            "device_busy_ms": round(self.device_busy_ms, 3),
            "attributed_ms": round(self.attributed_ms, 3),
            "unattributed_ms": round(self.unattributed_ms, 3),
            "coverage_frac": round(self.coverage_frac, 4),
            "kernels": [k.to_dict() for k in sorted(
                self.kernels, key=lambda k: -k.device_ms)],
            "per_lane_overlap": {
                str(k): round(v, 4) for k, v in sorted(
                    self.per_lane_overlap.items(),
                    key=lambda kv: (kv[0] is None, kv[0]))
            },
            "n_ops": self.n_ops,
            "n_marks": self.n_marks,
            "n_dump_marks": self.n_dump_marks,
            "devices": self.devices,
            "anchor": self.anchor,
            "matched_by": dict(self.matched_by),
            "clipped_ops": self.clipped_ops,
            "trace_path": self.trace_path,
        }

    def table(self) -> str:
        if self.absent:
            return f"(device profile absent: {self.absent})"
        lines = [
            f"host wall {self.wall_ms:10.3f} ms   device busy "
            f"{self.device_busy_ms:10.3f} ms   attributed "
            f"{self.attributed_ms:10.3f} ms "
            f"({100.0 * self.coverage_frac:.1f}% coverage)",
            f"{'kernel':>24} {'device ms':>12} {'ops':>6} {'launches':>9} "
            f"{'idle ms':>10} {'lanes':>6}",
        ]
        for k in sorted(self.kernels, key=lambda k: -k.device_ms):
            lines.append(
                f"{k.kernel:>24} {k.device_ms:12.3f} {k.op_count:6d} "
                f"{k.launches:9d} {k.idle_ms:10.3f} "
                f"{len(k.per_lane_ms):6d}"
            )
        if self.unattributed_ms > 0:
            lines.append(
                f"{'(unattributed)':>24} {self.unattributed_ms:12.3f}")
        return "\n".join(lines)


def _explicit_seq(op: DeviceOp) -> int | None:
    """Tier-1 evidence on the op itself: a ``ck-seq`` arg, or a mark
    string embedded in the op name or any string arg."""
    v = op.args.get("ck-seq")
    if v is not None:
        try:
            return int(v)
        except (TypeError, ValueError):
            pass
    for s in (op.name, *[a for a in op.args.values() if isinstance(a, str)]):
        i = s.find(MARK_PREFIX)
        if i >= 0:
            fields = parse_mark_name(s[i:].split()[0])
            if fields is not None:
                return fields["seq"]
    return None


def correlate(
    dump: TraceDump,
    marks: Sequence[Mark] = (),
    window: tuple[float, float] | None = None,
    capture_anchor: tuple[float, float] | None = None,
) -> DeviceWindowReport:
    """Attribute the dump's device ops to launch marks and reconcile
    against the host window.

    ``marks`` are the host-side :class:`Mark` records captured around
    the window; ``window`` is the host ``(perf_t0, perf_t1)`` wall;
    ``capture_anchor`` is ``(perf_counter_at_start, trace_ts_us_origin)``
    — the fallback clock anchor when no mark appears in the dump."""
    report = DeviceWindowReport(trace_path=dump.path)
    if window is not None:
        report.wall_ms = max(window[1] - window[0], 0.0) * 1000.0
    report.n_marks = len(marks)
    report.n_dump_marks = len(dump.dump_marks)
    report.devices = list(dump.devices)
    if not dump.ops:
        report.absent = (
            "no device op events in the dump (profiler off, or a "
            "CPU-only rig whose backend exposes no device tracks)"
            if dump.n_events else
            "no trace events captured (profiler unavailable)")
        return report

    # -- clock anchor: perf_counter seconds = trace µs * 1e-6 + offset
    by_seq = {m.seq: m for m in marks}
    pairs = [
        (m.t0, dump.dump_marks[m.seq]["ts"])
        for m in marks if m.seq in dump.dump_marks
    ]
    if pairs:
        report.anchor = "marks"
        report.anchor_offset_s = sum(
            t0 - ts * 1e-6 for t0, ts in pairs) / len(pairs)
    elif capture_anchor is not None:
        report.anchor = "capture-start"
        report.anchor_offset_s = (
            capture_anchor[0] - capture_anchor[1] * 1e-6)

    # -- clip ops to the host window (only meaningful with an anchor)
    ops = dump.ops
    if window is not None and report.anchor_offset_s is not None:
        lo_us = (window[0] - report.anchor_offset_s) * 1e6
        hi_us = (window[1] - report.anchor_offset_s) * 1e6
        clipped: list[DeviceOp] = []
        for o in ops:
            s, e = o.ts, o.ts + o.dur
            cs, ce = max(s, lo_us), min(e, hi_us)
            if ce <= cs:
                report.clipped_ops += 1
                continue
            if (cs, ce) != (s, e):
                report.clipped_ops += 1
                o = o._replace(ts=cs, dur=ce - cs)
            clipped.append(o)
        ops = clipped
    report.n_ops = len(ops)
    if not ops:
        report.absent = (
            "every device op fell outside the host window "
            "(clock anchor or window mismatch)")
        return report

    # -- mark timeline on the TRACE clock (dump marks preferred; host
    #    marks mapped through the anchor otherwise)
    mark_ts: list[tuple[float, Mark]] = []
    for m in marks:
        rec = dump.dump_marks.get(m.seq)
        if rec is not None:
            mark_ts.append((rec["ts"], m))
        elif report.anchor_offset_s is not None:
            mark_ts.append(((m.t0 - report.anchor_offset_s) * 1e6, m))
    for seq, rec in dump.dump_marks.items():  # dump-only marks still count
        if seq not in by_seq:
            m = Mark(seq, rec.get("kernel", "?"), rec.get("cid"),
                     rec.get("lane"), 0.0)
            by_seq[seq] = m
            mark_ts.append((rec["ts"], m))
    mark_ts.sort(key=lambda p: p[0])
    by_kernel_ts: dict[str, list[tuple[float, Mark]]] = {}
    for ts, m in mark_ts:
        by_kernel_ts.setdefault(m.kernel, []).append((ts, m))

    def latest_at_or_before(seq_list: list[tuple[float, Mark]],
                            ts: float,
                            fallback_first: bool = False) -> Mark | None:
        """The newest mark dispatched at or before ``ts``.  With
        ``fallback_first`` (the kernel-NAME tier, where the name already
        proved the match and time only picks among same-kernel marks)
        an op preceding every mark takes the first one; the stream-order
        tier must NOT fall back — an op before the first mark was
        dispatched by something unmarked and stays unattributed, or
        coverage_frac could never read below 1.0."""
        best = None
        for mts, m in seq_list:
            if mts <= ts:
                best = m
            else:
                break
        if best is None and fallback_first and seq_list:
            return seq_list[0][1]
        return best

    # -- attribution tiers
    attributed: list[DeviceOp] = []
    for o in ops:
        seq = _explicit_seq(o)
        if seq is not None and seq in by_seq:
            m = by_seq[seq]
            attributed.append(o._replace(
                kernel=m.kernel, seq=seq, cid=m.cid, lane=m.lane,
                matched_by="explicit"))
            continue
        low = o.name.lower()
        hit = None
        # longest kernel name first: an op named "fusion.add_fused.3"
        # must attach to "add_fused", never to a kernel "add" that
        # happened to be marked earlier (substring ambiguity)
        for kernel, seq_list in sorted(
                by_kernel_ts.items(), key=lambda kv: -len(kv[0])):
            if kernel != "?" and kernel.lower() in low:
                hit = latest_at_or_before(seq_list, o.ts,
                                          fallback_first=True)
                if hit is not None:
                    break
        if hit is not None:
            attributed.append(o._replace(
                kernel=hit.kernel, seq=hit.seq, cid=hit.cid,
                lane=hit.lane, matched_by="kernel-name"))
            continue
        m = latest_at_or_before(mark_ts, o.ts)
        if m is not None:
            attributed.append(o._replace(
                kernel=m.kernel, seq=m.seq, cid=m.cid, lane=m.lane,
                matched_by="stream-order"))
        else:
            attributed.append(o)  # unattributed: kernel stays "?"

    # -- reductions: per-track unions so busy never exceeds the wall
    #    per track; per-kernel and per-lane sums over tracks
    all_by_track: dict[tuple[int, int], list] = {}
    for o in attributed:
        all_by_track.setdefault((o.pid, o.tid), []).append(
            (o.ts, o.ts + o.dur))
    report.device_busy_ms = sum(
        _union_us(v) for v in all_by_track.values()) / 1000.0
    lo = min(o.ts for o in attributed)
    hi = max(o.ts + o.dur for o in attributed)
    report.device_span_ms = (hi - lo) / 1000.0

    profiles: dict[str, KernelDeviceProfile] = {}
    lane_tracks: dict[Any, dict[tuple[int, int], list]] = {}
    for o in attributed:
        if o.kernel == "?":
            continue
        p = profiles.setdefault(o.kernel, KernelDeviceProfile(o.kernel))
        p.op_count += 1
        p.matched_by[o.matched_by] = p.matched_by.get(o.matched_by, 0) + 1
        if o.cid is not None and o.cid not in p.cids:
            p.cids.append(o.cid)
        lane_tracks.setdefault(o.lane, {}).setdefault(
            (o.pid, o.tid), []).append((o.ts, o.ts + o.dur))
    # per-kernel busy/idle from per-(kernel, track) unions
    kt: dict[tuple[str, int, int], list] = {}
    for o in attributed:
        if o.kernel == "?":
            continue
        kt.setdefault((o.kernel, o.pid, o.tid), []).append(
            (o.ts, o.ts + o.dur))
    for (kernel, _pid, _tid), iv in kt.items():
        busy = _union_us(iv)
        span = max(e for _s, e in iv) - min(s for s, _e in iv)
        p = profiles[kernel]
        p.device_ms += busy / 1000.0
        p.idle_ms += max(span - busy, 0.0) / 1000.0
    for kernel, p in profiles.items():
        seqs = {o.seq for o in attributed
                if o.kernel == kernel and o.seq is not None}
        host_launches = sum(1 for m in marks if m.kernel == kernel)
        p.launches = len(seqs) or host_launches
    for lane, tr in lane_tracks.items():
        busy_ms = sum(_union_us(v) for v in tr.values()) / 1000.0
        # per-kernel per-lane busy: union per (kernel, lane, track)
        klt: dict[tuple[str, int, int], list] = {}
        for o in attributed:
            if o.lane == lane and o.kernel != "?":
                klt.setdefault((o.kernel, o.pid, o.tid), []).append(
                    (o.ts, o.ts + o.dur))
        for (kernel, _pid, _tid), iv in klt.items():
            profiles[kernel].per_lane_ms[lane] = \
                profiles[kernel].per_lane_ms.get(lane, 0.0) \
                + _union_us(iv) / 1000.0
        denom = report.wall_ms or report.device_span_ms
        report.per_lane_overlap[lane] = (
            busy_ms / denom if denom > 0 else 0.0)

    report.kernels = list(profiles.values())
    report.ops = attributed
    report.attributed_ms = sum(p.device_ms for p in profiles.values())
    report.unattributed_ms = max(
        report.device_busy_ms - report.attributed_ms, 0.0)
    for o in attributed:
        if o.matched_by:
            report.matched_by[o.matched_by] = \
                report.matched_by.get(o.matched_by, 0) + 1
    return report


# ---------------------------------------------------------------------------
# roofline
# ---------------------------------------------------------------------------

def roofline_row(
    flops: float,
    bytes_moved: float,
    device_ms: float,
    peak_tflops: float | None = None,
    peak_gbps: float | None = None,
    device_kind: str | None = None,
) -> dict:
    """Place one kernel on the machine roofline (Williams et al., 2009).

    ``flops``/``bytes_moved`` are the workload's analytic counts (the
    same numbers the bench's MFU rows use), ``device_ms`` the measured
    device-busy time.  Peaks default from :func:`hardware.device_peaks`
    for the current rig's device kind (``device_kind`` names one
    explicitly; ``peak_tflops``/``peak_gbps`` override outright) — an
    MFU printed on a non-v5e rig is no longer silently scaled to v5e.
    Returns intensity (flop/byte), attained Tflop/s and GB/s, the roof
    at this intensity, MFU vs the compute peak, the fraction of the
    (possibly memory-slanted) roof attained, and which side of the
    ridge the kernel sits on."""
    peak_kind = device_kind
    if peak_tflops is None or peak_gbps is None:
        from ..hardware import device_peaks

        tf, gb, peak_kind = device_peaks(device_kind)
        peak_tflops = tf if peak_tflops is None else peak_tflops
        peak_gbps = gb if peak_gbps is None else peak_gbps
    device_s = max(device_ms, 1e-9) / 1e3
    intensity = flops / max(bytes_moved, 1e-9)
    attained_tflops = flops / device_s / 1e12
    attained_gbps = bytes_moved / device_s / 1e9
    ridge = peak_tflops * 1e12 / (peak_gbps * 1e9)  # flop/byte
    roof_tflops = min(peak_tflops, intensity * peak_gbps * 1e9 / 1e12)
    return {
        "peak_kind": peak_kind or "override",
        "flops": flops,
        "bytes": bytes_moved,
        "device_ms": round(device_ms, 3),
        "intensity_flop_per_byte": round(intensity, 3),
        "ridge_flop_per_byte": round(ridge, 3),
        "bound": "compute" if intensity >= ridge else "memory",
        "attained_tflops": round(attained_tflops, 3),
        "attained_gbps": round(attained_gbps, 3),
        "peak_tflops": peak_tflops,
        "peak_gbps": peak_gbps,
        "roof_tflops": round(roof_tflops, 3),
        "mfu": round(attained_tflops / peak_tflops, 4),
        "frac_of_roof": round(attained_tflops / max(roof_tflops, 1e-12), 4),
    }


# ---------------------------------------------------------------------------
# unified Perfetto export
# ---------------------------------------------------------------------------

#: pid of the first device process in the unified export (host spans
#: keep pid 1, the export.py convention).
_DEVICE_PID0 = 100


def unified_chrome_trace(
    spans: Sequence[Span],
    report: DeviceWindowReport | None,
    ops: Sequence[DeviceOp] | None = None,
    marks: Sequence[Mark] = (),
    counters: dict | None = None,
    process_name: str = "cekirdekler_tpu",
    req_events: Sequence = (),
) -> dict:
    """Host spans + device ops on ONE timeline.

    Host spans ride the standard export (pid 1, one thread per lane);
    each device becomes its own process (``device:<name>``) whose
    threads are LANES (`lane N (device)`) so a lane's host track and
    its device track sit side by side.  Device ops map onto the host
    ``perf_counter`` axis through the report's clock anchor
    (mark pairs, else capture start); with no anchor the device ops are
    exported against their own origin and the trace says so
    (``args.anchor: null`` on the metadata).  Marks replay as
    zero-cost ``device-mark`` instants so the dispatch edge is visible
    beside the ops it explains.  ``req_events`` (obs/reqtrace.py
    events) add per-request lifecycle tracks as their own ``requests``
    process — one thread per rid, one slice per phase, cat ``ck-req``
    (wall-clock stamps, exported against their own origin — the phase
    anatomy is relative within each chain).  ``split_unified_trace``
    reads the merged schema back, ignoring the request tracks — the
    round trip is pinned by test."""
    from .export import to_chrome_trace

    spans = list(spans)
    ops = list(ops if ops is not None else [])
    offset_s = report.anchor_offset_s if report is not None else None
    anchor = report.anchor if report is not None else None

    def op_t0_s(o: DeviceOp) -> float:
        return o.ts * 1e-6 + (offset_s or 0.0)

    candidates = [s.t0 for s in spans] + [m.t0 for m in marks if m.t0]
    if offset_s is not None:
        candidates += [op_t0_s(o) for o in ops]
    elif ops:
        candidates += [o.ts * 1e-6 for o in ops]
    for series in (counters or {}).values():
        if series:
            candidates.append(series[0][0])
    t_base = min(candidates, default=0.0)

    doc = to_chrome_trace(spans, process_name=process_name,
                          counters=counters, t_base=t_base)
    events = doc["traceEvents"]
    dev_pids: dict[str, int] = {}
    dev_tids: dict[tuple[int, Any], int] = {}
    for o in ops:
        pid = dev_pids.get(o.device)
        if pid is None:
            pid = _DEVICE_PID0 + len(dev_pids)
            dev_pids[o.device] = pid
            events.append({
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": f"device:{o.device}", "anchor": anchor},
            })
        tkey = (pid, o.lane)
        tid = dev_tids.get(tkey)
        if tid is None:
            tid = 0 if o.lane is None else int(o.lane) + 1
            dev_tids[tkey] = tid
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": (
                    f"lane {o.lane} (device)" if o.lane is not None
                    else "device (no lane)")},
            })
        args: dict = {"op": o.name, "kind": "device-op"}
        if o.kernel != "?":
            args["kernel"] = o.kernel
        if o.seq is not None:
            args["ck-seq"] = o.seq
        if o.cid is not None:
            args["cid"] = o.cid
        if o.matched_by:
            args["matched_by"] = o.matched_by
        events.append({
            "ph": "X",
            "name": o.kernel if o.kernel != "?" else o.name,
            "cat": "ck-dev",
            "pid": pid,
            "tid": tid,
            "ts": (o.ts * 1e-6 + (offset_s or 0.0) - t_base) * 1e6,
            "dur": o.dur,
            "args": args,
        })
    for m in marks:
        if not m.t0:
            continue
        events.append({
            "ph": "i", "s": "p",   # process-scoped instant
            "name": "device-mark", "cat": "ck-dev",
            "pid": 1, "tid": 0 if m.lane is None else int(m.lane) + 1,
            "ts": (m.t0 - t_base) * 1e6,
            "args": {"kernel": m.kernel, "ck-seq": m.seq, "cid": m.cid,
                     "kind": "device-mark"},
        })
    if req_events:
        from ..obs.reqtrace import request_chrome_events
        events.extend(request_chrome_events(req_events))
    return doc


def split_unified_trace(trace: dict) -> tuple[list[Span], list[DeviceOp]]:
    """Inverse of :func:`unified_chrome_trace`: recover the host spans
    and the device ops (both on the unified relative clock — seconds
    for spans, microseconds for op ``ts``, the native unit each side's
    consumers expect)."""
    from .export import from_chrome_trace

    dev_pids: dict[int, str] = {}
    for e in trace.get("traceEvents", ()):
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name = e.get("args", {}).get("name", "")
            if name.startswith("device:") or "/device:" in name:
                dev_pids[e["pid"]] = name.split("device:", 1)[-1]
    host_events = [
        e for e in trace.get("traceEvents", ())
        if e.get("pid") not in dev_pids and e.get("ph") == "X"
        and e.get("cat") != "ck-req"   # request-lifecycle tracks are not spans
    ]
    spans = from_chrome_trace({"traceEvents": host_events})
    ops: list[DeviceOp] = []
    for e in trace.get("traceEvents", ()):
        if e.get("ph") != "X" or e.get("pid") not in dev_pids:
            continue
        args = e.get("args", {}) or {}
        tid = int(e.get("tid", 0))
        ops.append(DeviceOp(
            device=dev_pids[e["pid"]], pid=int(e["pid"]), tid=tid,
            name=str(args.get("op", e.get("name", "?"))),
            ts=float(e.get("ts", 0.0)), dur=float(e.get("dur", 0.0)),
            args=args,
            kernel=str(args.get("kernel", "?")),
            seq=args.get("ck-seq"),
            cid=args.get("cid"),
            lane=None if tid == 0 else tid - 1,
            matched_by=args.get("matched_by"),
        ))
    ops.sort(key=lambda o: o.ts)
    return spans, ops


# ---------------------------------------------------------------------------
# the capture wrapper
# ---------------------------------------------------------------------------

#: Most recent completed capture's report — what ``/profilez`` serves.
_LAST_REPORT: DeviceWindowReport | None = None
_LAST_LOCK = threading.Lock()


def last_report() -> DeviceWindowReport | None:
    with _LAST_LOCK:
        return _LAST_REPORT


def _set_last_report(rep: DeviceWindowReport) -> None:
    global _LAST_REPORT
    with _LAST_LOCK:
        _LAST_REPORT = rep


class DeviceCapture:
    """One traced window: profiler + marks around a region, parsed and
    correlated on exit.

    ::

        cap = DeviceCapture("/tmp/ck_dev_trace")
        with cap:
            ...launch-annotated framework work...
        print(cap.report.table())        # named absence on CPU rigs

    Lifecycle events ride the flight recorder (``profiler-start`` /
    ``profiler-stop``) and the ``ck_profile_captures_total`` counter, so
    a postmortem shows whether a crash happened under capture.  A
    profiler that cannot start degrades the report to a named absence;
    the region always runs."""

    def __init__(self, trace_dir: str, marks: DeviceMarks | None = None):
        self.trace_dir = trace_dir
        self.marks = marks if marks is not None else MARKS
        self.report: DeviceWindowReport = DeviceWindowReport(
            absent="capture never ran")
        self.profiler_ok = False
        self._handle = None
        self._t0 = 0.0
        self._marks_were_enabled = False

    def __enter__(self) -> "DeviceCapture":
        from ..metrics.registry import REGISTRY
        from ..obs.flight import FLIGHT

        from ..utils import timeline

        REGISTRY.counter(
            "ck_profile_captures_total",
            "device-timeline captures attempted").inc()
        self._marks_were_enabled = self.marks.enabled
        self.marks.enable(clear=not self._marks_were_enabled)
        self._handle, err = timeline.start_profiler(self.trace_dir)
        self.profiler_ok = self._handle is not None
        self._start_err = err
        FLIGHT.event("profiler-start", dir=self.trace_dir,
                     ok=self.profiler_ok)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        from ..metrics.registry import REGISTRY
        from ..obs.flight import FLIGHT

        from ..utils import timeline

        t1 = time.perf_counter()
        if self._handle is not None:
            timeline.stop_profiler(self._handle)
        FLIGHT.event("profiler-stop", dir=self.trace_dir,
                     wall_ms=round((t1 - self._t0) * 1e3, 3))
        window_marks = [m for m in self.marks.snapshot()
                        if m.t1 >= self._t0 and m.t0 <= t1]
        if not self._marks_were_enabled:
            self.marks.disable()
        if exc_type is not None:
            # the region failed — the caller's exception outranks the
            # analysis; leave a named absence instead of half a report
            self.report = DeviceWindowReport(
                absent=f"window raised {exc_type.__name__} — not analyzed")
            _set_last_report(self.report)
            return
        if not self.profiler_ok:
            self.report = DeviceWindowReport(
                wall_ms=(t1 - self._t0) * 1e3,
                absent=f"profiler unavailable: {self._start_err}")
            self.report.n_marks = len(window_marks)
            _set_last_report(self.report)
            return
        try:
            dump = parse_trace_dump(self.trace_dir)
            self.report = correlate(
                dump, window_marks, window=(self._t0, t1),
                capture_anchor=(
                    (self._t0, min((e.ts for e in dump.ops), default=0.0))
                    if dump.ops else None),
            )
        except Exception as e:  # noqa: BLE001 - analysis must not raise
            self.report = DeviceWindowReport(
                wall_ms=(t1 - self._t0) * 1e3,
                absent=f"trace analysis failed: {type(e).__name__}: {e}")
        REGISTRY.counter(
            "ck_profile_device_ops_total",
            "device ops parsed from capture dumps").inc(self.report.n_ops)
        _set_last_report(self.report)


@contextmanager
def capture_device(trace_dir: str):
    """Functional form of :class:`DeviceCapture`::

        with capture_device("/tmp/t") as cap:
            ...work...
        cap.report  # DeviceWindowReport (named absence on CPU rigs)
    """
    cap = DeviceCapture(trace_dir)
    with cap:
        yield cap


# ---------------------------------------------------------------------------
# the persistent kernel-profile store
# ---------------------------------------------------------------------------

class ProfileStore:
    """On-disk kernel-profile evidence base, keyed by
    ``(kernel signature, shape, blocks)``.

    One append-only ``.jsonl`` file per key under ``root`` (or the
    ``CK_PROFILE_STORE`` directory; with neither, the store is DISABLED
    and every write returns None — a bench on a scratch rig must not
    litter).  Rows are ``json_safe`` dicts tagged with the schema and a
    wall-clock timestamp; readers skip unparseable lines (a torn tail
    from a crashed writer loses one row, never the file).  This is the
    store a block-shape autotuner (ROADMAP item 3) reads: ``best()``
    returns the lowest-``device_ms`` row for a key, ``history()`` the
    full trajectory."""

    def __init__(self, root: str | None = None):
        self.root = root if root is not None else \
            os.environ.get(PROFILE_STORE_ENV) or None
        self._mu = threading.Lock()

    @property
    def enabled(self) -> bool:
        return bool(self.root)

    @staticmethod
    def _slug(kernel_sig: str, shape, blocks) -> str:
        raw = f"{kernel_sig}|{shape}|{blocks}"
        safe = "".join(
            c if c.isalnum() or c in "._+-" else "_" for c in kernel_sig
        )[:48]
        return (f"{safe}__"
                f"{hashlib.sha256(raw.encode()).hexdigest()[:12]}.jsonl")

    def path_for(self, kernel_sig: str, shape, blocks) -> str | None:
        if not self.root:
            return None
        return os.path.join(self.root, self._slug(kernel_sig, shape, blocks))

    def put(self, kernel_sig: str, shape, blocks, row: dict) -> str | None:
        """Append one profile row; returns the path, or None when the
        store is disabled.  The append is a single ``write()`` of one
        line, serialized under the store lock within this process."""
        path = self.path_for(kernel_sig, shape, blocks)
        if path is None:
            return None
        from ..metrics.registry import REGISTRY
        from ..utils.jsonsafe import json_safe

        doc = {
            "schema": STORE_SCHEMA,
            "kernel_sig": kernel_sig,
            "shape": list(shape) if isinstance(shape, (tuple, list))
            else shape,
            "blocks": list(blocks) if isinstance(blocks, (tuple, list))
            else blocks,
            "wrote_at": time.time(),
            **row,
        }
        line = json.dumps(json_safe(doc), allow_nan=False) + "\n"
        with self._mu:
            os.makedirs(self.root, exist_ok=True)
            with open(path, "a") as f:
                f.write(line)
        REGISTRY.counter(
            "ck_profile_store_writes_total",
            "kernel-profile rows persisted").inc()
        return path

    @staticmethod
    def _read_rows(path: str | None) -> list[dict]:
        """Parsed rows of one key file, torn/blank lines skipped — the
        ONE jsonl reader (history by key, the CLI's read by filename)."""
        if path is None or not os.path.exists(path):
            return []
        rows: list[dict] = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn tail line: skip, never raise
        return rows

    def history(self, kernel_sig: str, shape, blocks) -> list[dict]:
        return self._read_rows(self.path_for(kernel_sig, shape, blocks))

    def read_key(self, filename: str) -> list[dict]:
        """Rows of one key FILE (a ``keys()`` entry) — the store-wide
        enumeration path (``tools/kernel_profile.py --show-store``)."""
        if not self.root:
            return []
        return self._read_rows(os.path.join(self.root, filename))

    def get(self, kernel_sig: str, shape, blocks) -> dict | None:
        """The newest row for the key, or None."""
        rows = self.history(kernel_sig, shape, blocks)
        return rows[-1] if rows else None

    @staticmethod
    def best_row(rows: list[dict], metric: str = "device_ms") -> dict | None:
        """The lowest-``metric`` row (ties to newest), or None when no
        row carries a numeric ``metric``."""
        rows = [r for r in rows
                if isinstance(r.get(metric), (int, float))
                and not isinstance(r.get(metric), bool)]
        if not rows:
            return None
        return min(reversed(rows), key=lambda r: r[metric])

    def best(self, kernel_sig: str, shape, blocks,
             metric: str = "device_ms") -> dict | None:
        """The lowest-``metric`` row for the key (ties to newest)."""
        return self.best_row(self.history(kernel_sig, shape, blocks), metric)

    def keys(self) -> list[str]:
        """Key files present in the store (filenames, sorted)."""
        if not self.root or not os.path.isdir(self.root):
            return []
        return sorted(
            fn for fn in os.listdir(self.root) if fn.endswith(".jsonl"))

    def best_blocks(self, kernel_sig: str, shape,
                    metric: str = "device_ms") -> tuple[int, int] | None:
        """The block pair of the lowest-``metric`` row across ALL key
        files matching ``(kernel_sig, shape)`` — the autotuner's
        consumer API (``core/blocktuner.py`` seeds its warm start
        here).  Block keys are per-(sig, shape, blocks) files, so this
        scans every key file, filters by signature + shape, and
        returns the winning row's ``blocks`` as an int 2-tuple (None
        when no matching row has a usable pair)."""
        want_shape = list(shape) if isinstance(shape, (tuple, list)) \
            else shape
        rows: list[dict] = []
        for fn in self.keys():
            for r in self.read_key(fn):
                if r.get("kernel_sig") != kernel_sig:
                    break  # one key file == one (sig, shape, blocks)
                if r.get("shape") != want_shape:
                    break
                rows.append(r)
        best = self.best_row(rows, metric)
        if best is None:
            return None
        blocks = best.get("blocks")
        if not isinstance(blocks, (list, tuple)) or len(blocks) < 2:
            return None
        try:
            return int(blocks[0]), int(blocks[1])
        except (TypeError, ValueError):
            return None


#: The default store (``CK_PROFILE_STORE``-armed; disabled otherwise).
STORE = ProfileStore()


# ---------------------------------------------------------------------------
# /profilez
# ---------------------------------------------------------------------------

def profilez_payload(store: ProfileStore | None = None) -> dict:
    """What the debug server's ``/profilez`` endpoint serves: the last
    capture's reconciled report (or its named absence), mark-plane
    state, and the persistent store's index."""
    st = store if store is not None else STORE
    rep = last_report()
    return {
        "last_capture": rep.to_dict() if rep is not None else None,
        "marks": {
            "enabled": MARKS.enabled,
            "recorded": MARKS.total_recorded,
        },
        "store": {
            "enabled": st.enabled,
            "root": st.root,
            "keys": st.keys(),
        },
    }
