"""``cekirdekler_tpu.trace`` — span-based attribution: explain every
lost millisecond.

Five pieces (see ``docs/OBSERVABILITY.md`` for the guided tour):

- :mod:`.spans` — the process-global :data:`TRACER`: a lock-free-ish
  ring buffer of typed spans (enqueue, split, rebalance, launch, fence,
  upload, download, pipeline-stage, pool-task, dcn-exchange) recorded by
  every runtime layer; a no-op when disabled (<1 µs/span, pinned by
  test).
- :mod:`.attribution` — per-window "where did the time go" reports
  reconciling host wall time against span totals and device-busy time,
  plus the per-compute-id fence split that fixes the one-fence-time-
  for-all-cids balancer distortion.
- :mod:`.export` — Chrome-trace (``chrome://tracing`` / Perfetto) JSON
  export and the plain-text table.
- :mod:`.ceiling` — the overlap ceiling re-derived from same-rep duplex
  probes with a witness clamp, so ``achieved_vs_ceiling`` is a real
  ratio-to-a-bound (≤ 1 structurally) with per-rep spread.
- :mod:`.aggregate` — cluster-wide aggregation: DCN worker processes
  ship span batches + metric snapshots with RTT-symmetric clock-offset
  estimation, producing ONE merged, alignment-checked Perfetto trace
  for an N-process job.
- :mod:`.device` — device-timeline attribution on the ``jax.profiler``
  capture seam: launch marks (``MARKS``), per-kernel device profiles
  reconciled against the host window, roofline rows, the unified
  host+device Perfetto export, and the persistent on-disk kernel-
  profile store (``CK_PROFILE_STORE``).

None of these import jax at module level: enabling tracing costs no
backend initialization.
"""

from .aggregate import (
    ClusterSnapshot,
    collective_consistency,
    estimate_clock_offsets,
    gather_cluster,
    merged_chrome_trace,
)
from .attribution import AttributionReport, split_fence_benches, window_report
from .ceiling import RepSample, ceiling_report, rep_ceiling
from .device import (
    DEVICE_SPAN_KINDS,
    MARKS,
    STORE,
    DeviceCapture,
    DeviceWindowReport,
    ProfileStore,
    capture_device,
    profilez_payload,
    roofline_row,
    split_unified_trace,
    unified_chrome_trace,
)
from .export import (
    from_chrome_trace,
    save_chrome_trace,
    text_table,
    to_chrome_trace,
)
from .spans import SPAN_KINDS, TRACER, Span, Tracer, tracing

__all__ = [
    "AttributionReport",
    "ClusterSnapshot",
    "DEVICE_SPAN_KINDS",
    "DeviceCapture",
    "DeviceWindowReport",
    "MARKS",
    "ProfileStore",
    "RepSample",
    "SPAN_KINDS",
    "STORE",
    "Span",
    "TRACER",
    "Tracer",
    "capture_device",
    "ceiling_report",
    "collective_consistency",
    "estimate_clock_offsets",
    "from_chrome_trace",
    "gather_cluster",
    "merged_chrome_trace",
    "profilez_payload",
    "rep_ceiling",
    "roofline_row",
    "save_chrome_trace",
    "split_fence_benches",
    "split_unified_trace",
    "text_table",
    "to_chrome_trace",
    "tracing",
    "window_report",
]
