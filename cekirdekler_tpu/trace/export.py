"""Export recorded spans: Chrome-trace JSON (``chrome://tracing`` /
Perfetto) and the plain-text "where did the time go" table.

The Chrome trace event format is the JSON array-of-events schema both
viewers load directly: complete events (``ph: "X"``) with microsecond
``ts``/``dur``, plus ``M`` metadata events naming the process and one
thread per lane.  ``from_chrome_trace`` reads the same schema back into
:class:`~cekirdekler_tpu.trace.spans.Span` records — the round trip is
pinned by ``tests/test_trace.py`` so the exporter cannot silently drift
off the schema the viewers parse.
"""

from __future__ import annotations

import json
from typing import Iterable, Sequence

from .attribution import window_report
from .spans import Span

__all__ = [
    "to_chrome_trace", "from_chrome_trace", "save_chrome_trace",
    "text_table",
]

_PID = 1  # single-process trace; lanes map to tids


def _tid(lane: int | None) -> int:
    # tid 0 = spans with no lane (host-global events); lanes are 1-based
    return 0 if lane is None else int(lane) + 1


def to_chrome_trace(
    spans: Sequence[Span],
    process_name: str = "cekirdekler_tpu",
    counters: dict | None = None,
    pid: int = _PID,
    t_base: float | None = None,
) -> dict:
    """Spans → Chrome trace dict (``{"traceEvents": [...]}``).

    ``ts`` is microseconds relative to the earliest span so the viewer
    opens at t=0 instead of hours into a perf_counter epoch.

    ``counters`` (``metrics.REGISTRY.counter_series()`` output: series
    name → [(perf_counter, value), ...]) adds Perfetto **counter
    tracks** to the same timeline — balancer shares, driver-queue
    occupancy, transfer byte counters ride next to the spans that
    explain them.  ``pid``/``t_base`` exist for the cluster aggregator
    (``trace/aggregate.py``), which emits one process block per DCN
    process against one shared clock origin."""
    spans = list(spans)
    if t_base is None:
        # counter samples participate in the origin: with zero spans a
        # 0.0 base would place ph:C events at absolute perf_counter
        # microseconds (hours past t=0 in the viewer)
        candidates = [s.t0 for s in spans]
        for series in (counters or {}).values():
            if series:
                candidates.append(series[0][0])
        t_base = min(candidates, default=0.0)
    events: list[dict] = [
        {
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": process_name},
        },
        {
            "ph": "M", "name": "thread_name", "pid": pid, "tid": 0,
            "args": {"name": "host"},
        },
    ]
    lanes = sorted({s.lane for s in spans if s.lane is not None})
    for lane in lanes:
        events.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": _tid(lane),
            "args": {"name": f"lane {lane}"},
        })
    for s in spans:
        args: dict = {}
        if s.cid is not None:
            args["cid"] = s.cid
        if s.tag is not None:
            args["tag"] = s.tag
        events.append({
            "ph": "X",
            "name": s.kind,
            "cat": "ck",
            "pid": pid,
            "tid": _tid(s.lane),
            "ts": (s.t0 - t_base) * 1e6,
            "dur": (s.t1 - s.t0) * 1e6,
            "args": args,
        })
    if counters:
        from ..metrics.export import chrome_counter_events

        events.extend(chrome_counter_events(counters, t_base, pid=pid))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def from_chrome_trace(trace: dict) -> list[Span]:
    """Chrome trace dict → spans (the exporter's inverse; timestamps are
    relative seconds, not the original perf_counter epoch)."""
    out: list[Span] = []
    for e in trace.get("traceEvents", ()):
        if e.get("ph") != "X":
            continue
        t0 = float(e.get("ts", 0.0)) / 1e6
        dur = float(e.get("dur", 0.0)) / 1e6
        args = e.get("args", {}) or {}
        tid = int(e.get("tid", 0))
        out.append(Span(
            kind=str(e.get("name", "?")),
            t0=t0,
            t1=t0 + dur,
            cid=args.get("cid"),
            lane=None if tid == 0 else tid - 1,
            tag=args.get("tag"),
        ))
    out.sort(key=lambda s: s.t0)
    return out


def save_chrome_trace(
    spans: Sequence[Span], path: str, process_name: str = "cekirdekler_tpu",
    counters: dict | None = None,
) -> str:
    """Write the Chrome trace JSON; returns ``path`` for chaining."""
    from ..utils.jsonsafe import json_safe

    # json_safe: Perfetto's strict JSON parser rejects a bare Infinity —
    # one inf counter sample must not make the whole trace unloadable
    with open(path, "w") as f:
        json.dump(
            json_safe(to_chrome_trace(spans, process_name, counters=counters)),
            f, allow_nan=False,
        )
    return path


def text_table(
    spans: Iterable[Span],
    t0: float | None = None,
    t1: float | None = None,
    device_busy_ms: float | None = None,
) -> str:
    """The plain-text "where did the time go" table over [t0, t1]
    (defaults to the spans' own extent)."""
    spans = list(spans)
    if not spans:
        return "(no spans recorded)"
    lo = t0 if t0 is not None else min(s.t0 for s in spans)
    hi = t1 if t1 is not None else max(s.t1 for s in spans)
    return window_report(spans, lo, hi, device_busy_ms=device_busy_ms).table()
