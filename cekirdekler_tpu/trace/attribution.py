"""Attribution: reconcile host wall time against recorded spans and the
device timeline, and split shared fence cost per compute id.

Two jobs, both evidence-level gaps VERDICT r5 named:

1. **Window reports** (r5 #3): given the spans recorded inside a host
   wall window and (optionally) the device-busy time from
   ``utils/timeline.py``'s Xprof events, produce a "where did the time
   go" account: per-kind totals, per-compute-id totals, the host-covered
   union, and the unattributed gap.  The sum of span durations can
   legitimately exceed the wall (spans from concurrent lanes overlap) —
   the report therefore carries both the raw per-kind sums (cost
   accounting) and the union of intervals (wall coverage).

2. **Fence splitting** (r5 #8): enqueue-mode windows used to charge the
   ONE whole-window fence time to EVERY compute id dispatched in the
   window, feeding the balancer misattributed per-cid costs whenever
   kernels with different cost profiles shared a window.
   :func:`split_fence_benches` converts per-cid completion timestamps —
   measured by fencing each compute id's last output value in dispatch
   order (stream order makes each such fence retire exactly when that
   cid's final kernel retires) — into MARGINAL per-cid times: each cid
   is charged the time from the previous cid's completion to its own.
   For batched windows (all of cid A, then all of cid B — the common
   mixed pattern) the marginals are exact per-cid device costs;
   interleaved windows still charge a cid with any earlier-dispatched
   work of later-completing ids, which is the stream-order bound on what
   host-side fencing can attribute (documented, not hidden).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Sequence

from .spans import Span

__all__ = [
    "split_fence_benches", "window_report", "AttributionReport", "union_ms",
]


def split_fence_benches(
    completions: Sequence[tuple[int, float]], t_open: float
) -> dict[int, float]:
    """Per-cid marginal milliseconds from ordered completion timestamps.

    ``completions`` is [(cid, perf_counter_at_completion), ...] in the
    order the fences retired (== dispatch order of each cid's last
    launch); ``t_open`` is when the dispatch window opened.  Returns
    {cid: marginal_ms}.  Marginals are clamped at 0 (clock jitter on a
    same-instant retirement must not produce a negative bench, which the
    balancer would treat as infinite speed)."""
    out: dict[int, float] = {}
    prev = t_open
    for cid, t in completions:
        out[cid] = max(t - prev, 0.0) * 1000.0
        prev = max(prev, t)
    return out


def union_ms(intervals: list[tuple[float, float]]) -> float:
    """Length of the union of (start, end) second-intervals, in ms —
    the wall-coverage reduction shared by the report below and external
    residue accounting (workloads._nbody_attribution)."""
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    cs, ce = intervals[0]
    for s, e in intervals[1:]:
        if s > ce:
            total += ce - cs
            cs, ce = s, e
        else:
            ce = max(ce, e)
    return (total + (ce - cs)) * 1000.0


@dataclass
class AttributionReport:
    """One window's account.  All times in milliseconds."""

    wall_ms: float
    per_kind: dict = field(default_factory=dict)      # kind -> {ms, count}
    per_cid: dict = field(default_factory=dict)       # cid -> {kind: ms}
    # device kind -> {ms, count, lanes} (heterogeneous fleets, ISSUE
    # 20): only populated when the caller passes lane_kinds — the span
    # ring carries lane INDICES, the scheduler owns the index→kind map
    per_lane_kind: dict = field(default_factory=dict)
    covered_ms: float = 0.0    # union of span intervals (wall coverage)
    gap_ms: float = 0.0        # wall - covered: host time no span explains
    device_busy_ms: float | None = None   # from utils/timeline.py, if given
    device_busy_frac: float | None = None
    n_spans: int = 0
    ring_wrapped: bool = False
    dropped_spans: int = 0     # spans lost to ring wrap (totals undercount)

    def to_dict(self) -> dict:
        return {
            "wall_ms": round(self.wall_ms, 3),
            "covered_ms": round(self.covered_ms, 3),
            "gap_ms": round(self.gap_ms, 3),
            "gap_frac": round(self.gap_ms / self.wall_ms, 4)
            if self.wall_ms > 0 else None,
            "device_busy_ms": (
                round(self.device_busy_ms, 3)
                if self.device_busy_ms is not None else None
            ),
            "device_busy_frac": (
                round(self.device_busy_frac, 4)
                if self.device_busy_frac is not None else None
            ),
            "per_kind": {
                k: {"ms": round(v["ms"], 3), "count": v["count"]}
                for k, v in sorted(
                    self.per_kind.items(), key=lambda kv: -kv[1]["ms"]
                )
            },
            "per_cid": {
                str(cid): {k: round(ms, 3) for k, ms in kinds.items()}
                for cid, kinds in sorted(self.per_cid.items())
            },
            "per_lane_kind": {
                k: {"ms": round(v["ms"], 3), "count": v["count"],
                    "lanes": sorted(v["lanes"])}
                for k, v in sorted(
                    self.per_lane_kind.items(), key=lambda kv: -kv[1]["ms"]
                )
            },
            "n_spans": self.n_spans,
            "ring_wrapped": self.ring_wrapped,
            "dropped_spans": self.dropped_spans,
        }

    def table(self) -> str:
        """Plain-text "where did the time go" table."""
        lines = [
            f"wall {self.wall_ms:10.3f} ms   "
            f"span-covered {self.covered_ms:10.3f} ms   "
            f"gap {self.gap_ms:10.3f} ms"
        ]
        if self.device_busy_ms is not None:
            lines.append(
                f"device busy {self.device_busy_ms:10.3f} ms  "
                f"({100.0 * (self.device_busy_frac or 0.0):.1f}% of wall)"
            )
        lines.append(f"{'kind':>16} {'total ms':>12} {'count':>8} {'% wall':>8}")
        for kind, v in sorted(self.per_kind.items(), key=lambda kv: -kv[1]["ms"]):
            pct = 100.0 * v["ms"] / self.wall_ms if self.wall_ms > 0 else 0.0
            lines.append(
                f"{kind:>16} {v['ms']:12.3f} {v['count']:8d} {pct:8.1f}"
            )
        if self.per_lane_kind:
            lines.append(
                f"{'device kind':>16} {'total ms':>12} {'count':>8} "
                f"{'lanes':>8}")
            for kind, v in sorted(self.per_lane_kind.items(),
                                  key=lambda kv: -kv[1]["ms"]):
                lines.append(
                    f"{kind:>16} {v['ms']:12.3f} {v['count']:8d} "
                    f"{len(v['lanes']):8d}")
        if self.ring_wrapped:
            lines.append(
                f"(ring buffer wrapped: {self.dropped_spans} oldest spans "
                "overwritten — totals undercount; raise Tracer capacity)"
            )
        return "\n".join(lines)


def window_report(
    spans: Iterable[Span],
    t0: float,
    t1: float,
    device_busy_ms: float | None = None,
    ring_wrapped: bool = False,
    dropped_spans: int = 0,
    lane_kinds: dict | None = None,
) -> AttributionReport:
    """Account the host wall window [t0, t1] from recorded spans.

    Spans partially overlapping the window are clipped to it so a span
    straddling the boundary cannot inflate per-kind totals past the
    wall.  ``device_busy_ms`` (from ``timeline.analyze_trace_dir``)
    rides along for the host-vs-device reconciliation.
    ``dropped_spans`` (``Tracer.dropped_spans``) is how many spans the
    ring lost to wrap before this snapshot — when nonzero the report's
    totals/coverage undercount by exactly those spans, and the report
    says so instead of letting attribution coverage silently shrink.

    ``lane_kinds`` maps lane index → device kind (``Cores.lane_kinds``
    by position): when given, lane-tagged spans additionally roll up
    per DEVICE KIND — the heterogeneous-fleet account of which silicon
    the window's time went to (TPU vs host-CPU lanes in one Cores)."""
    wall_ms = max(t1 - t0, 0.0) * 1000.0
    per_kind: dict[str, dict] = {}
    per_cid: dict[int, dict] = {}
    per_lane_kind: dict[str, dict] = {}
    kind_of = {}
    if lane_kinds:
        kind_of = (dict(enumerate(lane_kinds))
                   if isinstance(lane_kinds, (list, tuple))
                   else dict(lane_kinds))
    intervals: list[tuple[float, float]] = []
    n = 0
    for s in spans:
        lo, hi = max(s.t0, t0), min(s.t1, t1)
        if hi < lo:
            continue
        n += 1
        ms = (hi - lo) * 1000.0
        k = per_kind.setdefault(s.kind, {"ms": 0.0, "count": 0})
        k["ms"] += ms
        k["count"] += 1
        if s.cid is not None:
            per_cid.setdefault(s.cid, {}).setdefault(s.kind, 0.0)
            per_cid[s.cid][s.kind] += ms
        if s.lane is not None and s.lane in kind_of:
            dk = per_lane_kind.setdefault(
                str(kind_of[s.lane]), {"ms": 0.0, "count": 0, "lanes": set()})
            dk["ms"] += ms
            dk["count"] += 1
            dk["lanes"].add(int(s.lane))
        if hi > lo:
            intervals.append((lo, hi))
    covered = union_ms(intervals)
    return AttributionReport(
        wall_ms=wall_ms,
        per_kind=per_kind,
        per_cid=per_cid,
        per_lane_kind=per_lane_kind,
        covered_ms=covered,
        gap_ms=max(wall_ms - covered, 0.0),
        device_busy_ms=device_busy_ms,
        device_busy_frac=(
            device_busy_ms / wall_ms
            if device_busy_ms is not None and wall_ms > 0 else None
        ),
        n_spans=n,
        ring_wrapped=ring_wrapped or dropped_spans > 0,
        dropped_spans=dropped_spans,
    )
