"""Cluster tier: multi-host compute (reference L6, SURVEY.md §2.1 #11-16).

Two tiers share the :class:`IComputeNode` surface:

- **DCN tier (primary)** — :class:`DistributedAccelerator` (dcn.py): the
  same ``compute()`` spanning the processes of a JAX distributed job,
  balanced in LCM-step units, results exchanged with XLA collectives over
  DCN.  This is the TPU-pod idiom (SURVEY.md §7 step 6).
- **TCP tier (parity fallback)** — :class:`ClusterAccelerator` driving
  :class:`CruncherServer` nodes through the :class:`CruncherClient` wire
  protocol: reproduces the reference's explicit node orchestration for
  heterogeneous/ad-hoc fleets and keeps the mid-compute failover + probe
  capabilities a static jax.distributed job cannot express.
"""

from .accelerator import ClusterAccelerator, IComputeNode
from .balancer import ClusterLoadBalancer
from .client import CruncherClient
from .dcn import DistributedAccelerator
from .netbuffer import ArrayRecord, Command, Message, recv_message, send_message
from .server import CruncherServer

__all__ = [
    "ArrayRecord",
    "ClusterAccelerator",
    "ClusterLoadBalancer",
    "Command",
    "CruncherClient",
    "CruncherServer",
    "DistributedAccelerator",
    "IComputeNode",
    "Message",
    "recv_message",
    "send_message",
]
