"""Cluster tier: multi-node compute over TCP (reference L6,
SURVEY.md §2.1 #11-16).

For TPU pods the idiomatic multi-host path is one JAX distributed runtime
spanning hosts (parallel/ meshes over DCN); this tier reproduces the
reference's explicit node orchestration — a :class:`ClusterAccelerator`
driving :class:`CruncherServer` nodes through the :class:`CruncherClient`
wire protocol — for parity and for heterogeneous/ad-hoc fleets.
"""

from .accelerator import ClusterAccelerator, IComputeNode
from .balancer import ClusterLoadBalancer
from .client import CruncherClient
from .netbuffer import ArrayRecord, Command, Message, recv_message, send_message
from .server import CruncherServer

__all__ = [
    "ArrayRecord",
    "ClusterAccelerator",
    "ClusterLoadBalancer",
    "Command",
    "CruncherClient",
    "CruncherServer",
    "IComputeNode",
    "Message",
    "recv_message",
    "send_message",
]
