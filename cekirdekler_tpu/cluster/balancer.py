"""Cluster-grain load balancer.

Port of the reference's ``ClusterLoadBalancer`` (ClusterLoadBalancer.cs):
coarser than the per-chip balancer (core/balance.py) — shares move in
LCM-of-node-steps units so every node's share stays divisible by its own
step (node step = its device count × local range, ClusterAccelerator.cs
compute()).  ``equal_split`` hands out LCM chunks round-robin with the
remainder going to the mainframe (the local node), mirroring
``dengeleEsit`` (ClusterLoadBalancer.cs:143-231); ``rebalance`` applies
the damped move ``t += 0.3·(p − t)`` on normalized measured performance
and snaps to step multiples (``balanceOnPerformances``, :233-325).
"""

from __future__ import annotations

import math
from typing import Sequence

__all__ = ["ClusterLoadBalancer"]


def _lcm_all(values: Sequence[int]) -> int:
    out = 1
    for v in values:
        out = math.lcm(out, max(1, int(v)))
    return out


class ClusterLoadBalancer:
    """Per-compute-id cluster balancer (one instance per compute id,
    reference: ClusterAccelerator.cs:170-355)."""

    def __init__(self, steps: Sequence[int], damping: float = 0.3):
        self.steps = [max(1, int(s)) for s in steps]
        self.lcm = _lcm_all(self.steps)
        self.damping = damping
        self.targets: list[float] | None = None  # normalized shares

    @property
    def num_nodes(self) -> int:
        return len(self.steps)

    def equal_split(self, total: int) -> tuple[list[int], int]:
        """Equal distribution in LCM chunks; remainder returned for the
        mainframe (reference: dengeleEsit)."""
        n = self.num_nodes
        chunks = total // self.lcm
        per = (chunks // n) * self.lcm
        ranges = [per] * n
        left = total - per * n
        # distribute leftover LCM chunks round-robin
        i = 0
        while left >= self.lcm:
            ranges[i % n] += self.lcm
            left -= self.lcm
            i += 1
        self.targets = [r / total if total else 0.0 for r in ranges]
        return ranges, left

    def resplit_active(self, total: int,
                       active: Sequence[int]) -> tuple[list[int], int]:
        """Membership-change re-split (ISSUE 13): equal LCM-chunk
        distribution over the ACTIVE node indices only — a departed/
        preempted node's share is 0 — with the remainder returned for
        the mainframe (the first active node).  The balancer's targets
        reset to the new split: the old trajectory described a
        membership that no longer exists, and damping toward it would
        drip work onto dead nodes.  ``cluster/elastic.member_resplit``
        (the replay-verified decision output) is the all-active,
        remainder-folded wrapper over this — one re-split
        implementation, two call forms."""
        active = sorted({int(i) for i in active})
        if not active or any(i < 0 or i >= self.num_nodes for i in active):
            raise ValueError(
                f"active indices {active} invalid for {self.num_nodes} nodes")
        sub = ClusterLoadBalancer(
            [self.steps[i] for i in active], damping=self.damping)
        shares, left = sub.equal_split(total)
        out = [0] * self.num_nodes
        for j, i in enumerate(active):
            out[i] = shares[j]
        self.targets = [r / total if total else 0.0 for r in out]
        return out, left

    def rebalance(self, ranges: Sequence[int], times_ms: Sequence[float], total: int) -> tuple[list[int], int]:
        """Move shares toward measured performance p_i = range_i / time_i,
        damped, snapped to each node's step; remainder (sum shortfall) goes
        to the mainframe."""
        n = self.num_nodes
        if n == 0 or total <= 0:
            return list(ranges), total - sum(ranges)
        # a node that ran nothing has no measurement: inherit its current
        # target instead of scoring it 0 (which would decay it to permanent
        # starvation)
        tgt = self.targets or [r / total for r in ranges]
        perf = [
            (r / t if r > 0 and t > 0 else None)
            for r, t in zip(ranges, times_ms)
        ]
        measured = [p for p in perf if p is not None]
        s_measured = sum(measured) or 1.0
        meas_share = sum(t for t, p in zip(tgt, perf) if p is not None) or 1.0
        perf = [
            (p / s_measured * meas_share if p is not None else tgt[i])
            for i, p in enumerate(perf)
        ]
        s = sum(perf)
        if s <= 0:
            return list(ranges), total - sum(ranges)
        perf = [p / s for p in perf]
        if self.targets is None or len(self.targets) != n:
            self.targets = [r / total for r in ranges]
        self.targets = [
            t + self.damping * (p - t) for t, p in zip(self.targets, perf)
        ]
        out: list[int] = []
        for t, step in zip(self.targets, self.steps):
            raw = t * total
            snapped = int(raw / step + 0.5) * step
            # floor at one step: a zero share yields no timing next call, so
            # a starved node could never earn work back — keep a probe share
            # (divergence from the reference, which shares the same defect)
            out.append(max(step if total >= sum(self.steps) else 0, snapped))
        # trim overflow from the largest shares (reference: overflow trimmed
        # from largest, ClusterLoadBalancer.cs:233-325)
        while sum(out) > total:
            i = max(range(n), key=lambda k: out[k])
            out[i] = max(0, out[i] - self.steps[i])
        remainder = total - sum(out)
        return out, remainder
