"""Cluster compute server: one TCP listener, one thread per client.

TPU-native analogue of ``ClCruncherServer(+Thread)`` (ClCruncherServer.cs,
ClCruncherServerThread.cs): SETUP builds a local :class:`NumberCruncher`
from the kernel source (ClCruncherServerThread.cs:113-146); COMPUTE
unmarshals kernel names / ranges / arrays, runs the local multi-chip
scheduler over the node's share of the global range, and returns the
written slices (:147-250); CONTROL answers pings; NUM_DEVICES reports the
node's chip count; DISPOSE tears the cruncher down; SERVER_STOP ends the
server.  Array identity across calls rides client-side ids cached per
connection (:175-185) so repeated computes reuse device buffers.
"""

from __future__ import annotations

import socket
import threading

import numpy as np

from ..arrays.clarray import ClArray
from ..core.cruncher import NumberCruncher
from ..hardware import all_devices
from .netbuffer import (
    FLAG_PARTIAL,
    FLAG_READ,
    FLAG_WRITE,
    FLAG_WRITE_ALL,
    ArrayRecord,
    Command,
    Message,
    recv_message,
    send_message,
)

__all__ = ["CruncherServer"]


def _error_reply(e: Exception) -> Message:
    """The ANSWER_ERROR for one failed operation.  A serving-tier
    rejection (``serve/admission.ServeRejected`` — including the
    fabric's ``shard-unavailable``) carries its NAMED reason, tenant,
    and retry-after hint in ``meta`` so the remote client re-raises
    the same typed error a local caller gets (detected structurally —
    by the reason/tenant/retry attributes — so this module never
    imports the serve package and no import cycle forms).  The wire
    meta dict is int-valued by format, so the reason and tenant ride
    the strings list behind the message text and the retry hint rides
    as integer microseconds."""
    reason = getattr(e, "reason", None)
    tenant = getattr(e, "tenant", None)
    retry_after = getattr(e, "retry_after_s", None)
    if isinstance(reason, str) and tenant is not None \
            and retry_after is not None:
        return Message(
            Command.ANSWER_ERROR,
            meta={"reject": 1,
                  "retry_after_us": int(float(retry_after) * 1e6)},
            strings=[str(e), reason, str(tenant)])
    return Message(Command.ANSWER_ERROR, strings=[str(e)])


class _ClientSession(threading.Thread):
    """Per-connection state + dispatch loop (reference:
    ClCruncherServerThread)."""

    def __init__(self, server: "CruncherServer", conn: socket.socket, addr):
        super().__init__(daemon=True, name=f"cruncher-client-{addr}")
        self.server = server
        self.conn = conn
        self.cruncher: NumberCruncher | None = None
        self.arrays: dict[int, ClArray] = {}  # client array id → local array

    def run(self) -> None:  # pragma: no cover - driven by tests via sockets
        try:
            while True:
                msg = recv_message(self.conn)
                if msg.command == Command.SETUP:
                    self._setup(msg)
                elif msg.command == Command.COMPUTE:
                    self._compute(msg)
                elif msg.command == Command.CONTROL:
                    send_message(self.conn, Message(Command.ANSWER_CONTROL))
                elif msg.command == Command.NUM_DEVICES:
                    n = self.cruncher.num_devices if self.cruncher else len(
                        self.server.devices
                    )
                    send_message(
                        self.conn,
                        Message(Command.ANSWER_NUM_DEVICES, meta={"n": n}),
                    )
                elif msg.command == Command.DISPOSE:
                    self._dispose()
                elif msg.command == Command.SERVER_STOP:
                    self.server.stop()
                    break
                else:
                    send_message(
                        self.conn,
                        Message(Command.ANSWER_ERROR, strings=[f"bad command {msg.command}"]),
                    )
        except (ConnectionError, OSError):
            pass
        finally:
            self._dispose()
            try:
                self.conn.close()
            except OSError:
                pass

    def _setup(self, msg: Message) -> None:
        try:
            source = msg.strings[0]
            max_devices = msg.meta.get("max_devices", 0)
            devices = self.server.devices
            if max_devices > 0:
                devices = devices.subset(max_devices)
            self._dispose()
            self.cruncher = NumberCruncher(devices, source)
            send_message(
                self.conn,
                Message(Command.ANSWER_SETUP, meta={"n": self.cruncher.num_devices}),
            )
        except Exception as e:
            send_message(self.conn, _error_reply(e))

    def _compute(self, msg: Message) -> None:
        try:
            if self.cruncher is None:
                raise RuntimeError("COMPUTE before SETUP")
            kernels = msg.strings
            cid = msg.meta["compute_id"]
            goff = msg.meta["global_offset"]
            grange = msg.meta["global_range"]
            lrange = msg.meta["local_range"]
            params: list[ClArray] = []
            for rec in msg.arrays:
                arr = self.arrays.get(rec.array_id)
                total = msg.meta[f"size_{rec.array_id}"]
                if arr is None or arr.size != total or arr.dtype != rec.data.dtype:
                    arr = ClArray(np.zeros(total, rec.data.dtype))
                    self.arrays[rec.array_id] = arr
                if rec.flags & FLAG_READ and rec.data.size:
                    arr.host()[rec.offset : rec.offset + rec.data.size] = rec.data
                arr.flags.read = bool(rec.flags & FLAG_READ)
                arr.flags.partial_read = bool(rec.flags & FLAG_PARTIAL)
                arr.flags.write = bool(rec.flags & FLAG_WRITE)
                arr.flags.write_all = bool(rec.flags & FLAG_WRITE_ALL)
                arr.flags.elements_per_work_item = rec.epw
                params.append(arr)
            from ..arrays.clarray import ParameterGroup

            group = ParameterGroup(params)
            group.compute(
                self.cruncher, cid, kernels, grange, lrange,
                global_offset=goff, values=tuple(msg.values),
            )
            # return written slices: this node's [goff, goff+grange) × epw
            reply = Message(Command.ANSWER_COMPUTE, meta={"compute_id": cid})
            for rec, arr in zip(msg.arrays, params):
                if not (rec.flags & FLAG_WRITE):
                    continue
                if rec.flags & FLAG_WRITE_ALL:
                    # cluster-level single-owner rule: remote nodes never
                    # return write_all arrays (the mainframe owns them) —
                    # else N nodes race full-array writebacks on the client
                    continue
                else:
                    epw = rec.epw
                    lo, hi = goff * epw, (goff + grange) * epw
                reply.arrays.append(
                    ArrayRecord(
                        rec.array_id, arr.host()[lo:hi], rec.flags, rec.epw, lo
                    )
                )
            send_message(self.conn, reply)
        except Exception as e:
            send_message(self.conn, _error_reply(e))

    def _dispose(self) -> None:
        if self.cruncher is not None:
            self.cruncher.dispose()
            self.cruncher = None
        self.arrays.clear()


class CruncherServer:
    """TCP compute node (reference: ClCruncherServer.cs:56-133).

    Concurrent-client contract: each accepted connection runs its OWN
    session thread with its own cruncher and array cache — a second
    client's SETUP/COMPUTE proceeds while the first session is
    mid-compute (nothing serializes sessions against each other;
    pinned by ``tests/test_cluster.py``).  ``max_sessions`` bounds the
    concurrency: a connection beyond it is REJECTED with a named
    ``ANSWER_ERROR`` and closed — the client's next round trip raises
    instead of hanging on a connection the server will never serve."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, devices=None,
                 max_sessions: int = 32):
        self.devices = devices if devices is not None else all_devices()
        self.max_sessions = max(1, int(max_sessions))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.host, self.port = self._sock.getsockname()
        self._running = True
        self._sessions: list[_ClientSession] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="cruncher-server"
        )
        self._accept_thread.start()

    def _accept_loop(self) -> None:  # pragma: no cover - exercised via tests
        while self._running:
            try:
                conn, addr = self._sock.accept()
            except OSError:
                break
            if not self._running:
                # stop() raced the blocked accept: on Linux, close()
                # alone does NOT wake a thread blocked in accept() (the
                # syscall pins the kernel socket, which keeps LISTENING)
                # — one post-stop connection could land here and be
                # served by a "stopped" server.  Found by the reconnect
                # client retrying a stopped node (ISSUE 13).
                try:
                    conn.close()
                except OSError:
                    pass
                break
            self._sessions = [s for s in self._sessions if s.is_alive()]
            if len(self._sessions) >= self.max_sessions:
                # reject-with-a-name, never a silent hang: the client's
                # first round trip reads this error instead of waiting
                # on a session thread that will never exist (the
                # serving tier's admission contract, applied here).  A
                # tiny daemon reads the client's first command BEFORE
                # replying — an unsolicited error followed by close can
                # be RST-discarded when the client's request lands on
                # the already-closed socket
                threading.Thread(
                    target=self._reject_session, args=(conn,),
                    daemon=True, name="cruncher-reject",
                ).start()
                continue
            session = _ClientSession(self, conn, addr)
            self._sessions.append(session)
            session.start()

    def _reject_session(self, conn: socket.socket) -> None:
        """Answer one over-capacity connection's first command with a
        named error, then close (request → error reply ordering, so
        the rejection survives the TCP teardown)."""
        try:
            conn.settimeout(5.0)
            recv_message(conn)
            send_message(conn, Message(
                Command.ANSWER_ERROR,
                strings=[
                    f"server at capacity ({self.max_sessions} "
                    "concurrent sessions); retry later"],
            ))
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._running = False
        try:
            # shutdown BEFORE close: close() does not wake a thread
            # blocked in accept() on Linux (the syscall holds a kernel
            # reference, so the socket keeps listening and accepts one
            # more connection); shutdown() forces the blocked accept to
            # return, so a stopped server genuinely stops accepting
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass  # platform may refuse shutdown on a listening socket
        try:
            self._sock.close()
        except OSError:
            pass
        # close live sessions: unblocks their recv loops, whose finally
        # blocks dispose crunchers (device buffers) and close sockets
        for session in self._sessions:
            try:
                session.conn.close()
            except OSError:
                pass
        me = threading.current_thread()
        for session in self._sessions:
            if session is not me:  # SERVER_STOP arrives on a session thread
                session.join(timeout=2.0)
        self._sessions.clear()

    def __enter__(self) -> "CruncherServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
