"""Multi-host DCN tier over the JAX distributed runtime.

The primary multi-host path (SURVEY.md §7 step 6, §5.8): the same
``compute()`` surface as :class:`ClusterAccelerator`, but spanning the N
*processes* of a JAX distributed job — each host computes its balanced
share on its process-local chips via a local :class:`NumberCruncher`, and
written ranges are exchanged with **XLA collectives over DCN** (an
all-gather jitted across the global device set) instead of the TCP tier's
hand-framed sockets.  The TCP tier (`accelerator.py`/`server.py`) remains
the reference-parity fallback for hosts outside a JAX distributed job.

Reference analogue: ``ClusterAccelerator.compute()``
(ClusterAccelerator.cs:170-355) driving remote ``Cores`` over
``NetworkBuffer`` marshaling (ClCruncherServerThread.cs:147-250).  Design
divergences, all TPU-pod idioms:

- **SPMD, not master/worker**: every process runs the same program and the
  same balancer arithmetic on identically all-gathered timings, so the
  per-compute-id splits agree everywhere without a control channel — the
  jax.distributed coordinator replaces SETUP/COMPUTE framing entirely.
- **Step-quantized shares**: per-process step = local device count ×
  local_range; the LCM-step :class:`ClusterLoadBalancer` is reused as-is.
  The remainder share goes to process 0 (the reference's "mainframe").
- **write_all single-owner rule**: process 0 owns write_all arrays
  (broadcast_one_to_all), mirroring the TCP tier's rule that remote nodes
  never return write_all payloads (server.py).
- **Restart-shaped elasticity**: jax.distributed jobs cannot lose or add
  processes MID-RUN, so elasticity here is preemption-shaped
  (``cluster/elastic.py``, ISSUE 13): the job checkpoints each window's
  partition state (atomic tmp+rename), a preempted job restarts —
  possibly with a different process count — resumes from the last
  complete window (:meth:`DistributedAccelerator.resume_elastic`), and
  the membership change is recorded as replayable
  ``member-leave``/``member-join`` decisions whose outputs are the new
  LCM-step re-split.  A kill-and-rejoin run converges to the
  bit-identical image of an undisturbed one
  (tests/_dcn_elastic_worker.py).

Testable without a pod: 2 processes × 4 virtual CPU devices each, with
``gloo`` cross-process collectives (tests/test_dcn.py).
"""

from __future__ import annotations

import functools as _functools
import time
from typing import Sequence

import numpy as np

from ..arrays.clarray import ClArray, ParameterGroup
from ..core.cruncher import NumberCruncher
from ..errors import CekirdeklerError, ComputeValidationError
from ..hardware import Device, Devices
from ..metrics.registry import REGISTRY
from ..trace.spans import TRACER
from .accelerator import IComputeNode
from .balancer import ClusterLoadBalancer

__all__ = ["initialize", "DistributedAccelerator"]


@_functools.lru_cache(maxsize=4)
def _process_mesh():
    """1-D mesh with ONE device per process (each process's first local
    device, in process order) — the cross-host exchange lattice.  Cached:
    membership of a jax.distributed job is static."""
    import jax
    from jax.sharding import Mesh

    first: dict[int, object] = {}
    for d in jax.devices():  # coordinator-assigned order, same everywhere
        first.setdefault(d.process_index, d)
    devs = [first[p] for p in sorted(first)]
    return Mesh(np.array(devs), ("x",))


@_functools.lru_cache(maxsize=4)
def _replicator(mesh):
    """One compiled all-gather (replicating identity) per mesh — a fresh
    ``jax.jit`` per call would re-trace and re-compile on every exchange,
    a cross-host synchronization point on the hot path."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    return jax.jit(lambda a: a, out_shardings=NamedSharding(mesh, P()))


@_functools.lru_cache(maxsize=4)
def _reducer(mesh):
    """One compiled replicating row-sum per mesh (the broadcast path)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    return jax.jit(
        lambda a: a.sum(axis=0).astype(jnp.uint8),
        out_shardings=NamedSharding(mesh, P()),
    )


def initialize(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    *,
    cpu_collectives: str = "gloo",
) -> None:
    """Join the JAX distributed job (idempotent).

    Wraps ``jax.distributed.initialize`` with the CPU-collectives
    implementation configured first — without it a multi-process CPU
    backend (the virtual test rig) comes up with single-process visibility
    and every cross-process collective silently degenerates."""
    import jax

    try:
        already = jax.distributed.is_initialized()
    except AttributeError:
        # pre-0.5 jax has no is_initialized(); the client handle on the
        # internal global state is the same signal (same convention as
        # the other pre-0.6 compat shims in parallel/)
        from jax._src import distributed as _dist

        already = getattr(_dist.global_state, "client", None) is not None
    if already:
        return  # already joined
    if cpu_collectives:
        try:
            jax.config.update(
                "jax_cpu_collectives_implementation", cpu_collectives
            )
        except Exception:
            pass  # flag absent on this jax version; TPU pods don't need it
    jax.distributed.initialize(
        coordinator_address, num_processes=num_processes,
        process_id=process_id,
    )


class DistributedAccelerator(IComputeNode):
    """N host processes behaving as ONE device over DCN.

    Construct AFTER :func:`initialize` (or ``jax.distributed.initialize``)
    in every process of the job, then use exactly like a
    :class:`NumberCruncher`-backed node: ``setup_nodes(src)`` once,
    ``compute(...)`` per step.  Every process must make the same calls in
    the same order (SPMD) — the collectives inside are global.

    ``timing_hook(compute_id, share, wall_ms) -> float`` optionally
    replaces the measured local wall time fed to the balancer — the same
    deterministic-bench-injection seam ``benchrig.compute_path_proof``
    uses, because on shared-core virtual rigs wall time measures scheduler
    contention, not work.
    """

    def __init__(self, local_devices: Devices | None = None,
                 timing_hook=None):
        import jax

        self.pid = jax.process_index()
        self.nproc = jax.process_count()
        if local_devices is None:
            local_devices = Devices(Device(d) for d in jax.local_devices())
        if not len(local_devices):
            raise CekirdeklerError("no process-local devices")
        self.local_devices = local_devices
        self.timing_hook = timing_hook
        self.cruncher: NumberCruncher | None = None
        self.kernel_source: str | None = None
        self.proc_device_counts: list[int] = []
        self.balancers: dict[int, ClusterLoadBalancer] = {}
        self.ranges: dict[int, list[int]] = {}
        self.timings: dict[int, list[float]] = {}

    # -- collective helpers --------------------------------------------------
    @staticmethod
    def _allgather(value: np.ndarray) -> np.ndarray:
        """Per-process all-gather → ``[nproc, *value.shape]`` via a jitted
        XLA all-gather over one device per process (the DCN path).

        Built directly on a process-representative device mesh rather than
        ``multihost_utils.process_allgather``: the latter reshapes the
        device list to (nproc, local_count) and so requires every process
        to hold the SAME number of devices — true on TPU pods, not on
        ad-hoc CPU fleets or asymmetric test rigs.  Each process's payload
        rides its first local device, so exactly ``nproc`` rows move over
        DCN (no zero rows for the other local chips).

        Payloads cross as raw bytes: ``device_put`` canonicalizes
        int64/float64 to 32-bit when ``jax_enable_x64`` is off (the
        production default), which would silently wrap/round 64-bit host
        arrays — the TCP tier ships raw bytes, and the two tiers must
        agree."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        _tt = TRACER.t0()
        _t0 = time.perf_counter()
        value = np.ascontiguousarray(value)
        raw = value.view(np.uint8)
        mesh = _process_mesh()
        nproc = mesh.devices.size
        my_dev = jax.local_devices()[0]
        shard = jax.device_put(raw[None], my_dev)
        garr = jax.make_array_from_single_device_arrays(
            (nproc,) + raw.shape, NamedSharding(mesh, P("x")), [shard]
        )
        gathered = np.asarray(_replicator(mesh)(garr))
        REGISTRY.counter(
            "ck_dcn_exchange_bytes_total", "bytes moved over DCN collectives",
            op="allgather",
        ).inc(raw.nbytes * nproc)
        REGISTRY.histogram(
            "ck_dcn_exchange_seconds", "per-collective wall latency",
            op="allgather",
        ).observe(time.perf_counter() - _t0)
        TRACER.record(
            "dcn-exchange", _tt, tag=f"allgather {raw.nbytes}B x{nproc}"
        )
        return gathered.view(value.dtype).reshape((nproc,) + value.shape)

    @staticmethod
    def _broadcast0(value: np.ndarray) -> np.ndarray:
        """Process 0's copy, everywhere (write_all single-owner rule).

        An owner-masked byte psum over the process mesh, NOT an N-row
        all-gather API call: non-owners contribute exact zeros, so the
        replicated row-sum IS the owner's payload.  INTENT is the
        reduce+broadcast traffic shape (O(M) per link vs O(N·M) for
        gathering N full copies), but on a 1-D process mesh XLA may
        still lower the replicated row-sum as all-gather + local reduce
        — the per-link byte claim is unverified on this backend (ADVICE
        r5 #3); what the masked-psum form guarantees is the single-owner
        SEMANTICS: every process ends with exactly process 0's bytes."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        _tt = TRACER.t0()
        _t0 = time.perf_counter()
        value = np.ascontiguousarray(value)
        raw = value.view(np.uint8)
        mesh = _process_mesh()
        nproc = mesh.devices.size
        mine = raw if jax.process_index() == 0 else np.zeros_like(raw)
        shard = jax.device_put(mine[None], jax.local_devices()[0])
        garr = jax.make_array_from_single_device_arrays(
            (nproc,) + raw.shape, NamedSharding(mesh, P("x")), [shard]
        )
        out = np.asarray(_reducer(mesh)(garr))
        REGISTRY.counter(
            "ck_dcn_exchange_bytes_total", "bytes moved over DCN collectives",
            op="broadcast0",
        ).inc(raw.nbytes)
        REGISTRY.histogram(
            "ck_dcn_exchange_seconds", "per-collective wall latency",
            op="broadcast0",
        ).observe(time.perf_counter() - _t0)
        TRACER.record(
            "dcn-exchange", _tt, tag=f"broadcast0 {raw.nbytes}B"
        )
        return out.view(value.dtype).reshape(value.shape)

    def barrier(self, tag: str = "ck_dcn_barrier") -> None:
        """Cross-process sync point (reference: the TCP tier's synchronous
        request/reply implies one; here it is explicit).

        Rides the tier's own :meth:`_allgather` rather than
        ``multihost_utils.sync_global_devices``: the latter reshapes the
        device list to ``(nproc, local_count)`` and so requires every
        process to hold the SAME device count — the exact constraint
        ``_allgather`` exists to avoid, and elastic rejoins
        (``resume_elastic``) are routinely asymmetric.  The gathered tag
        hash doubles as the name-mismatch assertion."""
        import zlib

        h = np.asarray([zlib.crc32(tag.encode())], np.uint32)
        gathered = self._allgather(h)
        if not (gathered == h[0]).all():
            raise CekirdeklerError(
                f"barrier tag mismatch across processes ({tag!r}): "
                f"{gathered.reshape(-1).tolist()}")

    # -- IComputeNode --------------------------------------------------------
    def setup_nodes(self, kernel_source: str) -> None:
        """Compile the kernel locally and agree on the per-process step
        table (reference: setupNodes, ClusterAccelerator.cs:364-443 —
        minus the socket handshake the coordinator already did)."""
        self.kernel_source = kernel_source
        self.cruncher = NumberCruncher(self.local_devices, kernel_source)
        counts = self._allgather(
            np.asarray([len(self.local_devices)], np.int64)
        )
        self.proc_device_counts = [int(c) for c in counts.reshape(-1)]

    @property
    def num_nodes(self) -> int:
        return self.nproc

    def compute(
        self,
        kernel_names: str | Sequence[str],
        params: Sequence[ClArray],
        compute_id: int,
        global_range: int,
        local_range: int = 256,
        values=(),
    ) -> None:
        if self.cruncher is None:
            raise CekirdeklerError("setup_nodes() must run before compute()")
        names = (
            kernel_names.split()
            if isinstance(kernel_names, str)
            else list(kernel_names)
        )
        if global_range % local_range != 0:
            raise ComputeValidationError(
                f"global_range ({global_range}) must be divisible by "
                f"local_range ({local_range})"
            )
        params = list(params)

        # identical balancer state on every process: inputs are the
        # all-gathered timings of the previous call and the shared range
        # table, so the arithmetic below agrees without coordination
        bal = self.balancers.get(compute_id)
        if bal is None:
            steps = [c * local_range for c in self.proc_device_counts]
            bal = ClusterLoadBalancer(steps)
            self.balancers[compute_id] = bal
            shares, remainder = bal.equal_split(global_range)
        else:
            prev = self.ranges[compute_id]
            times = self.timings.get(compute_id, [1.0] * self.nproc)
            shares, remainder = bal.rebalance(prev, times, global_range)
        shares = list(shares)
        shares[0] += remainder  # process 0 is the mainframe
        refs = np.concatenate([[0], np.cumsum(shares)]).astype(int)
        self.ranges[compute_id] = shares

        my_share = shares[self.pid]
        my_off = int(refs[self.pid])
        _tt = TRACER.t0()
        t0 = time.perf_counter()
        if my_share > 0:
            group = ParameterGroup(params)
            group.compute(
                self.cruncher, compute_id, names, my_share, local_range,
                global_offset=my_off, values=values,
            )
        wall_ms = (time.perf_counter() - t0) * 1000.0
        if self.timing_hook is not None:
            wall_ms = float(self.timing_hook(compute_id, my_share, wall_ms))

        # result exchange: every process contributes its written range,
        # padded to the max share so the all-gather is rectangular; the
        # collective sequence below is identical on every process (it
        # depends only on the shared share table and array flags)
        max_elems = int(max(shares))
        for p in params:
            if not (p.flags.write and not p.flags.read_only):
                continue
            host = p.host()
            if p.flags.write_all:
                # single-owner rule (server.py): process 0's copy wins
                np.copyto(host, self._broadcast0(host))
                continue
            epw = p.flags.elements_per_work_item
            pad = np.zeros(max_elems * epw, host.dtype)
            if my_share > 0:
                lo = my_off * epw
                n = my_share * epw
                pad[:n] = host[lo:lo + n]
            gathered = self._allgather(pad)
            for j in range(self.nproc):
                if j == self.pid or shares[j] <= 0:
                    continue
                lo = int(refs[j]) * epw
                n = shares[j] * epw
                host[lo:lo + n] = gathered[j, :n]

        times = self._allgather(np.asarray([wall_ms], np.float64))
        self.timings[compute_id] = [float(t) for t in times.reshape(-1)]
        TRACER.record(
            "enqueue", _tt, cid=compute_id,
            tag=f"dcn p{self.pid}/{self.nproc} share{my_share}",
        )

    # -- elastic membership & window checkpoints (cluster/elastic.py) --------
    def member_table(self, local_range: int) -> dict:
        """This job's elastic-membership roster: ``{"p<i>": step}`` with
        step = process i's device count × ``local_range`` (the LCM-step
        table's row).  Requires :meth:`setup_nodes` (the agreed
        device-count table is the input)."""
        if not self.proc_device_counts:
            raise CekirdeklerError(
                "setup_nodes() must run before member_table()")
        return {
            f"p{i}": c * local_range
            for i, c in enumerate(self.proc_device_counts)
        }

    def establish_membership(self, local_range: int,
                             prev_steps: Sequence[int] | None = None,
                             total: int | None = None):
        """Epoch-numbered membership for this job (elastic.Membership).

        ``prev_steps`` is a previous incarnation's member-step table
        (from a window checkpoint): when it differs from the current
        roster, the leave/join transitions — a preempted member gone,
        a rejoined one back, a resized one re-split — are recorded as
        replayable decisions carrying the new LCM-step re-split over
        ``total``.  Every process runs the same reconciliation on the
        same inputs (SPMD), so the recorded sequences agree."""
        from .elastic import Membership

        m = Membership()
        if prev_steps:
            m.establish({
                f"p{i}": int(s) for i, s in enumerate(prev_steps)})
            m.sync(self.member_table(local_range), total)
        else:
            m.establish(self.member_table(local_range))
        return m

    def checkpoint_window(self, root: str, window: int, arrays: dict,
                          local_range: int) -> str | None:
        """Persist one completed window's partition state (process 0
        only — post-exchange every process holds identical host
        arrays, and N writers racing one step dir would be N-1 wasted
        renames).  Callers barrier AFTER this so no process runs ahead
        of a checkpoint that may need to be resumed."""
        if self.pid != 0:
            return None
        from .elastic import save_window

        steps = [c * local_range for c in self.proc_device_counts]
        return save_window(root, window, arrays, member_steps=steps)

    def resume_elastic(self, root: str, local_range: int,
                       total: int | None = None) -> dict | None:
        """Resume a preempted job: load the newest COMPLETE window
        checkpoint (torn newest falls back — utils/checkpoint.py),
        reconcile membership against the checkpointed roster (recorded
        leave/join re-splits), warm the local cruncher's ladder set
        from the persistent executable cache when ``CK_COMPILE_CACHE``
        is armed (core/compilecache.py — a rejoining member re-traces
        the fleet's persisted signature mix and every XLA compile loads
        from disk, so the rejoin pays no fresh compile wall), and
        return ``{"window", "arrays", "member_steps", "membership"}``
        — or None on a fresh start."""
        from .elastic import resume_window

        state = resume_window(root)
        membership = self.establish_membership(
            local_range,
            prev_steps=(state or {}).get("member_steps"),
            total=total)
        if self.cruncher is not None:
            from ..core.compilecache import CACHE, warm_from_disk

            if CACHE.enabled:
                warm = warm_from_disk(self.cruncher.cores)
                if state is not None:
                    state["cache_warm"] = warm
        if state is None:
            return None
        state["membership"] = membership
        return state

    # -- introspection (obs/) ------------------------------------------------
    def health_report(self) -> dict:
        """This process's lane-health verdicts (``Cores.health_report``
        of the local cruncher; ``{}`` before ``setup_nodes``).
        ``trace.gather_cluster(acc)`` ships this automatically, so the
        DCN tier sees every process's lane verdicts merged on one table
        (``obs.health.cluster_health_table``)."""
        if self.cruncher is None:
            return {}
        return self.cruncher.cores.health_report()

    def serve_debug(self, port: int = 0, host: str = "127.0.0.1"):
        """Start this process's live debug endpoints over the local
        cruncher's scheduler (obs/debugserver.py) — one plane per DCN
        process; the cluster-wide view is the aggregated snapshot."""
        if self.cruncher is None:
            raise CekirdeklerError("setup_nodes() must run before serve_debug()")
        return self.cruncher.cores.serve_debug(port=port, host=host)

    def compute_timing(self, compute_id: int) -> list[float]:
        return list(self.timings.get(compute_id, []))

    def ranges_of(self, compute_id: int) -> list[int]:
        return list(self.ranges.get(compute_id, []))

    def dispose(self) -> None:
        if self.cruncher is not None:
            self.cruncher.dispose()
            self.cruncher = None
