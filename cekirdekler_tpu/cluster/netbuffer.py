"""Cluster wire format: length-prefixed binary messages with typed array
records.

The capability equivalent of the reference's ``NetworkBuffer``
(NetworkBuffer.cs): command codes (:109-126), typed per-array records
identified by client-side ids (:645-846), and a length header (:196-209).
The 8 KB segmentation is an artifact of its socket loop and is dropped —
Python sockets stream; framing is one ``!BQ`` header (command,
payload-length) followed by the payload.

Message payload layout (all little-endian via struct '<'):
  u32 n_meta | n_meta × (u16 key_len, key bytes, i64 value)   — int metadata
  u32 n_strs | n_strs × (u16 len, utf8)                       — string list
  u32 n_vals | n_vals × (u8 tag, f64|i64)                     — scalar values
  u32 n_arrs | n_arrs × array record
array record:
  u64 id | u8 dtype_code | u8 flags | u32 epw | u64 offset | u64 nbytes | raw
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

import numpy as np

from ..utils.faultinject import FAULTS

__all__ = [
    "Command",
    "ArrayRecord",
    "Message",
    "send_message",
    "recv_message",
]


class Command:
    """Command codes (reference: NetworkBuffer.cs:109-126)."""

    SETUP = 1
    COMPUTE = 2
    DISPOSE = 3
    CONTROL = 4
    NUM_DEVICES = 5
    SERVER_STOP = 6
    ANSWER_COMPUTE = 32
    ANSWER_SETUP = 33
    ANSWER_CONTROL = 34
    ANSWER_NUM_DEVICES = 35
    ANSWER_ERROR = 63


_DTYPES = [
    np.dtype(np.float32), np.dtype(np.float64), np.dtype(np.int32),
    np.dtype(np.uint32), np.dtype(np.int64), np.dtype(np.uint8),
    np.dtype(np.int8), np.dtype(np.int16), np.dtype(np.uint16),
    np.dtype(np.uint64),
]
_DTYPE_CODE = {dt: i for i, dt in enumerate(_DTYPES)}

FLAG_READ = 1
FLAG_PARTIAL = 2
FLAG_WRITE = 4
FLAG_WRITE_ALL = 8


@dataclass
class ArrayRecord:
    array_id: int
    data: np.ndarray          # the payload bytes view (may be a slice)
    flags: int = FLAG_READ | FLAG_WRITE
    epw: int = 1
    offset: int = 0           # element offset this record's data starts at


@dataclass
class Message:
    command: int
    meta: dict[str, int] = field(default_factory=dict)
    strings: list[str] = field(default_factory=list)
    values: list = field(default_factory=list)
    arrays: list[ArrayRecord] = field(default_factory=list)

    # -- encoding ------------------------------------------------------------
    def encode(self) -> bytes:
        parts: list[bytes] = []
        parts.append(struct.pack("<I", len(self.meta)))
        for k, v in self.meta.items():
            kb = k.encode()
            parts.append(struct.pack("<H", len(kb)) + kb + struct.pack("<q", int(v)))
        parts.append(struct.pack("<I", len(self.strings)))
        for s in self.strings:
            sb = s.encode()
            parts.append(struct.pack("<I", len(sb)) + sb)
        parts.append(struct.pack("<I", len(self.values)))
        for v in self.values:
            if isinstance(v, (int, np.integer)):
                parts.append(struct.pack("<Bq", 0, int(v)))
            else:
                parts.append(struct.pack("<Bd", 1, float(v)))
        parts.append(struct.pack("<I", len(self.arrays)))
        for rec in self.arrays:
            data = np.ascontiguousarray(rec.data)
            code = _DTYPE_CODE[data.dtype]
            raw = data.tobytes()
            parts.append(
                struct.pack(
                    "<QBBIQQ", rec.array_id, code, rec.flags, rec.epw,
                    rec.offset, len(raw),
                )
            )
            parts.append(raw)
        return b"".join(parts)

    @staticmethod
    def decode(command: int, payload: bytes) -> "Message":
        msg = Message(command)
        off = 0

        def take(fmt: str):
            nonlocal off
            size = struct.calcsize(fmt)
            out = struct.unpack_from(fmt, payload, off)
            off += size
            return out

        (n_meta,) = take("<I")
        for _ in range(n_meta):
            (klen,) = take("<H")
            key = payload[off : off + klen].decode()
            off += klen
            (val,) = take("<q")
            msg.meta[key] = val
        (n_strs,) = take("<I")
        for _ in range(n_strs):
            (slen,) = take("<I")
            msg.strings.append(payload[off : off + slen].decode())
            off += slen
        (n_vals,) = take("<I")
        for _ in range(n_vals):
            (tag,) = take("<B")
            if tag == 0:
                (v,) = take("<q")
                msg.values.append(int(v))
            else:
                (v,) = take("<d")
                msg.values.append(float(v))
        (n_arrs,) = take("<I")
        for _ in range(n_arrs):
            array_id, code, flags, epw, aoff, nbytes = take("<QBBIQQ")
            dt = _DTYPES[code]
            data = np.frombuffer(payload, dtype=dt, count=nbytes // dt.itemsize, offset=off)
            off += nbytes
            msg.arrays.append(ArrayRecord(array_id, data, flags, epw, aoff))
        return msg


_HEADER = struct.Struct("!BQ")


def send_message(sock, msg: Message) -> None:
    payload = msg.encode()
    data = _HEADER.pack(msg.command, len(payload)) + payload
    if FAULTS.enabled and FAULTS.fire("socket-drop", where="send"):
        # chaos plane: disconnect MID-message — half the frame lands,
        # then the socket dies (the peer's recv sees a torn message;
        # this side's next op sees a dead socket)
        try:
            sock.sendall(data[: max(1, len(data) // 2)])
        finally:
            try:
                sock.close()
            except OSError:
                pass
        raise ConnectionError("injected socket drop mid-send (CK_FAULTS)")
    sock.sendall(data)


def _recv_exact(sock, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(1 << 20, n - got))
        if not chunk:
            raise ConnectionError("socket closed mid-message")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_message(sock) -> Message:
    if FAULTS.enabled and FAULTS.fire("socket-drop", where="recv"):
        try:
            sock.close()
        except OSError:
            pass
        raise ConnectionError("injected socket drop mid-recv (CK_FAULTS)")
    header = _recv_exact(sock, _HEADER.size)
    command, length = _HEADER.unpack(header)
    payload = _recv_exact(sock, length) if length else b""
    return Message.decode(command, payload)
