"""Elastic membership for the cluster tier: epoch-numbered member sets,
heartbeat/timeout liveness, LCM-step re-splits on join/leave, and
per-window partition checkpoints — preemption-safe execution.

``jax.distributed`` jobs cannot lose or add processes mid-run (the
static-membership note in ``dcn.py``), so elasticity at the DCN tier is
RESTART-shaped: production TPU slices get preempted whole, the job
comes back (possibly with a different process count), and the work must
resume exactly where it left off.  Three primitives make that safe:

- :class:`Membership` — an epoch-numbered member table (member id →
  LCM step).  Every ``leave``/``join`` bumps the epoch and records a
  replayable ``member-leave``/``member-join`` decision whose outputs
  are the POST-change equal re-split from the new LCM-step table
  (:func:`member_resplit` — the pure function ``ckreplay verify``
  re-executes).  A kill-and-rejoin job's membership transitions are
  therefore event-sourced like every other controller decision.
- :class:`Heartbeat` / :func:`alive_members` — file-mtime heartbeats
  in a shared directory: a member whose beat goes stale past
  ``timeout_s`` is detected as departed (the detection half of
  preemption — the TCP tier and tests drive :meth:`Membership.sync`
  from it).
- :func:`save_window` / :func:`resume_window` — lightweight per-window
  checkpoints of the partition state through
  ``utils/checkpoint.py``'s atomic tmp+rename path, carrying the
  window index and the member-step table as metadata.  A restarted
  job resumes from the last COMPLETE window (torn newest steps fall
  back — ``utils/checkpoint.load_arrays``), re-splits for its new
  membership, and continues: windows are applied exactly once, so a
  kill-and-rejoin run converges to the bit-identical image of an
  undisturbed one (tests/_dcn_elastic_worker.py is the harness).

The restore is recorded as a ``checkpoint-restore`` decision (context
record — it reads the filesystem, so it is provenance, not an oracle).
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from ..metrics.registry import REGISTRY
from ..obs.decisions import DECISIONS
from ..obs.flight import FLIGHT
from ..utils import checkpoint as ckpt
from .balancer import ClusterLoadBalancer

__all__ = [
    "Membership",
    "member_resplit",
    "MODEL_INVARIANTS",
    "Heartbeat",
    "alive_members",
    "save_window",
    "resume_window",
    "META_PREFIX",
]

#: Checkpoint-metadata key prefix inside the arrays payload (the
#: window index and member-step table ride the same atomic .npz as the
#: partition arrays — one rename, one unit of consistency).
META_PREFIX = "_ck_meta_"

#: Machine-checked temporal invariants of the elastic-membership
#: machine (the ``MODEL_INVARIANTS`` contract — see ``obs/drain.py``):
#: ``analysis/model.py`` drives a REAL :class:`Membership` through
#: every leave/join/timeout interleaving over a small roster alphabet
#: (ids chosen to exercise the length-then-lex order) and checks each
#: captured ``member-leave``/``member-join`` record against these.
MODEL_INVARIANTS = (
    ("epoch-monotone", "safety",
     "every membership transition bumps the epoch by exactly one — "
     "epochs are strictly monotone across any interleaving"),
    ("resplit-conservation", "safety",
     "member_resplit ranges sum exactly to the total: a membership "
     "change never loses or invents work (remainder folded, not "
     "dropped)"),
    ("resplit-quantized", "safety",
     "every member's re-split share is a non-negative LCM-chunk "
     "multiple; only member 0 (the mainframe rule) may carry the "
     "sub-LCM remainder"),
    ("sync-converges", "liveness",
     "Membership.sync reconciles to exactly the observed roster in "
     "one call — departures recorded before arrivals, a step change "
     "recorded as leave+join, nothing left behind"),
    ("deterministic-order", "safety",
     "the same (roster, observation) diff always records the same "
     "transition sequence — length-then-lex member order, so a "
     "10-member roster cannot reorder the decision log"),
)


def member_resplit(steps: list, total: int) -> dict:
    """The PURE post-change re-split: equal LCM-chunk distribution over
    the (new) member-step table, remainder folded into member 0 (the
    mainframe rule ``dcn.py`` uses).  ONE re-split implementation on
    purpose — this delegates to
    :meth:`~.balancer.ClusterLoadBalancer.resplit_active` (the general
    active-subset form the TCP tier uses in place), so the two can
    never drift and break the replay-verify bit-identity contract.
    ``member-leave``/``member-join`` decision outputs store exactly
    this dict."""
    steps = [int(s) for s in steps]
    bal = ClusterLoadBalancer(steps)
    shares, rem = bal.resplit_active(int(total), range(len(steps)))
    if shares:
        shares[0] += rem
    return {"ranges": shares, "lcm": bal.lcm}


def _member_order(member: str):
    """Length-then-lexicographic member ordering (the obs/drain lane
    key): ``"p2" < "p10"`` — plain ``sorted`` would interleave 10+
    members out of process order and the positional ``steps_after`` /
    ``ranges`` in the decision record would attribute shares to the
    wrong member."""
    return (len(member), member)


class Membership:
    """Epoch-numbered member table (see module docstring).  Member ids
    are strings (``"p0"``, a hostname, …); the value is the member's
    LCM step (device count × local range)."""

    def __init__(self):
        self.epoch = 0
        self.members: dict[str, int] = {}
        self._mu = threading.Lock()
        self._g_epoch = REGISTRY.gauge(
            "ck_member_epoch", "cluster membership epoch")
        self._g_count = REGISTRY.gauge(
            "ck_member_count", "live cluster members")

    def establish(self, members: dict) -> int:
        """Initial member set — epoch 1, no per-member decisions (the
        starting roster is configuration, not a transition)."""
        with self._mu:
            self.members = {str(k): int(v) for k, v in members.items()}
            self.epoch = 1
            self._export_locked()
            return self.epoch

    def _export_locked(self) -> None:
        self._g_epoch.set(float(self.epoch))
        self._g_count.set(float(len(self.members)))

    def _transition(self, kind: str, member: str, step: int | None,
                    total: int | None) -> dict:
        """One leave/join: bump the epoch, record the decision with the
        post-change re-split (when a total is known)."""
        with self._mu:
            before = dict(self.members)
            epoch_before = self.epoch
            if kind == "member-leave":
                self.members.pop(member, None)
            else:
                self.members[str(member)] = int(step or 0)
            self.epoch += 1
            after = dict(self.members)
            epoch_after = self.epoch
            self._export_locked()
        REGISTRY.counter(
            "ck_member_changes_total", "membership transitions",
            kind="leave" if kind == "member-leave" else "join",
        ).inc()
        steps = [after[m] for m in sorted(after, key=_member_order)]
        outputs: dict = {"epoch_after": epoch_after,
                         "members_after": after}
        if total is not None and steps:
            outputs.update(member_resplit(steps, total))
        FLIGHT.event(kind, member=member, epoch=epoch_after,
                     members=len(after))
        if DECISIONS.enabled:
            DECISIONS.record(kind, {
                "member": str(member),
                "step": None if step is None else int(step),
                "epoch_before": epoch_before,
                "members_before": before,
                "steps_after": steps,
                "total": total,
            }, outputs)
        return outputs

    def leave(self, member: str, total: int | None = None) -> dict:
        """A member departed (preemption, timeout): epoch bump +
        recorded ``member-leave`` with the survivors' re-split."""
        return self._transition("member-leave", str(member), None, total)

    def join(self, member: str, step: int, total: int | None = None) -> dict:
        """A member arrived (rejoin, scale-up): epoch bump + recorded
        ``member-join`` with the new roster's re-split."""
        return self._transition("member-join", str(member), step, total)

    def sync(self, present: dict, total: int | None = None) -> list[dict]:
        """Reconcile against an observed member set (e.g. from
        :func:`alive_members` or a restarted job's new roster): one
        recorded transition per departure, then per arrival, in sorted
        member order — deterministic decision sequence for a given
        diff.  Returns the transition outputs in order."""
        present = {str(k): int(v) for k, v in present.items()}
        with self._mu:
            current = dict(self.members)
        out = []
        # a member whose STEP changed (device count moved under the
        # same id) is a rejoin: leave then join, both recorded — the
        # LCM-step table is the re-split's input, so a silent step
        # change would leave the decision log claiming an old geometry
        resized = sorted(
            (m for m in present
             if m in current and present[m] != current[m]),
            key=_member_order)
        for m in sorted(set(current) - set(present),
                        key=_member_order) + resized:
            out.append(self.leave(m, total))
        for m in sorted(set(present) - set(current),
                        key=_member_order) + resized:
            out.append(self.join(m, present[m], total))
        return out

    def snapshot(self) -> dict:
        with self._mu:
            return {"epoch": self.epoch, "members": dict(self.members)}


# -- heartbeats ---------------------------------------------------------------

def _hb_path(root: str, member: str) -> str:
    return os.path.join(root, f"hb_{member}")


class Heartbeat:
    """File-mtime heartbeat: a daemon thread touches
    ``<root>/hb_<member>`` every ``interval_s`` until :meth:`close`.
    Liveness is mtime recency (:func:`alive_members`) — a SIGKILLed
    process simply stops beating, which is exactly the failure mode
    preemption presents."""

    def __init__(self, root: str, member: str, interval_s: float = 0.5,
                 start: bool = True):
        self.root = root
        self.member = str(member)
        self.interval_s = float(interval_s)
        os.makedirs(root, exist_ok=True)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.beat()
        if start:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"ck-heartbeat-{member}")
            self._thread.start()

    def beat(self) -> None:
        path = _hb_path(self.root, self.member)
        with open(path, "w") as f:
            f.write(f"{time.time()}\n")

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.beat()
            except OSError:
                pass  # a full disk must not kill the member itself

    def close(self, remove: bool = False) -> None:
        """Stop beating; ``remove=True`` also retracts the file (a
        CLEAN leave — a crash leaves the file to go stale instead)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if remove:
            try:
                os.remove(_hb_path(self.root, self.member))
            except OSError:
                pass


def alive_members(root: str, timeout_s: float,
                  now: float | None = None) -> list[str]:
    """Members whose heartbeat file's mtime is within ``timeout_s`` of
    ``now`` — sorted; an empty/missing root is an empty roster."""
    if not os.path.isdir(root):
        return []
    t = time.time() if now is None else now
    out = []
    for name in os.listdir(root):
        if not name.startswith("hb_"):
            continue
        try:
            mtime = os.path.getmtime(os.path.join(root, name))
        except OSError:
            continue  # retracted between listdir and stat
        if t - mtime <= timeout_s:
            out.append(name[3:])
    return sorted(out)


# -- per-window partition checkpoints -----------------------------------------

def save_window(root: str, window: int, arrays: dict,
                member_steps: list | None = None) -> str:
    """Checkpoint one completed window's partition state: the arrays
    plus the window index and (optionally) the member-step table, all
    in ONE atomic ``utils/checkpoint.py`` step dir — a killed writer
    never leaves a half-window (tmp+rename), and a reader always gets
    a consistent (window, arrays, membership) triple."""
    payload = dict(arrays)
    payload[META_PREFIX + "window"] = np.asarray([int(window)], np.int64)
    if member_steps is not None:
        payload[META_PREFIX + "members"] = np.asarray(
            [int(s) for s in member_steps], np.int64)
    return ckpt.save_arrays(root, int(window), payload)


def resume_window(root: str) -> dict | None:
    """Load the newest COMPLETE window checkpoint (torn/corrupt newest
    steps fall back — ``utils/checkpoint.load_arrays``'s contract).
    Returns ``{"window", "arrays", "member_steps"}`` or None when no
    checkpoint exists.  The restore lands as a ``checkpoint-restore``
    decision (context record) and a flight event, so a resumed run's
    provenance names exactly which window it continued from."""
    step = ckpt.latest_step(root)
    if step is None:
        return None
    loaded = ckpt.load_arrays(root)
    window = int(loaded.pop(META_PREFIX + "window")[0]) \
        if META_PREFIX + "window" in loaded else step
    members = loaded.pop(META_PREFIX + "members", None)
    member_steps = None if members is None else [int(s) for s in members]
    FLIGHT.event("checkpoint-restore", root=root, window=window,
                 arrays=len(loaded))
    if DECISIONS.enabled:
        DECISIONS.record("checkpoint-restore", {
            "root": root, "latest_step": step,
        }, {
            "window": window,
            "arrays": sorted(loaded),
            "member_steps": member_steps,
        })
    REGISTRY.counter(
        "ck_checkpoint_restores_total",
        "window-checkpoint restores (elastic resume)").inc()
    return {"window": window, "arrays": loaded,
            "member_steps": member_steps}
