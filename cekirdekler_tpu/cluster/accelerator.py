"""Multi-node cluster orchestrator.

TPU-native analogue of ``ClusterAccelerator`` (ClusterAccelerator.cs):
drives N remote :class:`CruncherClient` nodes plus one local
:class:`NumberCruncher` "mainframe" that absorbs the remainder share
(:364-443).  Each compute id gets its own :class:`ClusterLoadBalancer`;
the first call splits equally in LCM-step units, later calls rebalance on
measured per-node wall times (:170-355).

The reference discovers servers by probing 255 LAN IPs over TCP
(:77-155); here discovery takes an explicit endpoint list (the TPU-pod
equivalent is the JAX distributed coordinator address list) — probing a
/24 is a LAN-party artifact, but :meth:`probe` covers the capability for
explicit candidates.

Implements :class:`IComputeNode` (IHesapNode.cs:33-59) so clusters nest.
"""

from __future__ import annotations

import abc
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Sequence

from ..arrays.clarray import ClArray, ParameterGroup
from ..core.cruncher import NumberCruncher
from ..errors import CekirdeklerError, ComputeValidationError
from ..hardware import Devices, all_devices
from .balancer import ClusterLoadBalancer
from .client import CruncherClient

__all__ = ["IComputeNode", "ClusterAccelerator"]


class IComputeNode(abc.ABC):
    """Node abstraction (reference: IHesapNode.cs:33-59) — lets clusters
    nest 'tree-like' (ClusterAccelerator.cs:29-31)."""

    @abc.abstractmethod
    def setup_nodes(self, kernel_source: str) -> None: ...

    @abc.abstractmethod
    def compute(
        self, kernel_names, params, compute_id: int,
        global_range: int, local_range: int,
    ) -> None: ...

    @abc.abstractmethod
    def compute_timing(self, compute_id: int) -> list[float]: ...

    @abc.abstractmethod
    def dispose(self) -> None: ...


class ClusterAccelerator(IComputeNode):
    """N remote nodes + a local mainframe behaving as ONE device."""

    def __init__(
        self,
        endpoints: Sequence[tuple[str, int]] = (),
        local_devices: Devices | None = None,
    ):
        self.clients: list[CruncherClient] = [
            CruncherClient(h, p) for h, p in endpoints
        ]
        self.local_devices = local_devices if local_devices is not None else all_devices()
        self.mainframe: NumberCruncher | None = None
        self.kernel_source: str | None = None
        self.balancers: dict[int, ClusterLoadBalancer] = {}
        self.ranges: dict[int, list[int]] = {}     # per node (clients..., mainframe)
        self.timings: dict[int, list[float]] = {}
        self._shadows: dict[int, ClArray] = {}     # mainframe read-array shadows
        self._pool = ThreadPoolExecutor(max_workers=max(2, len(self.clients) + 1))

    @staticmethod
    def probe(candidates: Sequence[tuple[str, int]], timeout: float = 0.5) -> list[tuple[str, int]]:
        """Find live servers among candidate endpoints (reference:
        findServer's parallel TCP probe, ClusterAccelerator.cs:77-155)."""
        import socket

        def try_one(ep):
            try:
                with socket.create_connection(ep, timeout=timeout):
                    return ep
            except OSError:
                return None

        with ThreadPoolExecutor(max_workers=min(64, max(1, len(candidates)))) as pool:
            return [ep for ep in pool.map(try_one, candidates) if ep is not None]

    @classmethod
    def discover(
        cls, port: int, subnet: str | None = None, timeout: float = 0.5,
    ) -> list[tuple[str, int]]:
        """LAN discovery parity (reference: findServer probes all 255 host
        addresses of the local /24 in parallel and keeps responders,
        ClusterAccelerator.cs:77-155).  ``subnet`` like ``"192.168.1"``;
        None derives it from this host's primary address.  Coordinator
        address lists are the TPU-pod idiom — this exists for the ad-hoc
        LAN fleets the TCP tier serves.

        **A /24 netmask is ASSUMED** when ``subnet`` is None (ADVICE r5):
        the derived prefix is the primary address minus its last octet
        (``rsplit('.', 1)``), exactly the reference's behavior — no
        interface netmask is consulted.  On a WIDER subnet (/23, /16…)
        the 255-host candidate list misses peers outside this /24 slice;
        on a NARROWER one (/25…) it probes addresses beyond the broadcast
        domain (harmless: they just time out).  Fleets on non-/24
        networks should pass ``subnet`` explicitly — one probe call per
        /24 slice — or full endpoint lists to :meth:`probe`."""
        import socket

        if subnet is None:
            # the UDP "connect" assigns the outbound interface without
            # sending a packet — the portable local-address trick
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                try:
                    s.connect(("10.255.255.255", 1))
                    local_ip = s.getsockname()[0]
                except OSError:
                    local_ip = "127.0.0.1"
            subnet = local_ip.rsplit(".", 1)[0]
        candidates = [(f"{subnet}.{h}", port) for h in range(1, 256)]
        return cls.probe(candidates, timeout=timeout)

    # -- IComputeNode --------------------------------------------------------
    def setup_nodes(self, kernel_source: str) -> None:
        """Ship the kernel source to every node and build the local
        mainframe (reference: setupNodes, ClusterAccelerator.cs:364-443)."""
        self.kernel_source = kernel_source
        for c in self.clients:
            c.setup(kernel_source)
        self.mainframe = NumberCruncher(self.local_devices, kernel_source)

    @property
    def num_nodes(self) -> int:
        return len(self.clients) + 1  # + mainframe

    def _steps(self, local_range: int) -> list[int]:
        steps = [
            max(1, c.remote_devices) * local_range for c in self.clients
        ]
        steps.append(max(1, self.mainframe.num_devices) * local_range)
        return steps

    def compute(
        self,
        kernel_names: str | Sequence[str],
        params: Sequence[ClArray],
        compute_id: int,
        global_range: int,
        local_range: int = 256,
        values=(),
    ) -> None:
        if self.mainframe is None or self.kernel_source is None:
            raise CekirdeklerError("setup_nodes() must run before compute()")
        names = (
            kernel_names.split() if isinstance(kernel_names, str) else list(kernel_names)
        )
        if global_range % local_range != 0:
            raise ComputeValidationError(
                f"global_range ({global_range}) must be divisible by local_range ({local_range})"
            )
        params = list(params)
        bal = self.balancers.get(compute_id)
        if bal is None:
            bal = ClusterLoadBalancer(self._steps(local_range))
            self.balancers[compute_id] = bal
            node_ranges, remainder = bal.equal_split(global_range)
        else:
            prev = self.ranges[compute_id]
            times = self.timings.get(compute_id, [1.0] * len(prev))
            node_ranges, remainder = bal.rebalance(prev, times, global_range)
        # mainframe takes its balanced share + the remainder
        shares = list(node_ranges)
        shares[-1] += remainder
        refs = []
        acc = 0
        for r in shares:
            refs.append(acc)
            acc += r
        self.ranges[compute_id] = shares

        # consistent input snapshot, taken on this thread BEFORE any node
        # starts writing results back — concurrent writebacks must not tear
        # the view another node's payload is marshaled from
        import numpy as np

        def eff_read(p: ClArray) -> bool:
            return p.flags.read and not p.flags.write_only

        snapshot = {id(p): p.host().copy() for p in params if eff_read(p)}

        def run_client(i: int):
            if shares[i] <= 0:
                return 0.0
            t0 = time.perf_counter()
            self.clients[i].compute(
                names, params, compute_id, refs[i], shares[i], local_range,
                values, snapshot=snapshot,
            )
            return (time.perf_counter() - t0) * 1000.0

        def mainframe_share(goff: int, grange: int) -> float:
            """Run [goff, goff+grange) on the mainframe against shadow
            arrays (its own copies of the snapshot), then copy the written
            ranges back — reading live host arrays would race client
            writebacks."""
            t0 = time.perf_counter()
            shadows: list[ClArray] = []
            for p in params:
                if eff_read(p):
                    # reuse one shadow per user array: the mainframe worker
                    # caches device buffers by array identity
                    sh = self._shadows.get(id(p))
                    if sh is None or sh.size != p.size or sh.dtype != p.dtype:
                        sh = ClArray(snapshot[id(p)].copy(), name=p.name)
                        self._shadows[id(p)] = sh
                    else:
                        np.copyto(sh.host(), snapshot[id(p)])
                    sh.flags = p.flags
                    shadows.append(sh)
                else:
                    shadows.append(p)
            group = ParameterGroup(shadows)
            group.compute(
                self.mainframe, compute_id, names, grange, local_range,
                global_offset=goff, values=values,
            )
            for p, sh in zip(params, shadows):
                if sh is p or not (p.flags.write and not p.flags.read_only):
                    continue
                if p.flags.write_all:
                    np.copyto(p.host(), sh.host())
                else:
                    epw = p.flags.elements_per_work_item
                    lo, hi = goff * epw, (goff + grange) * epw
                    p.host()[lo:hi] = sh.host()[lo:hi]
            return (time.perf_counter() - t0) * 1000.0

        def run_mainframe():
            i = len(self.clients)
            if shares[i] <= 0:
                return 0.0
            return mainframe_share(refs[i], shares[i])

        futures = [self._pool.submit(run_client, i) for i in range(len(self.clients))]
        futures.append(self._pool.submit(run_mainframe))
        timings: list[float] = []
        failed: list[int] = []
        for i, f in enumerate(futures):
            try:
                timings.append(f.result())
            except Exception:
                # node loss mid-compute (reference leaves this unhandled,
                # SURVEY.md §5.3): remember the node, recover its share
                timings.append(0.0)
                failed.append(i)
        if failed:
            # failover: the mainframe recomputes every lost share (serially,
            # AFTER all surviving writebacks — shares are disjoint)
            for i in failed:
                if shares[i] > 0:
                    timings[-1] += mainframe_share(refs[i], shares[i])
            # drop dead nodes and reset balancer state (step lists changed)
            dead = {id(self.clients[i]) for i in failed}
            for i in failed:
                try:
                    self.clients[i].close()
                except Exception:
                    pass
            self.clients = [c for c in self.clients if id(c) not in dead]
            self.balancers.clear()
            self.ranges.clear()
            self.timings.clear()
            return
        self.timings[compute_id] = timings

    def compute_timing(self, compute_id: int) -> list[float]:
        return list(self.timings.get(compute_id, []))

    def ranges_of(self, compute_id: int) -> list[int]:
        return list(self.ranges.get(compute_id, []))

    def dispose(self) -> None:
        for c in self.clients:
            c.dispose_remote()
            c.close()
        if self.mainframe is not None:
            self.mainframe.dispose()
            self.mainframe = None
        self._pool.shutdown(wait=False)
