"""Cluster compute client: drives one remote server node.

TPU-native analogue of ``ClCruncherClient`` (ClCruncherClient.cs):
``setup`` ships the kernel source (:121-155); ``compute`` marshals the
node's share of ranges + the needed array regions, blocks on the reply,
and writes returned slices back into the caller's host arrays (:156-259);
``control``/``num_devices``/``stop`` mirror the management surface
(:260-325).

Resilience contract (ISSUE 13 — the reference's TCP tier only fails
over at connect time):

- **Per-operation read timeouts.**  Every round trip runs under
  ``op_timeout`` (``socket.settimeout`` on the connection) — a server
  dying mid-``recv_message`` surfaces as a timeout instead of hanging
  the client forever (the seed behavior: only the CONNECT had one).
- **Bounded reconnect with exponential backoff + jitter.**  A failed
  round trip (connection reset, injected socket drop, timeout)
  reconnects and retries up to ``max_retries`` times, sleeping
  ``backoff_s·2^k + jitter`` (capped at ``backoff_max_s``; jitter from
  a seeded RNG so tests are deterministic).  Exhaustion raises the
  NAMED :class:`~cekirdekler_tpu.errors.ClusterRetryExhausted` — a
  dead node is a typed error, never a hang.
- **Idempotent retries via a request sequence number.**  Each logical
  operation gets one ``seq`` (``meta["seq"]``) assigned at first
  attempt; every retry RESENDS the same seq, so a server (or a
  dedup-aware proxy) can recognize a replay.  The retried payload is
  identical — the client's host arrays are unchanged until a reply
  lands, so re-execution produces the same result.
- **Session replay.**  The server's session state (cruncher + array
  cache) is per-connection; after a reconnect the cached ``setup``
  is replayed before the retried operation, so a mid-job failover is
  invisible to the caller beyond latency.

Application errors (``ANSWER_ERROR``) are never retried — they are
deterministic replies, not transport failures.
"""

from __future__ import annotations

import random
import socket
import threading
import time

import numpy as np

from ..arrays.clarray import ClArray
from ..errors import CekirdeklerError, ClusterRetryExhausted
from .netbuffer import (
    FLAG_PARTIAL,
    FLAG_READ,
    FLAG_WRITE,
    FLAG_WRITE_ALL,
    ArrayRecord,
    Command,
    Message,
    recv_message,
    send_message,
)

__all__ = ["CruncherClient"]

_COMMAND_NAMES = {
    v: k for k, v in vars(Command).items() if isinstance(v, int)
}


def _flags_of(arr: ClArray) -> int:
    fl = arr.flags
    out = 0
    if fl.read and not fl.write_only:
        out |= FLAG_READ
    if fl.partial_read:
        out |= FLAG_PARTIAL
    if fl.write and not fl.read_only:
        out |= FLAG_WRITE
    if fl.write_all:
        out |= FLAG_WRITE_ALL
    return out


class CruncherClient:
    """Synchronous request/reply client of one compute node (see the
    module docstring for the retry/timeout contract)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 op_timeout: float = 30.0, max_retries: int = 4,
                 backoff_s: float = 0.05, backoff_max_s: float = 2.0,
                 retry_seed: int = 0):
        self.host = host
        self.port = port
        self.timeout = float(timeout)
        self.op_timeout = float(op_timeout)
        self.max_retries = max(0, int(max_retries))
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self._rng = random.Random(retry_seed)
        self._lock = threading.Lock()
        self._seq = 0
        self._setup_args: tuple[str, int] | None = None
        self.reconnects = 0  # observability: transport failovers survived
        self.remote_devices = 0
        self.sock = self._connect()

    # -- transport ------------------------------------------------------------
    def _connect(self) -> socket.socket:
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # per-OPERATION read timeout: a peer dying mid-recv_message
        # surfaces as socket.timeout (an OSError) instead of a hang
        sock.settimeout(self.op_timeout)
        return sock

    def _reconnect_locked(self) -> None:
        """Close, reconnect, and replay the cached SETUP (the server's
        session state is per-connection).  Caller holds the lock."""
        try:
            self.sock.close()
        except OSError:
            pass
        self.sock = self._connect()
        self.reconnects += 1
        if self._setup_args is not None:
            source, max_devices = self._setup_args
            send_message(self.sock, Message(
                Command.SETUP, meta={"max_devices": max_devices},
                strings=[source],
            ))
            reply = recv_message(self.sock)
            if reply.command == Command.ANSWER_ERROR:
                raise CekirdeklerError(
                    "remote error replaying setup: "
                    f"{reply.strings and reply.strings[0]}")
            self.remote_devices = reply.meta.get("n", 0)

    def _backoff(self, attempt: int) -> float:
        base = min(self.backoff_s * (2 ** attempt), self.backoff_max_s)
        return base * (0.5 + self._rng.random())  # jitter in [0.5, 1.5)·base

    def _roundtrip(self, msg: Message) -> Message:
        """One logical operation: send + receive, with bounded
        reconnect-and-retry on transport failure.  The operation's
        ``seq`` is assigned ONCE — retries resend the identical
        message (idempotency marker, see module docstring)."""
        with self._lock:
            if "seq" not in msg.meta:
                self._seq += 1
                msg.meta["seq"] = self._seq
            last_exc: BaseException | None = None
            for attempt in range(self.max_retries + 1):
                if attempt > 0:
                    time.sleep(self._backoff(attempt - 1))
                    try:
                        self._reconnect_locked()
                    except (ConnectionError, OSError) as e:
                        last_exc = e
                        continue  # node still down — next backoff step
                try:
                    send_message(self.sock, msg)
                    reply = recv_message(self.sock)
                    break
                except (ConnectionError, OSError) as e:
                    last_exc = e
            else:
                op = _COMMAND_NAMES.get(msg.command, str(msg.command))
                raise ClusterRetryExhausted(
                    op, self.max_retries + 1, last_exc) from last_exc
        if reply.command == Command.ANSWER_ERROR:
            if reply.meta.get("reject") and len(reply.strings) >= 3:
                # a serving-tier rejection (circuit-open / brownout /
                # shard-unavailable / ...): re-raise the SAME typed
                # error a local caller gets, named reason and
                # retry-after hint intact (never retried here — an
                # application reply is deterministic, not transport)
                from ..serve.admission import ServeRejected

                raise ServeRejected(
                    str(reply.strings[2]),
                    str(reply.strings[1]),
                    reply.meta.get("retry_after_us", 0) / 1e6)
            raise CekirdeklerError(
                f"remote error: {reply.strings and reply.strings[0]}")
        return reply

    # -- operations -----------------------------------------------------------
    def setup(self, kernel_source: str, max_devices: int = 0) -> int:
        self._setup_args = (kernel_source, int(max_devices))
        reply = self._roundtrip(
            Message(
                Command.SETUP,
                meta={"max_devices": max_devices},
                strings=[kernel_source],
            )
        )
        self.remote_devices = reply.meta.get("n", 0)
        return self.remote_devices

    def compute(
        self,
        kernel_names: list[str],
        params: list[ClArray],
        compute_id: int,
        global_offset: int,
        global_range: int,
        local_range: int,
        values=(),
        snapshot: dict | None = None,
    ) -> None:
        """Run this node's share [global_offset, global_offset+global_range)
        remotely; blocks and writes results back into ``params``.

        ``snapshot`` maps ``id(param) -> numpy copy``: when given, read
        payloads marshal from the snapshot so concurrent writebacks from
        other nodes can't tear the input view."""
        msg = Message(
            Command.COMPUTE,
            meta={
                "compute_id": compute_id,
                "global_offset": global_offset,
                "global_range": global_range,
                "local_range": local_range,
            },
            strings=list(kernel_names),
            values=list(values),
        )
        for p in params:
            flags = _flags_of(p)
            aid = id(p)
            msg.meta[f"size_{aid}"] = p.size
            host = p.host()
            if snapshot is not None and aid in snapshot:
                host = snapshot[aid]
            if flags & FLAG_READ:
                if flags & FLAG_PARTIAL:
                    epw = p.flags.elements_per_work_item
                    lo, hi = global_offset * epw, (global_offset + global_range) * epw
                    data, off = host[lo:hi], lo
                else:
                    data, off = host, 0
            else:
                data, off = host[:0], 0
            msg.arrays.append(
                ArrayRecord(aid, data, flags, p.flags.elements_per_work_item, off)
            )
        reply = self._roundtrip(msg)
        by_id = {id(p): p for p in params}
        for rec in reply.arrays:
            arr = by_id.get(rec.array_id)
            if arr is None:
                continue
            arr.host()[rec.offset : rec.offset + rec.data.size] = rec.data

    def control(self) -> bool:
        """Liveness ping (reference: control, ClCruncherClient.cs:275).
        Retries like every op; a node dead through every attempt
        answers False (ClusterRetryExhausted is a CekirdeklerError)."""
        try:
            return self._roundtrip(Message(Command.CONTROL)).command == Command.ANSWER_CONTROL
        except (CekirdeklerError, OSError, ConnectionError):
            return False

    def num_devices(self) -> int:
        return self._roundtrip(Message(Command.NUM_DEVICES)).meta.get("n", 0)

    def dispose_remote(self) -> None:
        try:
            send_message(self.sock, Message(Command.DISPOSE))
        except OSError:
            pass

    def stop_server(self) -> None:
        try:
            send_message(self.sock, Message(Command.SERVER_STOP))
        except OSError:
            pass

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
