"""Cluster compute client: drives one remote server node.

TPU-native analogue of ``ClCruncherClient`` (ClCruncherClient.cs):
``setup`` ships the kernel source (:121-155); ``compute`` marshals the
node's share of ranges + the needed array regions, blocks on the reply,
and writes returned slices back into the caller's host arrays (:156-259);
``control``/``num_devices``/``stop`` mirror the management surface
(:260-325).
"""

from __future__ import annotations

import socket
import threading

import numpy as np

from ..arrays.clarray import ClArray
from ..errors import CekirdeklerError
from .netbuffer import (
    FLAG_PARTIAL,
    FLAG_READ,
    FLAG_WRITE,
    FLAG_WRITE_ALL,
    ArrayRecord,
    Command,
    Message,
    recv_message,
    send_message,
)

__all__ = ["CruncherClient"]


def _flags_of(arr: ClArray) -> int:
    fl = arr.flags
    out = 0
    if fl.read and not fl.write_only:
        out |= FLAG_READ
    if fl.partial_read:
        out |= FLAG_PARTIAL
    if fl.write and not fl.read_only:
        out |= FLAG_WRITE
    if fl.write_all:
        out |= FLAG_WRITE_ALL
    return out


class CruncherClient:
    """Synchronous request/reply client of one compute node."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self.remote_devices = 0

    def _roundtrip(self, msg: Message) -> Message:
        with self._lock:
            send_message(self.sock, msg)
            reply = recv_message(self.sock)
        if reply.command == Command.ANSWER_ERROR:
            raise CekirdeklerError(f"remote error: {reply.strings and reply.strings[0]}")
        return reply

    def setup(self, kernel_source: str, max_devices: int = 0) -> int:
        reply = self._roundtrip(
            Message(
                Command.SETUP,
                meta={"max_devices": max_devices},
                strings=[kernel_source],
            )
        )
        self.remote_devices = reply.meta.get("n", 0)
        return self.remote_devices

    def compute(
        self,
        kernel_names: list[str],
        params: list[ClArray],
        compute_id: int,
        global_offset: int,
        global_range: int,
        local_range: int,
        values=(),
        snapshot: dict | None = None,
    ) -> None:
        """Run this node's share [global_offset, global_offset+global_range)
        remotely; blocks and writes results back into ``params``.

        ``snapshot`` maps ``id(param) -> numpy copy``: when given, read
        payloads marshal from the snapshot so concurrent writebacks from
        other nodes can't tear the input view."""
        msg = Message(
            Command.COMPUTE,
            meta={
                "compute_id": compute_id,
                "global_offset": global_offset,
                "global_range": global_range,
                "local_range": local_range,
            },
            strings=list(kernel_names),
            values=list(values),
        )
        for p in params:
            flags = _flags_of(p)
            aid = id(p)
            msg.meta[f"size_{aid}"] = p.size
            host = p.host()
            if snapshot is not None and aid in snapshot:
                host = snapshot[aid]
            if flags & FLAG_READ:
                if flags & FLAG_PARTIAL:
                    epw = p.flags.elements_per_work_item
                    lo, hi = global_offset * epw, (global_offset + global_range) * epw
                    data, off = host[lo:hi], lo
                else:
                    data, off = host, 0
            else:
                data, off = host[:0], 0
            msg.arrays.append(
                ArrayRecord(aid, data, flags, p.flags.elements_per_work_item, off)
            )
        reply = self._roundtrip(msg)
        by_id = {id(p): p for p in params}
        for rec in reply.arrays:
            arr = by_id.get(rec.array_id)
            if arr is None:
                continue
            arr.host()[rec.offset : rec.offset + rec.data.size] = rec.data

    def control(self) -> bool:
        """Liveness ping (reference: control, ClCruncherClient.cs:275)."""
        try:
            return self._roundtrip(Message(Command.CONTROL)).command == Command.ANSWER_CONTROL
        except (CekirdeklerError, OSError, ConnectionError):
            return False

    def num_devices(self) -> int:
        return self._roundtrip(Message(Command.NUM_DEVICES)).meta.get("n", 0)

    def dispose_remote(self) -> None:
        try:
            send_message(self.sock, Message(Command.DISPOSE))
        except OSError:
            pass

    def stop_server(self) -> None:
        try:
            send_message(self.sock, Message(Command.SERVER_STOP))
        except OSError:
            pass

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
