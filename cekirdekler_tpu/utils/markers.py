"""Progress markers: the framework's dispatch-progress observability
primitive.

The reference's one real observability feature is queue markers — native
callbacks count how many enqueued markers a command queue has reached,
giving in-flight depth and a smoothed 'marker reach speed' used by the
pool scheduler for throttling (ClCommandQueue.cs:99-115,
ClNumberCruncher.cs:356-372, ClPipeline.cs:4788-4827).  The TPU analogue
counts dispatched vs retired operations per lane: XLA dispatch is async,
so 'reached' means the op's result became ready — :meth:`reach_when_ready`
joins ``block_until_ready`` on a completion thread, the PJRT-side
equivalent of the reference's queue-completion callback.

The added/reached counts live in the native C++ counter
(native/kutuphane_tpu.cpp ck_createMarkerCounter et al.) when the library
is available — the same native-callback-counter architecture as the
reference — with a pure-Python fallback.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque

from ..native import load as _load_native

__all__ = ["MarkerCounter"]


class MarkerCounter:
    """Dispatched/retired op counting + smoothed retire rate.

    ``add()`` marks a dispatch; ``reach()`` marks completion *now*;
    ``reach_when_ready(x)`` marks completion when the device value ``x``
    actually retires.  The rate estimate averages the last ``window``
    retire intervals (the reference's 15-sample markerReachSpeed smoothing,
    ClPipeline.cs:4788-4817).
    """

    def __init__(self, window: int = 15):
        self._lock = threading.Lock()
        # (retire-observation time, op count) — batched observations carry
        # their op count so reach_speed() stays ops/second
        self._times: deque[tuple[float, int]] = deque(maxlen=window)
        self._completions: "queue.Queue" = queue.Queue()
        self._completion_thread: threading.Thread | None = None
        self._closed = False
        self._native = _load_native()
        # python-side counters always exist: they are the fallback when no
        # native library is loaded AND the final snapshot after close()
        # releases the native counter (queries must keep working)
        self._added = 0
        self._reached = 0
        self._nid = (
            self._native.ck_createMarkerCounter()
            if self._native is not None else None
        )

    def close(self) -> None:
        """Stop the completion thread and release the native counter.
        ``_closed`` makes the drain thread skip further device joins, so
        the join below converges even when a burst of completions is
        queued on a slow link."""
        self._closed = True
        t = self._completion_thread
        if t is not None:
            self._completions.put(None)
            t.join(timeout=5.0)
            self._completion_thread = None
        # every native access (here and in the count paths) happens under
        # the lock: a reader racing this delete would otherwise pass a
        # freed counter id into the C library (use-after-free)
        with self._lock:
            if self._nid is not None and self._native is not None:
                # snapshot final counts so added/reached/remaining() keep
                # answering after the native counter is gone
                self._added = int(self._native.ck_markersAdded(self._nid))
                self._reached = int(self._native.ck_markersReached(self._nid))
                self._native.ck_deleteMarkerCounter(self._nid)
                self._nid = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- counting ------------------------------------------------------------
    def add(self, n: int = 1) -> None:
        with self._lock:
            if self._nid is not None:
                for _ in range(n):
                    self._native.ck_addMarker(self._nid)
            else:
                self._added += n

    def reach(self, n: int = 1) -> None:
        now = time.perf_counter()
        with self._lock:
            if self._nid is not None:
                for _ in range(n):
                    self._native.ck_markerReached(self._nid)
            else:
                self._reached += n
            # (time, count) samples: batched retirement observations carry
            # their op count, so reach_speed() stays ops/second — n bunched
            # reach() calls would otherwise compress the window span and
            # inflate the rate by orders of magnitude
            self._times.append((now, n))

    def reach_when_ready(self, x, n: int = 1) -> None:
        """Reach when ``x`` (a jax.Array or any object with
        ``block_until_ready``) retires on the device — joined on a
        completion thread so in-flight depth reflects real device work,
        not host dispatch."""
        # ckcheck: ok double-checked lazy start — re-validated under _lock
        if self._completion_thread is None:
            with self._lock:
                if self._completion_thread is None and not self._closed:
                    # daemon: a hung device must not block interpreter exit
                    self._completion_thread = threading.Thread(
                        target=self._drain_completions,
                        name="marker-reach",
                        daemon=True,
                    )
                    self._completion_thread.start()
        self._completions.put((x, n))

    def _drain_completions(self) -> None:
        # BATCHED joins: when several completions are queued, they are
        # joined with ONE jax.block_until_ready over the whole batch (NOT
        # only the newest item — transfer and compute streams of one
        # device can retire out of order, so a single-item join would
        # under-prove the batch).  Without batching, on a tunneled backend
        # where every join costs ~1 RTT (~100 ms), the thread lags minutes
        # behind a burst of light dispatches, remaining() wildly
        # overestimates in-flight depth, and close()'s bounded join leaves
        # an orphan thread to die inside PJRT teardown at interpreter exit
        # (native terminate).  The whole batch retires as ONE weighted
        # rate sample (see below).
        while True:
            # ckcheck: ok sentinel-terminated daemon loop — close()
            # always enqueues the None sentinel; the unbounded get is
            # this thread's idle state
            item = self._completions.get()
            if item is None:
                return
            batch = [item]
            while True:
                try:
                    nxt = self._completions.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:  # close() requested: finish batch, exit
                    item = None
                    break
                batch.append(nxt)
            if not self._closed:
                try:
                    import jax

                    jax.block_until_ready([x for x, _ in batch])
                except Exception:
                    # one poisoned op must not retire the REST of the batch
                    # early (block_until_ready raises on the first failure
                    # before joining the others): join the rest one by one
                    for x, _ in batch:
                        try:
                            x.block_until_ready()
                        except Exception:
                            pass  # a failed op still retires its marker
            # ONE weighted rate sample for the whole batch: per-item
            # reach() calls would bunch the window into microseconds and
            # inflate reach_speed() by orders of magnitude
            self.reach(sum(n for _, n in batch))
            if item is None:
                return

    # -- queries -------------------------------------------------------------
    @property
    def added(self) -> int:
        with self._lock:
            if self._nid is not None:
                return int(self._native.ck_markersAdded(self._nid))
            return self._added

    @property
    def reached(self) -> int:
        with self._lock:
            if self._nid is not None:
                return int(self._native.ck_markersReached(self._nid))
            return self._reached

    def remaining(self) -> int:
        """In-flight depth (reference: countMarkersRemaining)."""
        with self._lock:
            if self._nid is not None:
                return int(self._native.ck_markersRemaining(self._nid))
            return self._added - self._reached

    def drain(self, timeout: float = 30.0) -> None:
        """Block until every added marker has reached (bounded)."""
        deadline = time.perf_counter() + timeout
        while self.remaining() > 0 and time.perf_counter() < deadline:
            time.sleep(0.0005)

    def reach_speed(self) -> float:
        """Retired ops/second over the smoothing window (0 if <2 samples):
        ops counted from the second observation on, over the window span —
        each sample may represent a batch of retirements."""
        with self._lock:
            if len(self._times) < 2:
                return 0.0
            span = self._times[-1][0] - self._times[0][0]
            ops = sum(n for _, n in list(self._times)[1:])
            return ops / span if span > 0 else 0.0

    def reset(self) -> None:
        with self._lock:
            if self._nid is not None:
                self._native.ck_resetMarkerCounter(self._nid)
            self._added = 0
            self._reached = 0
            self._times.clear()
