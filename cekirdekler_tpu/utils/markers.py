"""Progress markers: the framework's dispatch-progress observability
primitive.

The reference's one real observability feature is queue markers — native
callbacks count how many enqueued markers a command queue has reached,
giving in-flight depth and a smoothed 'marker reach speed' used by the
pool scheduler for throttling (ClCommandQueue.cs:99-115,
ClNumberCruncher.cs:356-372, ClPipeline.cs:4788-4827).  The TPU analogue
counts dispatched vs retired operations per lane: XLA dispatch is async,
so 'reached' means the op's result became ready (host callback /
``block_until_ready`` completion).
"""

from __future__ import annotations

import threading
import time
from collections import deque

__all__ = ["MarkerCounter"]


class MarkerCounter:
    """Dispatched/retired op counting + smoothed retire rate.

    ``add()`` marks a dispatch; ``reach()`` marks completion.  The rate
    estimate averages the last ``window`` retire intervals (the
    reference's 15-sample markerReachSpeed smoothing,
    ClPipeline.cs:4788-4817).
    """

    def __init__(self, window: int = 15):
        self._lock = threading.Lock()
        self._added = 0
        self._reached = 0
        self._times: deque[float] = deque(maxlen=window)

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._added += n

    def reach(self, n: int = 1) -> None:
        now = time.perf_counter()
        with self._lock:
            self._reached += n
            self._times.append(now)

    @property
    def added(self) -> int:
        with self._lock:
            return self._added

    @property
    def reached(self) -> int:
        with self._lock:
            return self._reached

    def remaining(self) -> int:
        """In-flight depth (reference: countMarkersRemaining)."""
        with self._lock:
            return self._added - self._reached

    def reach_speed(self) -> float:
        """Retired ops/second over the smoothing window (0 if <2 samples)."""
        with self._lock:
            if len(self._times) < 2:
                return 0.0
            span = self._times[-1] - self._times[0]
            return (len(self._times) - 1) / span if span > 0 else 0.0

    def reset(self) -> None:
        with self._lock:
            self._added = 0
            self._reached = 0
            self._times.clear()
