"""Deterministic, seeded fault injection: the chaos plane the recovery
tier is proved against.

The drain/elastic machinery (obs/drain.py, cluster/elastic.py) exists
for failures that are rare and unreproducible in CI: a lane's link
silently degrading 5x, a driver submit failing mid-window, a cluster
socket dying mid-message.  This module makes those failures FIRST-CLASS
and REPRODUCIBLE: a fault plan is a seeded, named schedule of injection
points, armed by the :data:`FAULTS_ENV` environment variable
(``CK_FAULTS``) or programmatically, and the same plan string always
produces the same fault sequence — a chaos test that fails is re-run
bit-identically from its plan.

Plan grammar (documented in docs/RESILIENCE.md)::

    CK_FAULTS="seed=42;slow-link@lane1:factor=5,times=8;socket-drop@recv:after=2,times=1"

``;``-separated clauses; an optional leading ``seed=N`` seeds the
probabilistic draws.  Each fault clause is
``<point>[@<selector>][:<k>=<v>,...]``:

- **point** — one of :data:`FAULT_POINTS`:
  ``driver-submit`` (a dispatch-driver submit raises
  :class:`~cekirdekler_tpu.errors.InjectedFaultError`), ``lane-stall``
  (the barrier's per-lane fence sleeps ``delay_ms``), ``slow-link``
  (worker H2D/D2H transfers run ``factor``× slower — the injected
  delay is ``(factor-1) × measured wall + delay_ms``), ``socket-drop``
  (a cluster socket send/recv disconnects mid-message),
  ``serve-dispatch`` (a serving-tier dispatch part raises
  :class:`~cekirdekler_tpu.errors.InjectedFaultError` INSIDE the
  frontend's dispatch cycle, before anything reaches a driver queue —
  the blast-radius-containment/retry-budget chaos seam).
- **selector** — ``lane<N>`` matches only that lane's sites; any other
  token matches the site's ``where`` tag (``send``/``recv`` for
  sockets).  Absent = every matching site.
- **params** — ``after=K`` skip the first K matching hits, ``times=M``
  fire at most M times (default unlimited), ``p=0.5`` fire with
  probability p (drawn from a per-clause ``random.Random`` seeded by
  the plan seed — deterministic), ``delay_ms=X`` / ``factor=N`` the
  delay shape.

Design constraints (the flight-recorder family's):

1. **Disabled costs nothing.**  Every instrumented site guards with
   ``if FAULTS.enabled:`` — one attribute read + falsy check; the plane
   is disabled unless a plan is armed.  :meth:`FaultPlane.fire` is a
   declared ckcheck hot root (it is reached from the driver-queue
   submit path): per-point counter handles are cached at arm time and
   the one lock is only taken when an armed clause matches the point.
2. **Every injected fault is evidence.**  A fired clause records a
   ``fault-injected`` flight event and bumps
   ``ck_fault_injected_total{point}`` — postmortems and chaos tests
   read one stream; an unexplained failure can always be checked
   against what was injected.
3. **Determinism is the contract.**  Counting (``after``/``times``) is
   exact under the clause lock, and probabilistic draws come from
   per-clause seeded RNGs — the same plan + the same sequence of
   ``fire()`` calls yields the same fault sequence (pinned by
   tests/test_faultinject.py).
"""

from __future__ import annotations

import os
import random
import threading

from ..errors import InjectedFaultError

__all__ = [
    "FAULT_POINTS",
    "FAULTS_ENV",
    "FaultClause",
    "FaultPlane",
    "FAULTS",
    "parse_plan",
]

FAULTS_ENV = "CK_FAULTS"

#: The declared fault-point vocabulary — every instrumented site names
#: one of these (the EVENT_KINDS contract applied to fault points);
#: docs/RESILIENCE.md carries the table.
FAULT_POINTS = (
    "driver-submit",   # core/worker._DriverQueue.submit — submit raises
    "lane-stall",      # core/cores.Cores.barrier — per-lane fence sleeps
    "slow-link",       # core/worker transfers — Nx slowdown
    "socket-drop",     # cluster/netbuffer send/recv — disconnect mid-message
    "serve-dispatch",  # serve/frontend dispatch cycle — the part raises
)


class FaultClause:
    """One armed fault clause (see the module-docstring grammar)."""

    def __init__(self, point: str, selector: str | None = None,
                 after: int = 0, times: int | None = None, p: float = 1.0,
                 delay_ms: float = 0.0, factor: float = 1.0,
                 rng: random.Random | None = None):
        if point not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; points: {FAULT_POINTS}")
        self.point = point
        self.selector = selector
        self.lane: int | None = None
        if selector and selector.startswith("lane") \
                and selector[4:].isdigit():
            self.lane = int(selector[4:])
        self.after = max(0, int(after))
        self.times = None if times is None else max(0, int(times))
        self.p = float(p)
        self.delay_ms = float(delay_ms)
        self.factor = float(factor)
        self.rng = rng or random.Random(0)
        self.seen = 0    # matching hits observed (exact, under the lock)
        self.fired = 0   # faults actually injected

    def matches(self, lane, where) -> bool:
        if self.selector is None:
            return True
        if self.lane is not None:
            return lane == self.lane
        return where == self.selector

    def to_row(self) -> dict:
        return {
            "point": self.point, "selector": self.selector,
            "after": self.after, "times": self.times, "p": self.p,
            "delay_ms": self.delay_ms, "factor": self.factor,
            "seen": self.seen, "fired": self.fired,
        }


def parse_plan(plan: str, seed: int | None = None
               ) -> tuple[int, list[FaultClause]]:
    """Parse a plan string into ``(seed, clauses)``.  Raises
    ``ValueError`` with the offending clause on any grammar error — an
    armed-but-silently-ignored fault plan would be the worst failure
    mode a chaos rig can have."""
    clauses: list[FaultClause] = []
    plan_seed = 0 if seed is None else int(seed)
    parts = [p.strip() for p in plan.split(";") if p.strip()]
    for idx, part in enumerate(parts):
        if part.startswith("seed="):
            plan_seed = int(part[5:])
            continue
        head, _, params_str = part.partition(":")
        point, _, selector = head.partition("@")
        kw: dict = {}
        if params_str:
            for kv in params_str.split(","):
                k, eq, v = kv.partition("=")
                k = k.strip()
                if not eq or k not in (
                        "after", "times", "p", "delay_ms", "factor"):
                    raise ValueError(
                        f"bad fault param {kv!r} in clause {part!r}")
                kw[k] = int(v) if k in ("after", "times") else float(v)
        clauses.append(FaultClause(
            point.strip(), selector.strip() or None,
            rng=random.Random(plan_seed * 1000 + idx), **kw))
    return plan_seed, clauses


class FaultPlane:
    """The process-global fault injector (:data:`FAULTS`).

    ``enabled`` is a plain attribute (the tracer/flight convention) —
    every instrumented site's disabled fast path is one attribute read.
    """

    def __init__(self):
        self.enabled = False
        self.seed = 0
        self.plan: str | None = None
        self._mu = threading.Lock()
        self._by_point: dict[str, list[FaultClause]] = {}
        self._counters: dict[str, object] = {}
        self.arm_from_env()

    # -- arming ---------------------------------------------------------------
    def arm(self, plan: str, seed: int | None = None) -> None:
        """Arm the plane from a plan string (replaces any armed plan).
        Per-point metric handles are cached HERE so the fire path never
        pays a registry get-or-create (the hot-root discipline)."""
        plan_seed, clauses = parse_plan(plan, seed)
        from ..metrics.registry import REGISTRY

        by_point: dict[str, list[FaultClause]] = {}
        counters: dict[str, object] = {}
        for c in clauses:
            by_point.setdefault(c.point, []).append(c)
        for point in by_point:
            counters[point] = REGISTRY.counter(
                "ck_fault_injected_total",
                "deliberately injected faults (utils/faultinject.py)",
                point=point)
        with self._mu:
            self.seed = plan_seed
            self.plan = plan
            self._by_point = by_point
            self._counters = counters
        self.enabled = bool(by_point)

    def disarm(self) -> None:
        self.enabled = False
        with self._mu:
            self._by_point = {}
            self._counters = {}
            self.plan = None

    def arm_from_env(self) -> bool:
        """Arm from :data:`FAULTS_ENV` (unset/empty = disarmed).
        Returns True when a plan was armed."""
        plan = os.environ.get(FAULTS_ENV)
        if plan:
            self.arm(plan)
            return True
        return False

    # -- the injection sites' entry ------------------------------------------
    def fire(self, point: str, lane: int | None = None,
             where: str | None = None) -> FaultClause | None:
        """One site hit: returns the FIRST armed clause that fires for
        ``(point, lane, where)``, or None.  Counting is exact under the
        clause lock (determinism is the contract); the fired fault
        lands as a ``fault-injected`` flight event + metric."""
        if not self.enabled:
            return None
        clauses = self._by_point.get(point)
        if not clauses:
            return None
        hit: FaultClause | None = None
        with self._mu:
            for c in clauses:
                if not c.matches(lane, where):
                    continue
                c.seen += 1
                if c.seen <= c.after:
                    continue
                if c.times is not None and c.fired >= c.times:
                    continue
                if c.p < 1.0 and c.rng.random() >= c.p:
                    continue
                c.fired += 1
                hit = c
                break
        if hit is None:
            return None
        from ..obs.flight import FLIGHT

        FLIGHT.event(
            "fault-injected", point=point, lane=lane, where=where,
            selector=hit.selector, fired=hit.fired,
            delay_ms=hit.delay_ms, factor=hit.factor)
        counter = self._counters.get(point)
        if counter is not None:
            counter.inc()
        return hit

    def delay_s(self, point: str, lane: int | None = None,
                where: str | None = None, base_s: float = 0.0) -> float:
        """Seconds of injected delay for a delay-shaped point
        (``lane-stall``, ``slow-link``): ``(factor-1)×base_s +
        delay_ms`` when a clause fires, else 0.0."""
        hit = self.fire(point, lane=lane, where=where)
        if hit is None:
            return 0.0
        return max(0.0, (hit.factor - 1.0) * base_s) + hit.delay_ms / 1000.0

    def raise_if_fired(self, point: str, lane: int | None = None,
                       where: str | None = None) -> None:
        """Raise :class:`InjectedFaultError` when a clause fires for
        the point (``driver-submit`` shape)."""
        hit = self.fire(point, lane=lane, where=where)
        if hit is not None:
            raise InjectedFaultError(point, lane=lane, where=where)

    # -- observability --------------------------------------------------------
    def snapshot(self) -> dict:
        with self._mu:
            return {
                "enabled": self.enabled,
                "seed": self.seed,
                "plan": self.plan,
                "clauses": [
                    c.to_row()
                    for cs in self._by_point.values() for c in cs
                ],
            }


#: The process-global plane every instrumented site consults.
FAULTS = FaultPlane()
