"""Host-controlled events — the ClEvent / ClUserEvent analogue.

Reference: ClUserEvent.cs:29-121 — a host-triggered event with an attached
counter, bound to command queues so enqueued work HOLDS until the host
triggers it; Worker.cs:487-557 uses it for a synchronized start across all
of a device's queues.  Here the native tier (kutuphane_tpu.cpp, events
section) provides the condition-variable object — waits through ctypes run
WITHOUT the GIL — with a pure-Python fallback when the toolchain is
unavailable.

The dispatch-gating use (``NumberCruncher.dispatch_gate``): every worker
lane blocks on the event at the top of its compute phase, so triggering
starts all lanes simultaneously — the reference's synchronized queue
start, with TPU dispatch lanes in place of OpenCL queues.
"""

from __future__ import annotations

import threading

from ..native import load as _native_load

__all__ = ["UserEvent"]


class UserEvent:
    """Host-triggered gate with a pending counter (fires at zero).

    ``increment``/``decrement`` mirror the reference's counter semantics
    (ClUserEvent.cs:49-117): hold the gate open for N contributors, fire
    when the last one decrements — or fire immediately with ``trigger()``.
    """

    def __init__(self):
        self._lib = _native_load()
        if self._lib is not None:
            self._id = self._lib.ck_eventCreate()
            self._ev = None
        else:
            self._id = 0
            self._ev = threading.Event()
            self._pending = 0
            self._lock = threading.Lock()

    # -- native/fallback split ------------------------------------------------
    def trigger(self) -> None:
        if self._lib is not None:
            self._lib.ck_eventTrigger(self._id)
        else:
            self._ev.set()

    def fired(self) -> bool:
        if self._lib is not None:
            return self._lib.ck_eventFired(self._id) == 1
        return self._ev.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        """Block until triggered (GIL-free under the native tier)."""
        if self._lib is not None:
            ms = -1 if timeout is None else int(timeout * 1000)
            return self._lib.ck_eventWait(self._id, ms) == 1
        return self._ev.wait(timeout)

    def increment(self) -> None:
        if self._lib is not None:
            self._lib.ck_eventIncrement(self._id)
        else:
            with self._lock:
                self._pending += 1

    def decrement(self) -> None:
        """Decrement the pending counter; fires the event at zero."""
        if self._lib is not None:
            self._lib.ck_eventDecrement(self._id)
        else:
            with self._lock:
                self._pending -= 1
                if self._pending <= 0:
                    self._ev.set()

    def pending(self) -> int:
        if self._lib is not None:
            return int(self._lib.ck_eventPending(self._id))
        with self._lock:
            return self._pending

    def close(self) -> None:
        if self._lib is not None and self._id:
            self._lib.ck_eventDelete(self._id)
            self._id = 0

    def __del__(self):  # best-effort; explicit close preferred
        try:
            self.close()
        except Exception:
            pass
