"""Device-timeline capture and analysis over ``jax.profiler``.

The reference's only profiling is host-side stopwatches (SURVEY.md §5.1;
Worker.cs:753-807, Cores.cs:994-1063) and its planned timeline-overlap query
is a ``NotImplementedException`` (ClPipeline.cs:2391-2399).  This module is
the TPU-native upgrade: capture an Xprof trace around any region, then
answer "how busy was the chip, and how much of the wall time did compute
cover?" from the DEVICE-side event stream instead of host stopwatches.

Backend caveat, stated honestly: tunneled/remote PJRT backends expose XLA
module/op execution events but not DMA-engine events, so transfer busy time
cannot be read off the device timeline there — compute busy/span can, and is
exactly the evidence needed for overlap claims ("during the pipelined run
the compute stream was busy X% of the makespan; transfers supplied it
without starving it").
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from contextlib import contextmanager
from dataclasses import dataclass, field

__all__ = [
    "DeviceTimeline", "Tracer", "capture", "analyze_trace_dir",
    "load_trace_events", "start_profiler", "stop_profiler",
]


@dataclass
class DeviceTimeline:
    """Busy/span statistics of one captured region, from device events."""

    compute_busy_ms: float = 0.0      # union of XLA-op intervals on device
    span_ms: float = 0.0              # first device event start → last end
    n_events: int = 0
    n_devices: int = 0
    per_device_busy_ms: dict = field(default_factory=dict)
    trace_path: str | None = None

    @property
    def compute_busy_fraction(self) -> float:
        """Fraction of the device-event makespan covered by compute — the
        timeline-derived overlap evidence (1.0 = transfers fully hidden
        behind compute; small = the chip sat idle between kernels)."""
        return self.compute_busy_ms / self.span_ms if self.span_ms > 0 else 0.0


def _merged_busy(intervals: list[tuple[float, float]]) -> float:
    """Total length of the union of (start, end) intervals (µs)."""
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    return total + (cur_e - cur_s)


def load_trace_events(trace_dir: str) -> tuple[str | None, list]:
    """(path, traceEvents) of the newest trace-event dump under
    ``trace_dir`` — the shared loader behind :func:`analyze_trace_dir`
    and ``trace/device.py``'s richer parse.  Accepts both the gzipped
    form every ``jax.profiler.trace`` on a JSON-emitting backend writes
    (``*.trace.json.gz``) and a plain ``*.trace.json`` (synthetic
    fixtures, hand-converted dumps).  Returns ``(None, [])`` when the
    directory holds no dump or the newest one does not parse — callers
    degrade to an empty analysis, never raise."""
    files = glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    ) + glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json"), recursive=True
    )
    if not files:
        return None, []
    path = max(files, key=os.path.getmtime)
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt") as f:
                trace = json.load(f)
        else:
            with open(path) as f:
                trace = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError, EOFError):
        return None, []
    # real dumps are ``{"traceEvents": [...]}``; some converters emit
    # the bare event array — accept both (the r8 real-format check)
    if isinstance(trace, list):
        return path, trace
    return path, trace.get("traceEvents", [])


def analyze_trace_dir(trace_dir: str) -> DeviceTimeline:
    """Parse the newest trace dump under ``trace_dir`` and reduce
    the device-side "XLA Ops" tracks to busy/span statistics."""
    path, events = load_trace_events(trace_dir)
    if path is None:
        return DeviceTimeline()
    device_pids: dict[int, str] = {}
    op_tracks: set[tuple[int, int]] = set()
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            name = e.get("args", {}).get("name", "")
            if "/device:" in name:
                device_pids[e["pid"]] = name
        elif e.get("name") == "thread_name":
            if e.get("args", {}).get("name") == "XLA Ops":
                op_tracks.add((e["pid"], e["tid"]))
    per_dev: dict[str, list[tuple[float, float]]] = {}
    lo, hi, count = float("inf"), float("-inf"), 0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        if (e["pid"], e.get("tid")) not in op_tracks:
            continue
        s = float(e.get("ts", 0.0))
        d = float(e.get("dur", 0.0))
        per_dev.setdefault(device_pids[e["pid"]], []).append((s, s + d))
        lo, hi = min(lo, s), max(hi, s + d)
        count += 1
    busy = {k: _merged_busy(v) / 1000.0 for k, v in per_dev.items()}
    return DeviceTimeline(
        compute_busy_ms=sum(busy.values()),
        span_ms=(hi - lo) / 1000.0 if count else 0.0,
        n_events=count,
        n_devices=len(per_dev),
        per_device_busy_ms=busy,
        trace_path=path,
    )


def start_profiler(trace_dir: str):
    """Start a ``jax.profiler`` trace into ``trace_dir`` — the capture
    seam ``trace/device.py`` builds on.  Returns ``(handle, None)`` on
    success or ``(None, reason)`` when profiling is unavailable (the
    region should still run; degrade to a named absence)."""
    try:
        import jax

        prof = jax.profiler.trace(trace_dir)
        prof.__enter__()
        return prof, None
    except Exception as e:  # noqa: BLE001 - unavailability is a reason
        return None, f"{type(e).__name__}: {e}"


def stop_profiler(handle) -> None:
    """Stop a profiler started by :func:`start_profiler` (best-effort:
    Xprof teardown failures never mask the region's own outcome)."""
    if handle is None:
        return
    try:
        handle.__exit__(None, None, None)
    except Exception:  # noqa: BLE001
        pass


@contextmanager
def capture(trace_dir: str):
    """Capture a device timeline around a region::

        with timeline.capture("/tmp/trace") as result:
            ...work...
        print(result().compute_busy_fraction)

    Yields a zero-arg callable returning the :class:`DeviceTimeline`
    (analyzed lazily, after the region closes).  If the backend cannot
    profile, the region still runs and the analysis is empty.  Exceptions
    raised INSIDE the region propagate unchanged (profiler stopped
    best-effort) — only profiler-start failures are swallowed."""
    state: dict = {}
    prof, _err = start_profiler(trace_dir)
    if prof is None:
        # profiling unavailable: run the region untraced rather than fail
        yield lambda: state.setdefault("tl", DeviceTimeline())
        return
    try:
        yield lambda: state.setdefault("tl", analyze_trace_dir(trace_dir))
    finally:
        stop_profiler(prof)


class Tracer:
    """Reusable tracer: each ``region(name)`` captures into its own subdir
    and records the analyzed :class:`DeviceTimeline` under that name."""

    def __init__(self, base_dir: str):
        self.base_dir = base_dir
        self.regions: dict[str, DeviceTimeline] = {}

    @contextmanager
    def region(self, name: str):
        sub = os.path.join(self.base_dir, name)
        with capture(sub) as result:
            yield
        self.regions[name] = result()

    def report(self) -> str:
        lines = []
        for name, tl in self.regions.items():
            lines.append(
                f"{name}: busy {tl.compute_busy_ms:.3f} ms / span {tl.span_ms:.3f} ms "
                f"({100.0 * tl.compute_busy_fraction:.1f}% busy, {tl.n_events} events)"
            )
        return "\n".join(lines) or "(no regions captured)"
