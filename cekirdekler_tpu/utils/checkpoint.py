"""Checkpoint/resume for host arrays and (sharded) jax pytrees.

The reference has NO checkpointing (SURVEY.md §5.4 — state lives in user
arrays, 'checkpoint' is implicitly the user's own host copies); this is a
new subsystem the TPU build adds.  Two surfaces:

- :func:`save_arrays` / :func:`load_arrays` — ClArray/numpy dict → one
  ``.npz`` (the compute-framework tier: user arrays are the state).
- :func:`save_pytree` / :func:`load_pytree` — arbitrary pytrees of
  jax/numpy arrays (model params + optimizer state), one ``.npy`` per
  leaf plus a json manifest of the treedef; sharded ``jax.Array`` leaves
  are fetched to host (process-local) before writing and can be re-placed
  on load with a ``sharding_fn``.

Writes are atomic: a temp directory renamed into place, so a killed run
never leaves a half checkpoint (resume-safety the reference lacks).

Corruption tolerance (ISSUE 13): the NEWEST step can still be torn by
an unlucky crash (a partially-written .npz inside an already-renamed
dir cannot happen, but disk faults and manual copies do) — so
:func:`load_arrays`/:func:`load_pytree` with ``step=None`` fall back
to the previous COMPLETE step when the newest fails to parse,
recording a ``checkpoint-fallback`` flight event; an EXPLICIT step
still raises (the caller pinned exactness).  Stale ``.ckpt_tmp_*``
dirs abandoned by a crashed writer are swept on the next save
(age-gated so a concurrent writer's live tmp survives).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
from typing import Any, Callable, Mapping

import jax
import numpy as np

__all__ = [
    "save_arrays",
    "load_arrays",
    "save_pytree",
    "load_pytree",
    "latest_step",
]

#: A ``.ckpt_tmp_*`` dir older than this at save time belongs to a
#: crashed writer and is swept (a live concurrent writer's tmp is
#: seconds old; single-writer-per-root is the supported pattern).
TMP_SWEEP_AGE_S = 60.0


def _step_dir(root: str, step: int) -> str:
    return os.path.join(root, f"step_{step:012d}")


def _steps_desc(root: str) -> list[int]:
    if not os.path.isdir(root):
        return []
    return sorted((
        int(name[5:]) for name in os.listdir(root)
        if name.startswith("step_") and name[5:].isdigit()
    ), reverse=True)


def latest_step(root: str) -> int | None:
    """Highest checkpoint step under ``root`` (None if empty)."""
    steps = _steps_desc(root)
    return steps[0] if steps else None


def _note_fallback(root: str, bad_step: int, exc: BaseException,
                   to_step: int | None) -> None:
    from ..obs.flight import FLIGHT

    FLIGHT.event(
        "checkpoint-fallback", root=root, bad_step=bad_step,
        fell_back_to=to_step,
        error=f"{type(exc).__name__}: {exc}"[:200])


def _sweep_stale_tmps(root: str) -> int:
    """Remove crashed writers' abandoned tmp dirs (age-gated).
    Returns how many were swept; never raises."""
    swept = 0
    try:
        now = time.time()
        for name in os.listdir(root):
            if not name.startswith(".ckpt_tmp_"):
                continue
            path = os.path.join(root, name)
            try:
                if now - os.path.getmtime(path) > TMP_SWEEP_AGE_S:
                    shutil.rmtree(path, ignore_errors=True)
                    swept += 1
            except OSError:
                continue
        if swept:
            from ..obs.flight import FLIGHT

            FLIGHT.event("checkpoint-sweep", root=root, swept=swept)
    except Exception:  # noqa: BLE001 - sweeping is best-effort hygiene
        pass
    return swept


def _atomic_write(root: str, step: int, write_fn: Callable[[str], None]) -> str:
    os.makedirs(root, exist_ok=True)
    _sweep_stale_tmps(root)
    final = _step_dir(root, step)
    tmp = tempfile.mkdtemp(prefix=".ckpt_tmp_", dir=root)
    try:
        write_fn(tmp)
        if os.path.isdir(final):
            shutil.rmtree(final)
        os.replace(tmp, final)
    except Exception:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return final


# -- array-dict surface ------------------------------------------------------

def save_arrays(root: str, step: int, arrays: Mapping[str, Any]) -> str:
    """Checkpoint named host arrays (ClArray or numpy) at ``step``."""
    host = {}
    for name, arr in arrays.items():
        host[name] = np.asarray(arr.host() if hasattr(arr, "host") else arr)

    def write(tmp: str) -> None:
        np.savez(os.path.join(tmp, "arrays.npz"), **host)

    return _atomic_write(root, step, write)


def _load_arrays_step(root: str, step: int) -> dict[str, np.ndarray]:
    with np.load(os.path.join(_step_dir(root, step), "arrays.npz")) as z:
        return {k: z[k].copy() for k in z.files}


def load_arrays(root: str, step: int | None = None) -> dict[str, np.ndarray]:
    """Load the arrays of ``step`` (default: latest COMPLETE step — a
    torn/corrupt newest falls back to the previous one with a
    ``checkpoint-fallback`` flight event; an explicit ``step`` raises
    on corruption, the caller pinned exactness)."""
    if step is not None:
        return _load_arrays_step(root, step)
    steps = _steps_desc(root)
    if not steps:
        raise FileNotFoundError(f"no checkpoints under {root}")
    last_exc: BaseException | None = None
    for i, s in enumerate(steps):
        try:
            return _load_arrays_step(root, s)
        except Exception as e:  # noqa: BLE001 - torn newest, try previous
            _note_fallback(root, s, e,
                           steps[i + 1] if i + 1 < len(steps) else None)
            last_exc = e
    raise last_exc


# -- pytree surface ----------------------------------------------------------

def save_pytree(root: str, step: int, tree: Any) -> str:
    """Checkpoint a pytree of jax/numpy arrays (params, optimizer state)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)

    def write(tmp: str) -> None:
        manifest = {"treedef": str(treedef), "n_leaves": len(leaves), "step": step}
        for i, leaf in enumerate(leaves):
            np.save(os.path.join(tmp, f"leaf_{i:05d}.npy"), np.asarray(leaf))
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            from .jsonsafe import json_safe

            # step may arrive as a numpy scalar from a training loop;
            # the manifest must stay loadable by strict parsers
            json.dump(json_safe(manifest), f, allow_nan=False)

    return _atomic_write(root, step, write)


def load_pytree(
    root: str,
    like: Any,
    step: int | None = None,
    sharding_fn: Callable[[Any, np.ndarray], Any] | None = None,
) -> Any:
    """Restore a pytree saved by :func:`save_pytree`.

    ``like`` supplies the tree structure (e.g. a freshly-initialized params
    pytree).  ``sharding_fn(like_leaf, loaded)`` may re-place each leaf
    (e.g. ``lambda l, x: jax.device_put(x, l.sharding)``).
    """
    like_leaves, treedef = jax.tree_util.tree_flatten(like)

    class _LeafMismatch(ValueError):
        """A COMPLETE dir disagreeing with `like` — a caller error the
        fallback must NOT absorb (json.JSONDecodeError is also a
        ValueError, so the sentinel keeps torn manifests fallable)."""

    def load_step(d: str):
        with open(os.path.join(d, "manifest.json")) as f:
            manifest = json.load(f)
        if manifest["n_leaves"] != len(like_leaves):
            raise _LeafMismatch(
                f"checkpoint has {manifest['n_leaves']} leaves, 'like' tree has {len(like_leaves)}"
            )
        loaded = []
        for i, like_leaf in enumerate(like_leaves):
            x = np.load(os.path.join(d, f"leaf_{i:05d}.npy"))
            if sharding_fn is not None:
                x = sharding_fn(like_leaf, x)
            loaded.append(x)
        return jax.tree_util.tree_unflatten(treedef, loaded)

    if step is not None:
        return load_step(_step_dir(root, step))
    steps = _steps_desc(root)
    if not steps:
        raise FileNotFoundError(f"no checkpoints under {root}")
    last_exc: BaseException | None = None
    for i, s in enumerate(steps):
        try:
            return load_step(_step_dir(root, s))
        except _LeafMismatch:
            # a complete dir whose leaf count disagrees with `like` is
            # a CALLER error (wrong tree), not a torn checkpoint — an
            # older step would silently load the wrong model
            raise
        except Exception as e:  # noqa: BLE001 - torn newest, try previous
            _note_fallback(root, s, e,
                           steps[i + 1] if i + 1 < len(steps) else None)
            last_exc = e
    raise last_exc
