"""Checkpoint/resume for host arrays and (sharded) jax pytrees.

The reference has NO checkpointing (SURVEY.md §5.4 — state lives in user
arrays, 'checkpoint' is implicitly the user's own host copies); this is a
new subsystem the TPU build adds.  Two surfaces:

- :func:`save_arrays` / :func:`load_arrays` — ClArray/numpy dict → one
  ``.npz`` (the compute-framework tier: user arrays are the state).
- :func:`save_pytree` / :func:`load_pytree` — arbitrary pytrees of
  jax/numpy arrays (model params + optimizer state), one ``.npy`` per
  leaf plus a json manifest of the treedef; sharded ``jax.Array`` leaves
  are fetched to host (process-local) before writing and can be re-placed
  on load with a ``sharding_fn``.

Writes are atomic: a temp directory renamed into place, so a killed run
never leaves a half checkpoint (resume-safety the reference lacks).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any, Callable, Mapping

import jax
import numpy as np

__all__ = [
    "save_arrays",
    "load_arrays",
    "save_pytree",
    "load_pytree",
    "latest_step",
]


def _step_dir(root: str, step: int) -> str:
    return os.path.join(root, f"step_{step:012d}")


def latest_step(root: str) -> int | None:
    """Highest checkpoint step under ``root`` (None if empty)."""
    if not os.path.isdir(root):
        return None
    steps = [
        int(name[5:]) for name in os.listdir(root)
        if name.startswith("step_") and name[5:].isdigit()
    ]
    return max(steps) if steps else None


def _atomic_write(root: str, step: int, write_fn: Callable[[str], None]) -> str:
    os.makedirs(root, exist_ok=True)
    final = _step_dir(root, step)
    tmp = tempfile.mkdtemp(prefix=".ckpt_tmp_", dir=root)
    try:
        write_fn(tmp)
        if os.path.isdir(final):
            shutil.rmtree(final)
        os.replace(tmp, final)
    except Exception:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return final


# -- array-dict surface ------------------------------------------------------

def save_arrays(root: str, step: int, arrays: Mapping[str, Any]) -> str:
    """Checkpoint named host arrays (ClArray or numpy) at ``step``."""
    host = {}
    for name, arr in arrays.items():
        host[name] = np.asarray(arr.host() if hasattr(arr, "host") else arr)

    def write(tmp: str) -> None:
        np.savez(os.path.join(tmp, "arrays.npz"), **host)

    return _atomic_write(root, step, write)


def load_arrays(root: str, step: int | None = None) -> dict[str, np.ndarray]:
    """Load the arrays of ``step`` (default: latest)."""
    if step is None:
        step = latest_step(root)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {root}")
    with np.load(os.path.join(_step_dir(root, step), "arrays.npz")) as z:
        return {k: z[k].copy() for k in z.files}


# -- pytree surface ----------------------------------------------------------

def save_pytree(root: str, step: int, tree: Any) -> str:
    """Checkpoint a pytree of jax/numpy arrays (params, optimizer state)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)

    def write(tmp: str) -> None:
        manifest = {"treedef": str(treedef), "n_leaves": len(leaves), "step": step}
        for i, leaf in enumerate(leaves):
            np.save(os.path.join(tmp, f"leaf_{i:05d}.npy"), np.asarray(leaf))
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            from .jsonsafe import json_safe

            # step may arrive as a numpy scalar from a training loop;
            # the manifest must stay loadable by strict parsers
            json.dump(json_safe(manifest), f, allow_nan=False)

    return _atomic_write(root, step, write)


def load_pytree(
    root: str,
    like: Any,
    step: int | None = None,
    sharding_fn: Callable[[Any, np.ndarray], Any] | None = None,
) -> Any:
    """Restore a pytree saved by :func:`save_pytree`.

    ``like`` supplies the tree structure (e.g. a freshly-initialized params
    pytree).  ``sharding_fn(like_leaf, loaded)`` may re-place each leaf
    (e.g. ``lambda l, x: jax.device_put(x, l.sharding)``).
    """
    if step is None:
        step = latest_step(root)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {root}")
    d = _step_dir(root, step)
    like_leaves, treedef = jax.tree_util.tree_flatten(like)
    with open(os.path.join(d, "manifest.json")) as f:
        manifest = json.load(f)
    if manifest["n_leaves"] != len(like_leaves):
        raise ValueError(
            f"checkpoint has {manifest['n_leaves']} leaves, 'like' tree has {len(like_leaves)}"
        )
    loaded = []
    for i, like_leaf in enumerate(like_leaves):
        x = np.load(os.path.join(d, f"leaf_{i:05d}.npy"))
        if sharding_fn is not None:
            x = sharding_fn(like_leaf, x)
        loaded.append(x)
    return jax.tree_util.tree_unflatten(treedef, loaded)
