"""RFC-8259-safe JSON export: the one sanitizer every export path uses.

Python's ``json.dumps`` serializes ``float('inf')`` / ``nan`` as the
bare tokens ``Infinity`` / ``NaN`` — NOT valid JSON per RFC 8259 —
so every strict consumer (browsers, jq, Go/Rust services, the bench
driver's parser) rejects the whole body.  PR 6 hit exactly this on
``/healthz`` (a zero-baseline health ratio) and fixed it at the source;
numpy scalars are the sibling failure (``TypeError`` mid-export kills
the artifact at the moment it matters).  This module generalizes both
fixes into one helper, and ``tools/ckcheck``'s invariant pass enforces
its use: a ``json.dumps`` on an export path must either wrap its
payload in :func:`json_safe` or pass ``allow_nan=False`` (fail loudly,
never emit invalid JSON).

Rules, applied recursively:

- non-finite floats → ``None`` (the PR 6 convention: absence over an
  unparseable token; consumers already handle null ratios);
- numpy scalars/0-d arrays → native Python via ``.item()`` (then the
  float rule re-applies — ``np.float64('inf')`` becomes ``None`` too);
- numpy ndarrays → lists (element-wise sanitized);
- dict keys → strings (JSON object keys are strings; numpy ints appear
  as lane/cid keys in health tables);
- sets/tuples → lists;
- anything else non-JSON-native → ``str(obj)`` (the postmortem dump's
  ``default=str`` contract: a weird value must never suppress a black
  box).
"""

from __future__ import annotations

import json
import math

__all__ = ["json_safe", "dumps_safe"]

_ATOMS = (str, int, bool, type(None))


def json_safe(obj):
    """A deep copy of ``obj`` that ``json.dumps(..., allow_nan=False)``
    is guaranteed to accept.  Cycles are broken with a placeholder
    rather than recursing forever (a postmortem ``extra`` dict may be
    arbitrarily weird)."""
    return _safe(obj, _seen=set())


def _safe(obj, _seen):
    if isinstance(obj, bool) or obj is None or isinstance(obj, (str, int)):
        return obj
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    # numpy scalars / 0-d arrays expose .item(); ndarrays expose .tolist()
    item = getattr(obj, "item", None)
    if item is not None and getattr(obj, "shape", None) in ((), None):
        try:
            return _safe(item(), _seen)
        except Exception:  # noqa: BLE001 - fall through to str()
            pass
    tolist = getattr(obj, "tolist", None)
    if tolist is not None:
        try:
            return _safe(tolist(), _seen)
        except Exception:  # noqa: BLE001 - fall through to str()
            pass
    if isinstance(obj, dict):
        oid = id(obj)
        if oid in _seen:
            return "<cycle>"
        _seen.add(oid)
        out = {}
        for k, v in obj.items():
            if not isinstance(k, str):
                k = _safe(k, _seen)
                k = "null" if k is None else str(k)
            out[k] = _safe(v, _seen)
        _seen.discard(oid)
        return out
    if isinstance(obj, (list, tuple, set, frozenset)):
        oid = id(obj)
        if oid in _seen:
            return ["<cycle>"]
        _seen.add(oid)
        out = [_safe(v, _seen) for v in obj]
        _seen.discard(oid)
        return out
    return str(obj)


def dumps_safe(obj, **kw) -> str:
    """``json.dumps(json_safe(obj), allow_nan=False, **kw)`` — the
    convenience every in-package export path calls.  ``allow_nan=False``
    stays on even after sanitizing: if a future edit routes an unsafe
    value around :func:`json_safe`, the export raises loudly instead of
    emitting an RFC-invalid body."""
    kw.setdefault("allow_nan", False)
    return json.dumps(json_safe(obj), **kw)
