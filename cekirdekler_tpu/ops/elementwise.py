"""Blockwise fused elementwise ops via Pallas.

``map_blocks`` turns any jnp elementwise function into a tiled Pallas
kernel: inputs are cut into (rows, 128) VMEM blocks on a 1-D grid and the
function is applied per block — one HBM read + one write per array
regardless of how many ops the function fuses (the HBM-bandwidth play of
SURVEY.md §"Design for tpu hardware").  XLA fuses most elementwise chains
by itself; this is the explicit path for chains XLA splits (e.g. around
custom dtypes) and the building block user Pallas kernels plug into the
framework with (kernel/registry.PythonKernel wraps ops like these).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["map_blocks", "saxpy"]

_LANES = 128


def map_blocks(
    fn: Callable,
    *arrays,
    block_rows: int = 256,
    interpret: bool | None = None,
):
    """Apply elementwise ``fn(*blocks) -> block`` over 1-D arrays of equal
    length (multiple of 128)."""
    n = arrays[0].shape[0]
    if any(a.shape != (n,) for a in arrays):
        raise ValueError("map_blocks needs equal-length 1-D arrays")
    if n % _LANES != 0:
        raise ValueError(f"length ({n}) must be a multiple of {_LANES}")
    rows_total = n // _LANES
    rows = min(block_rows, rows_total)
    while rows_total % rows != 0:
        rows //= 2
    rows = max(rows, 1)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def kernel(*refs):
        out_ref = refs[-1]
        out_ref[:] = fn(*(r[:] for r in refs[:-1]))

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows_total, _LANES), arrays[0].dtype),
        grid=(rows_total // rows,),
        in_specs=[pl.BlockSpec((rows, _LANES), lambda i: (i, 0)) for _ in arrays],
        out_specs=pl.BlockSpec((rows, _LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(*(a.reshape(rows_total, _LANES) for a in arrays))
    return out.reshape(n)


@functools.partial(jax.jit, static_argnames=("alpha", "interpret"))
def saxpy(alpha, x, y, interpret: bool | None = None):
    """y + alpha·x, fused in one pass (``alpha`` a python scalar — folded
    into the kernel; pallas_call rejects captured array constants)."""
    a = float(alpha)
    return map_blocks(lambda xb, yb: yb + a * xb, x, y, interpret=interpret)
