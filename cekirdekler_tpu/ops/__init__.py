"""Pallas TPU kernels for the framework's hot ops.

These are the hand-tiled paths the kernel language can't reach: they plug
into the compute API as :class:`~cekirdekler_tpu.kernel.registry.
PythonKernel` functions (the escape hatch for raw-Pallas kernels,
kernel/registry.py) or are called directly.  Off-TPU they run under the
Pallas interpreter so the CPU test rig covers them.
"""

from .elementwise import map_blocks, saxpy
from .mandelbrot import mandelbrot_pallas

__all__ = ["map_blocks", "mandelbrot_pallas", "saxpy"]
