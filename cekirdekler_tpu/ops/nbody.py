"""Direct O(n²) n-body — the reference's flagship numeric workload.

Reference: ``Tester.nBody`` (Tester.cs:7682-7799) is both a correctness
test (±0.01f vs a host loop) and the micro-benchmark behind the device
ranking DSL (``devicesWithHighestDirectNbodyPerformance``,
ClObjectApi.cs:1222-1244).  The kernel-language version
(workloads.NBODY_SRC) exercises the C-subset gather path; this module is
the TPU-fast path: the pairwise interaction sum as one fused XLA program —
broadcasting builds the (chunk, n) distance tile, the VPU does the
rsqrt/accumulate, and XLA tiles it without a Python-visible loop.

``nbody_jnp_kernel`` plugs that math into the SAME compute()/balancer
machinery as the C kernel (a ``@kernel`` Python program, like the
mandelbrot Pallas plug-in, workloads.mandelbrot_pallas_kernel);
``microbenchmark`` times one step on a specific device for the hardware
ranking DSL.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["nbody_accels", "nbody_jnp_kernel", "microbenchmark"]

SOFTENING = 1e-4  # matches NBODY_SRC's +0.0001f


def nbody_accels(xi, yi, zi, x, y, z):
    """Accelerations on bodies (xi, yi, zi) from ALL bodies (x, y, z):
    fused pairwise O(chunk·n) — (chunk, n) tiles, f32."""
    dx = x[None, :] - xi[:, None]
    dy = y[None, :] - yi[:, None]
    dz = z[None, :] - zi[:, None]
    r2 = dx * dx + dy * dy + dz * dz + SOFTENING
    inv = lax.rsqrt(r2) / r2  # 1 / (r2 * sqrt(r2))
    return (dx * inv).sum(axis=1), (dy * inv).sum(axis=1), (dz * inv).sum(axis=1)


def nbody_jnp_kernel():
    """The n-body velocity update as a :func:`~kernel.registry.kernel`
    Python program — same signature as workloads.NBODY_SRC's ``nBody``
    kernel, runnable through the load-balanced compute() path."""
    from ..kernel.registry import kernel

    @kernel(name="nBody", static_values=True)
    def nBody(gid, x, y, z, vx, vy, vz, n=0, dt=0.0):
        chunk = gid.shape[0]
        off = jnp.asarray(gid[0], jnp.int32)
        xi = lax.dynamic_slice(x, (off,), (chunk,))
        yi = lax.dynamic_slice(y, (off,), (chunk,))
        zi = lax.dynamic_slice(z, (off,), (chunk,))
        ax, ay, az = nbody_accels(xi, yi, zi, x, y, z)

        def upd(v, a):
            cur = lax.dynamic_slice(v, (off,), (chunk,))
            return lax.dynamic_update_slice(v, cur + a * dt, (off,))

        return x, y, z, upd(vx, ax), upd(vy, ay), upd(vz, az)

    return nBody


def microbenchmark(device, n: int = 2048, iters: int = 3) -> float:
    """Seconds per full n-body step on ``device`` (lower = faster) — the
    ranking metric behind ``Devices.with_highest_nbody_performance``
    (reference: ClObjectApi.cs:1222-1244 runs Tester.nBody per device)."""
    import numpy as np

    rng = np.random.default_rng(0)
    with jax.default_device(device):
        pos = [
            jnp.asarray(rng.standard_normal(n), jnp.float32) for _ in range(3)
        ]

        @jax.jit
        def step(x, y, z):
            return nbody_accels(x, y, z, x, y, z)

        out = step(*pos)
        np.asarray(out[0][:1])  # warm + fence
        t0 = time.perf_counter()
        for _ in range(iters):
            out = step(*pos)
        np.asarray(out[0][:1])
    return (time.perf_counter() - t0) / max(iters, 1)
