"""Pallas TPU kernel for the mandelbrot workload.

The kernel-language path (workloads.MANDELBROT_SRC) lowers the escape loop
to a vectorized ``lax.while_loop`` over the whole launch chunk — every
iteration streams the full chunk's state. This Pallas version tiles the
flat pixel range into VMEM blocks on a 1-D grid: each program holds one
(rows, 128) block in registers/VMEM for its entire ``fori_loop``, so orbit
state never round-trips HBM and the VPU runs at full tilt.  This is the
hot op behind bench.py (BASELINE.md: Mpixels/sec is the headline metric).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["mandelbrot_pallas", "MANDEL_LANES", "MANDEL_SUBLANES"]

MANDEL_LANES = 128      # TPU lane width
MANDEL_SUBLANES = 8     # f32 sublane tile


def _mandel_kernel(offset_ref, out_ref, *, x0, y0, dx, dy, width, max_iter, rows):
    """One grid step: compute escape counts for a (rows, 128) pixel block.

    Flat pixel index of element (r, c) in this block:
        offset + program_id * rows * 128 + r * 128 + c
    (``offset`` arrives in SMEM so the framework's chunked launcher can
    pass it at runtime without retracing.)
    """
    base = offset_ref[0, 0] + pl.program_id(0) * rows * MANDEL_LANES
    r = jax.lax.broadcasted_iota(jnp.int32, (rows, MANDEL_LANES), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (rows, MANDEL_LANES), 1)
    idx = base + r * MANDEL_LANES + c
    px = idx % width
    py = idx // width
    cx = x0 + dx * px.astype(jnp.float32)
    cy = y0 + dy * py.astype(jnp.float32)

    # no bool/mask in the carry (Mosaic relayout limitation) and no wheres:
    # escaped orbits free-run to inf/nan, and since nan/inf compare False
    # against 4.0 the count freezes at the escape iteration regardless.
    # while_loop gives per-block early exit — a block whose pixels have all
    # escaped stops iterating (big win away from the set boundary).
    def cond(carry):
        i, live, _, _, _ = carry
        return jnp.logical_and(i < max_iter, live > 0.0)

    def body(carry):
        i, _, zx, zy, count = carry
        zx2 = zx * zx
        zy2 = zy * zy
        inside = (zx2 + zy2 < 4.0).astype(jnp.float32)
        count = count + inside
        t = zx2 - zy2 + cx
        zy = 2.0 * zx * zy + cy
        zx = t
        return i + 1, jnp.sum(inside), zx, zy, count

    # init the carry from computed values (cx·0), not jnp.zeros: constant
    # inits get a replicated Mosaic layout the loop body's computed carries
    # can't be relaid out to
    zeros = cx * 0.0
    _, _, _, _, count = jax.lax.while_loop(
        cond, body, (jnp.int32(0), jnp.float32(1.0), zeros, zeros, zeros)
    )
    out_ref[:] = count


@functools.partial(
    jax.jit,
    static_argnames=(
        "n", "x0", "y0", "dx", "dy", "width", "max_iter", "block_rows", "interpret",
    ),
)
def mandelbrot_pallas(
    n: int,
    x0: float,
    y0: float,
    dx: float,
    dy: float,
    width: int,
    max_iter: int,
    offset=0,
    block_rows: int = 512,  # device-timeline sweep on v5e: 512 > 256 > 128
    interpret: bool | None = None,
):
    """Escape counts (f32) for flat pixels [offset, offset+n).

    ``n`` must be a multiple of 128; blocks are (block_rows, 128);
    ``offset`` may be a traced scalar (no retrace per chunk).
    ``interpret=None`` auto-selects the Pallas interpreter off-TPU.
    """
    if n % MANDEL_LANES != 0:
        raise ValueError(f"n ({n}) must be a multiple of {MANDEL_LANES}")
    rows_total = n // MANDEL_LANES
    rows = min(block_rows, rows_total)
    while rows_total % rows != 0:
        rows //= 2
    rows = max(rows, 1)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # python-float scalars fold into the kernel trace (array constants are
    # rejected by pallas_call); f32 rounding of the coefficients matches the
    # kernel-language path
    kernel = functools.partial(
        _mandel_kernel,
        x0=float(np.float32(x0)), y0=float(np.float32(y0)),
        dx=float(np.float32(dx)), dy=float(np.float32(dy)),
        width=width, max_iter=max_iter, rows=rows,
    )
    grid = rows_total // rows
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows_total, MANDEL_LANES), jnp.float32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(
                (1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM
            )
        ],
        out_specs=pl.BlockSpec((rows, MANDEL_LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(jnp.asarray(offset, jnp.int32).reshape(1, 1))
    return out.reshape(n)
