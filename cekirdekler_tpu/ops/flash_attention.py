"""Flash attention — Pallas TPU kernel for the transformer's hot op.

The framework's attention tier so far: a dense jnp reference
(parallel/attention.py:attention_reference) and the ring/Ulysses
sequence-parallel forms whose INNER block math is plain XLA einsums.  This
module adds the single-chip hot op those forms sit on: a tiled
flash-attention forward in Pallas — Q blocks resident in VMEM, K/V streamed
block-by-block with a running stable-softmax (max/denominator carries), so
attention memory is O(block²) instead of O(T²) and the MXU runs back-to-back
``q·kᵀ`` / ``p·v`` contractions without materializing scores in HBM.

Causal masking skips fully-masked K blocks entirely (the loop bound per Q
block is derived from its last query position), halving causal work.

Gradients: ``flash_attention`` carries a ``jax.custom_vjp`` whose backward
is ALSO tiled Pallas (FlashAttention-2 structure): the forward saves the
per-row logsumexp, the backward recomputes each score block from it (the
flash trade — FLOPs for memory) and runs two kernels, one accumulating dq
across k blocks and one accumulating dk/dv across q blocks, so training
memory stays O(T) + O(block²) — the full [T, T] probability matrix is
never materialized in either direction.

Mosaic constraints mirror ops/mandelbrot.py: no ±inf mask arithmetic in the
carry path (a −1e30 additive mask keeps every exp finite) and accumulators
derived from computed values, not constants.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

__all__ = ["flash_attention", "flash_attention_parts",
           "flash_attention_bwd_parts", "auto_block"]

_NEG = -1e30  # finite "-inf": exp(_NEG - m) == 0 without nan hazards


def auto_block(T: int, target: int = 512, floor: int = 8) -> int | None:
    """Largest power-of-two block ≤ ``target`` dividing ``T``, or None when
    only degenerate tiles (< ``floor``) divide it — callers should fall
    back to dense attention then (a (1, D)-tile grid of T² steps is far
    slower than the dense einsum it replaces).

    The 512 default target comes from an on-chip block sweep (T=4096,
    D=64, f32): small 128² blocks leave the MXU ~6% utilized (the
    per-block softmax VPU work dominates); 256-1024 element blocks are
    1.5-3x faster, with q=512/k=512 the fwd+bwd sweet spot (r5
    full-gradient sweep)."""
    blk = math.gcd(T, target)
    return blk if blk >= floor else None


def _fa_kernel(*refs, scale, block_q, block_k, n_kb, causal, precision,
               parts=False, with_lse=False):
    """One (bh, q-block, k-block) grid step.

    The k dimension is the MINOR grid axis: Pallas runs it sequentially per
    q block and auto-pipelines the K/V block DMA behind compute (double
    buffering — the kernel never holds more than one K/V block in VMEM, so
    sequence length is unbounded).  Running max / denominator / output
    accumulate in VMEM scratch across the k steps; the final k step
    normalizes into the output block.

    ``parts=True`` is the ring-attention inner form: two extra SMEM scalars
    (global position offsets of this chip's Q and the in-flight K/V block,
    runtime values — the ring rotates them) shift the causal mask, and the
    kernel emits the UNNORMALIZED accumulator plus running max/denominator
    so ring steps merge stable-softmax state across chips."""
    if parts:
        q_off_ref, k_off_ref = refs[0], refs[1]
        q_ref, k_ref, v_ref = refs[2:5]
        o_ref, m_ref, l_ref = refs[5:8]
        m_scr, l_scr, acc_scr = refs[8:]
        q_pos0 = q_off_ref[0, 0]
        k_pos0 = k_off_ref[0, 0]
    elif with_lse:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs[:5]
        m_scr, l_scr, acc_scr = refs[5:]
        q_pos0 = k_pos0 = 0
    else:
        q_ref, k_ref, v_ref, o_ref = refs[:4]
        m_scr, l_scr, acc_scr = refs[4:]
        q_pos0 = k_pos0 = 0
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: the last query of block qi attends keys at global positions
    # <= its own; blocks wholly beyond that are skipped (no FLOPs, the DMA
    # is wasted but the grid is dense)
    live = (
        (k_pos0 + kj * block_k <= q_pos0 + qi * block_q + block_q - 1)
        if causal
        else True
    )

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale      # (bq, D)
        kb = k_ref[0].astype(jnp.float32)             # (bk, D)
        vb = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )                                             # (bq, bk)
        if causal:
            q_pos = q_pos0 + qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = k_pos0 + kj * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(k_pos <= q_pos, s, _NEG)
        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        l_scr[:, 0] = l_scr[:, 0] * alpha + p.sum(axis=-1)
        m_scr[:, 0] = m_new

    @pl.when(kj == n_kb - 1)
    def _finish():
        if parts:
            o_ref[0] = acc_scr[...]
            m_ref[0] = jnp.broadcast_to(
                m_scr[:, 0][:, None], m_ref.shape[1:]
            )
            l_ref[0] = jnp.broadcast_to(
                l_scr[:, 0][:, None], l_ref.shape[1:]
            )
        else:
            o_ref[0] = (
                acc_scr[...] / jnp.maximum(l_scr[:, 0], 1e-30)[:, None]
            ).astype(o_ref.dtype)
            if with_lse:
                lse = m_scr[:, 0] + jnp.log(jnp.maximum(l_scr[:, 0], 1e-30))
                lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])



def _resolve(interpret, precision):
    """One place for the interpret default (Pallas interpreter off-TPU)
    and the precision-string -> lax.Precision mapping — used by the
    primal, parts, fwd, and bwd paths so they can never diverge."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    prec = (
        lax.Precision.HIGHEST if precision == "highest"
        else lax.Precision.DEFAULT
    )
    return interpret, prec


def _blocks_for(Tq: int, Tk: int, block_q: int, block_k: int):
    """Effective (bq, bk): the largest divisors of the sequence lengths
    not exceeding the requested blocks (gcd) — so default-argument calls
    degrade gracefully for any T a smaller block would have handled
    (e.g. T=640 with the 512/512 defaults -> 128-wide tiles).

    The degradation floor is a quarter of the smaller requested block,
    capped at 32 rows/columns: default-argument calls for short
    sequences like T=32 or T=96 keep working after the block retunes
    (r4 advisor note), explicitly-requested tiny blocks (e.g. 16/16 in
    tests) are honored, and genuinely awkward lengths (T=4104 → 8-wide
    tiles under the defaults, ~100x slower than the dense einsum this
    replaces) raise loudly rather than run silently degenerate."""
    bq = math.gcd(Tq, block_q)
    bk = math.gcd(Tk, block_k)
    floor = min(32, max(8, min(block_q, block_k) // 4))
    if bq < floor or bk < floor:
        raise ValueError(
            f"sequence lengths (Tq={Tq}, Tk={Tk}) admit only degenerate "
            f"tiles ({bq}, {bk}) for requested blocks ({block_q}, "
            f"{block_k}); use auto_block() or pad the sequence"
        )
    return bq, bk


def _vma_sds(*operands):
    """ShapeDtypeStruct factory carrying the union of the operands'
    varying-axes sets — under shard_map every pallas_call output must
    declare how it varies over mesh axes (a replicated q attending
    sharded k/v still produces per-shard-varying output)."""
    try:
        vma = frozenset().union(*(jax.typeof(o).vma for o in operands))
        return functools.partial(jax.ShapeDtypeStruct, vma=vma)
    except (TypeError, AttributeError):
        return jax.ShapeDtypeStruct


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret", "precision",
                     "with_lse"),
)
def _flash_forward(q, k, v, causal, block_q, block_k, interpret, precision,
                   with_lse=False):
    """Forward pass; ``with_lse=True`` also emits the per-row logsumexp
    (m + log l) in lane-broadcast layout [B*H, Tq, 128] — the residual
    the tiled backward reconstructs probabilities from."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    bq, bk = _blocks_for(Tq, Tk, block_q, block_k)
    if causal and Tq != Tk:
        raise ValueError("causal flash attention requires Tq == Tk")
    # [B, T, H, D] -> [B*H, T, D]: one grid row per (batch, head)
    q3 = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    k3 = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    v3 = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    n_kb = Tk // bk
    kernel = functools.partial(
        _fa_kernel, scale=scale, block_q=bq, block_k=bk, n_kb=n_kb,
        causal=causal, precision=precision, with_lse=with_lse,
    )
    from jax.experimental.pallas import tpu as pltpu

    sds = _vma_sds(q3, k3, v3)
    out_specs = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))
    out_shape = sds((B * H, Tq, D), q.dtype)
    if with_lse:
        out_specs = [out_specs,
                     pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0))]
        out_shape = [out_shape, sds((B * H, Tq, 128), jnp.float32)]
    res = pl.pallas_call(
        kernel,
        grid=(B * H, Tq // bq, n_kb),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),  # running max (col 0)
            pltpu.VMEM((bq, 128), jnp.float32),  # running denominator
            pltpu.VMEM((bq, D), jnp.float32),    # output accumulator
        ],
        interpret=interpret,
    )(q3, k3, v3)
    if with_lse:
        out, lse = res
        return (
            out.reshape(B, H, Tq, D).transpose(0, 2, 1, 3),
            lse,  # [B*H, Tq, 128] lane-broadcast, fed to the backward as-is
        )
    return res.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret", "precision"),
)
def flash_attention_parts(
    q, k, v, q_pos0=0, k_pos0=0, causal=False, block_q=128, block_k=128,
    interpret=None, precision="highest",
):
    """Ring-attention inner: UNNORMALIZED flash accumulation of q against
    one K/V block with runtime global position offsets for the causal
    mask.  Returns ``(acc, m, l)`` — acc f32 [B, Tq, H, D], running max
    and denominator f32 [B, Tq, H] — which ring steps merge with the
    standard stable-softmax combine (parallel/attention.py).  Forward
    only (no custom_vjp): training uses the einsum ring path."""
    from jax.experimental.pallas import tpu as pltpu

    interpret, prec = _resolve(interpret, precision)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    if Tq % bq or Tk % bk:
        raise ValueError(
            f"sequence lengths (Tq={Tq}, Tk={Tk}) must be multiples of the "
            f"blocks (bq={bq}, bk={bk})"
        )
    q3 = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    k3 = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    v3 = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    n_kb = Tk // bk
    kernel = functools.partial(
        _fa_kernel, scale=scale, block_q=bq, block_k=bk, n_kb=n_kb,
        causal=causal, precision=prec, parts=True,
    )
    scalar_spec = pl.BlockSpec((1, 1), lambda b, i, j: (0, 0),
                               memory_space=pltpu.SMEM)
    tile_q = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))
    tile_k = pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0))
    tile_ml = pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0))
    try:
        vma = frozenset(
            jax.typeof(q3).vma | jax.typeof(k3).vma | jax.typeof(v3).vma
        )
        sds = functools.partial(jax.ShapeDtypeStruct, vma=vma)
    except (TypeError, AttributeError):
        sds = jax.ShapeDtypeStruct
    acc, m, l = pl.pallas_call(
        kernel,
        grid=(B * H, Tq // bq, n_kb),
        in_specs=[scalar_spec, scalar_spec, tile_q, tile_k, tile_k],
        out_specs=[tile_q, tile_ml, tile_ml],
        out_shape=[
            sds((B * H, Tq, D), jnp.float32),
            sds((B * H, Tq, 128), jnp.float32),
            sds((B * H, Tq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(
        jnp.asarray(q_pos0, jnp.int32).reshape(1, 1),
        jnp.asarray(k_pos0, jnp.int32).reshape(1, 1),
        q3, k3, v3,
    )
    acc = acc.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)
    m = m[..., 0].reshape(B, H, Tq).transpose(0, 2, 1)
    l = l[..., 0].reshape(B, H, Tq).transpose(0, 2, 1)
    return acc, m, l


def _fa_bwd_dq_kernel(*refs, scale, block_q, block_k, n_kb, causal, precision,
                      parts=False):
    """Backward dq: grid (bh, q-block, k-block minor).  Recomputes each
    score block from q/k and the saved logsumexp, accumulates
    dq += ds · K in VMEM scratch across the k steps.

    ``parts=True`` prepends two SMEM scalars (global position offsets of
    this chip's Q and the in-flight K/V block) shifting the causal mask —
    the ring backward's analogue of the parts forward kernel."""
    if parts:
        q_off_ref, k_off_ref = refs[0], refs[1]
        q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dq_ref = refs[2:9]
        (dq_scr,) = refs[9:]
        q_pos0 = q_off_ref[0, 0]
        k_pos0 = k_off_ref[0, 0]
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dq_ref = refs[:7]
        (dq_scr,) = refs[7:]
        q_pos0 = k_pos0 = 0
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    live = (
        (k_pos0 + kj * block_k <= q_pos0 + qi * block_q + block_q - 1)
        if causal
        else True
    )

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale       # (bq, D)
        kb = k_ref[0].astype(jnp.float32)              # (bk, D)
        vb = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)             # (bq, D)
        lse = lse_ref[0][:, 0]                         # (bq,)
        dlt = dlt_ref[0][:, 0]                         # (bq,)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        if causal:
            q_pos = q_pos0 + qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_pos0 + kj * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG)
        p = jnp.exp(s - lse[:, None])                  # (bq, bk)
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        ds = p * (dp - dlt[:, None])
        dq_scr[...] = dq_scr[...] + jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )

    @pl.when(kj == n_kb - 1)
    def _finish():
        dq_ref[0] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(*refs, scale, block_q, block_k, n_qb, causal,
                       precision, parts=False):
    """Backward dk/dv: grid (bh, k-block, q-block minor).  Accumulates
    dv += pᵀ · dO and dk += dsᵀ · q in VMEM scratch across the q steps.

    ``parts=True``: SMEM global position offsets, as in the dq kernel."""
    if parts:
        q_off_ref, k_off_ref = refs[0], refs[1]
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dk_ref,
         dv_ref) = refs[2:10]
        dk_scr, dv_scr = refs[10:]
        q_pos0 = q_off_ref[0, 0]
        k_pos0 = k_off_ref[0, 0]
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dk_ref,
         dv_ref) = refs[:8]
        dk_scr, dv_scr = refs[8:]
        q_pos0 = k_pos0 = 0
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    live = (
        (k_pos0 + kj * block_k <= q_pos0 + qi * block_q + block_q - 1)
        if causal
        else True
    )

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0]
        dlt = dlt_ref[0][:, 0]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        if causal:
            q_pos = q_pos0 + qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_pos0 + kj * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG)
        p = jnp.exp(s - lse[:, None])                  # (bq, bk)
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),           # pᵀ · do -> (bk, D)
            preferred_element_type=jnp.float32, precision=precision,
        )
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        ds = p * (dp - dlt[:, None])
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),           # dsᵀ · q -> (bk, D)
            preferred_element_type=jnp.float32, precision=precision,
        )

    @pl.when(qi == n_qb - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)   # q pre-scaled
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret", "precision"),
)
def _flash_backward(q, k, v, out, lse3, do, causal, block_q, block_k,
                    interpret, precision):
    """Tiled flash backward: dq in one pallas_call (k minor), dk/dv in a
    second (q minor).  ``lse3`` arrives in compact [B*H, Tq, 1] layout
    (the residual held across the fwd→bwd interval must be O(T), not
    O(128·T) — r4 advisor note) and is re-broadcast to the 128-lane tile
    layout here, at backward time; delta = rowsum(dO ∘ O) is a cheap XLA
    reduction."""
    from jax.experimental.pallas import tpu as pltpu

    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    lse3 = jnp.broadcast_to(lse3[..., :1], (B * H, Tq, 128))
    bq, bk = _blocks_for(Tq, Tk, block_q, block_k)
    scale = 1.0 / math.sqrt(D)
    q3 = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    k3 = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    v3 = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    do3 = do.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    # delta_i = sum_d dO_id * O_id, broadcast to the (.., 128) lane layout
    delta = jnp.einsum(
        "bqhd,bqhd->bhq", do.astype(jnp.float32), out.astype(jnp.float32)
    ).reshape(B * H, Tq)
    dlt3 = jnp.broadcast_to(delta[..., None], (B * H, Tq, 128))
    sds = _vma_sds(q3, k3, v3, do3)
    n_qb, n_kb = Tq // bq, Tk // bk
    tile_q = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))
    tile_ml = pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0))
    tile_k_minor = pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0))
    dq = pl.pallas_call(
        functools.partial(
            _fa_bwd_dq_kernel, scale=scale, block_q=bq, block_k=bk,
            n_kb=n_kb, causal=causal, precision=precision,
        ),
        grid=(B * H, n_qb, n_kb),
        in_specs=[tile_q, tile_k_minor, tile_k_minor, tile_q, tile_ml,
                  tile_ml],
        out_specs=tile_q,
        out_shape=sds((B * H, Tq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, dlt3)
    # dk/dv: k-block is the 2nd grid axis, q streams as the minor axis
    tile_q_minor = pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0))
    tile_ml_minor = pl.BlockSpec((1, bq, 128), lambda b, j, i: (b, i, 0))
    tile_k = pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _fa_bwd_dkv_kernel, scale=scale, block_q=bq, block_k=bk,
            n_qb=n_qb, causal=causal, precision=precision,
        ),
        grid=(B * H, n_kb, n_qb),
        in_specs=[tile_q_minor, tile_k, tile_k, tile_q_minor, tile_ml_minor,
                  tile_ml_minor],
        out_specs=[tile_k, tile_k],
        out_shape=[
            sds((B * H, Tk, D), k.dtype),
            sds((B * H, Tk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, dlt3)
    reshape = lambda a, T: a.reshape(B, H, T, D).transpose(0, 2, 1, 3)
    return reshape(dq, Tq), reshape(dk, Tk), reshape(dv, Tk)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret", "precision"),
)
def flash_attention_bwd_parts(
    q, k, v, do, lse, delta, q_pos0=0, k_pos0=0, causal=False,
    block_q=128, block_k=128, interpret=None, precision="highest",
):
    """Ring-attention inner BACKWARD: gradients of one chip's queries
    against one in-flight K/V block, with runtime global position offsets
    for the causal mask — the bwd analogue of
    :func:`flash_attention_parts` (same tiled kernels as the single-chip
    backward, SMEM offsets added).

    ``lse`` and ``delta`` are per-row [B, Tq, H] f32: the ring-global
    logsumexp (m + log l merged across ALL ring steps) and
    rowsum(dO ∘ O).  Returns ``(dq_partial, dk_block, dv_block)`` in
    **f32** regardless of input dtype — the caller accumulates partials
    across ring steps, and rounding each partial to a low-precision
    input dtype would add n independent roundings the single-chip
    backward doesn't have (it rounds once from f32 scratch).  The caller
    sums dq over ring steps and rotates dk/dv accumulators with their
    blocks (parallel/attention.py:_raf_bwd)."""
    from jax.experimental.pallas import tpu as pltpu

    interpret, prec = _resolve(interpret, precision)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    if Tq % bq or Tk % bk:
        raise ValueError(
            f"sequence lengths (Tq={Tq}, Tk={Tk}) must be multiples of the "
            f"blocks (bq={bq}, bk={bk})"
        )
    q3 = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    k3 = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    v3 = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    do3 = do.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    to_lanes = lambda a: jnp.broadcast_to(
        a.astype(jnp.float32).transpose(0, 2, 1).reshape(B * H, Tq, 1),
        (B * H, Tq, 128),
    )
    lse3 = to_lanes(lse)
    dlt3 = to_lanes(delta)
    offs = (
        jnp.asarray(q_pos0, jnp.int32).reshape(1, 1),
        jnp.asarray(k_pos0, jnp.int32).reshape(1, 1),
    )
    sds = _vma_sds(q3, k3, v3, do3)
    n_qb, n_kb = Tq // bq, Tk // bk
    scalar_spec = pl.BlockSpec((1, 1), lambda b, i, j: (0, 0),
                               memory_space=pltpu.SMEM)
    tile_q = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))
    tile_ml = pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0))
    tile_k_minor = pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0))
    dq = pl.pallas_call(
        functools.partial(
            _fa_bwd_dq_kernel, scale=scale, block_q=bq, block_k=bk,
            n_kb=n_kb, causal=causal, precision=prec, parts=True,
        ),
        grid=(B * H, n_qb, n_kb),
        in_specs=[scalar_spec, scalar_spec, tile_q, tile_k_minor,
                  tile_k_minor, tile_q, tile_ml, tile_ml],
        out_specs=tile_q,
        out_shape=sds((B * H, Tq, D), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(*offs, q3, k3, v3, do3, lse3, dlt3)
    tile_q_minor = pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0))
    tile_ml_minor = pl.BlockSpec((1, bq, 128), lambda b, j, i: (b, i, 0))
    tile_k = pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0))
    scalar_spec_m = pl.BlockSpec((1, 1), lambda b, j, i: (0, 0),
                                 memory_space=pltpu.SMEM)
    dk, dv = pl.pallas_call(
        functools.partial(
            _fa_bwd_dkv_kernel, scale=scale, block_q=bq, block_k=bk,
            n_qb=n_qb, causal=causal, precision=prec, parts=True,
        ),
        grid=(B * H, n_kb, n_qb),
        in_specs=[scalar_spec_m, scalar_spec_m, tile_q_minor, tile_k,
                  tile_k, tile_q_minor, tile_ml_minor, tile_ml_minor],
        out_specs=[tile_k, tile_k],
        out_shape=[
            sds((B * H, Tk, D), jnp.float32),
            sds((B * H, Tk, D), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(*offs, q3, k3, v3, do3, lse3, dlt3)
    reshape = lambda a, T: a.reshape(B, H, T, D).transpose(0, 2, 1, 3)
    return reshape(dq, Tq), reshape(dk, Tk), reshape(dv, Tk)


def _dense_f32(q, k, v, causal, prec=lax.Precision.HIGHEST):
    """Score/probability recompute used by the backward (plain XLA)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale, k.astype(jnp.float32),
        precision=prec,
    )
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        qpos = jnp.arange(Tq) + (Tk - Tq)
        mask = jnp.arange(Tk)[None, :] <= qpos[:, None]
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return p, scale


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, block_q=512, block_k=512,
                    interpret=None, precision="highest"):
    """Tiled flash attention on TPU (Pallas), fwd AND bwd kernels.

    Shapes match :func:`parallel.attention.attention_reference`:
    q [B, Tq, H, D], k/v [B, Tk, H, D] → [B, Tq, H, D].
    ``interpret=None`` auto-selects the Pallas interpreter off-TPU.
    ``precision``: "highest" (true-f32 MXU passes, matches the dense
    reference bit-for-bit-ish) or "default" (bf16 MXU passes — the usual
    flash-attention trade, ~1e-2 relative on f32 inputs, ~2x faster).
    Default blocks (512/512) are the measured fwd+bwd sweet spot from the
    r5 full-gradient sweep (tools/flash_sweep.py — the r4 256/512 pick
    predates the anti-DCE harness fix and measured a pruned backward);
    training memory is O(T) residuals (out + per-row logsumexp) +
    O(block²) tiles — no [T, T] materialization in either direction."""
    interpret, prec = _resolve(interpret, precision)
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret, prec)


def _fa_fwd(q, k, v, causal, block_q, block_k, interpret, precision):
    interpret, prec = _resolve(interpret, precision)
    out, lse3 = _flash_forward(
        q, k, v, causal, block_q, block_k, interpret, prec, with_lse=True
    )
    # keep only lane 0 of the lane-broadcast kernel output: the residual
    # saved across the whole forward→backward interval is [B*H, Tq, 1]
    # f32 (true O(T)), not the 128x lane-broadcast tile layout
    return out, (q, k, v, out, lse3[..., :1])


def _fa_bwd(causal, block_q, block_k, interpret, precision, res, do):
    q, k, v, out, lse3 = res
    # honor the caller's precision trade in the backward too — it is the
    # dominant training cost, so "default" (bf16 MXU passes) must actually
    # apply here, not just in the forward kernel
    interpret, prec = _resolve(interpret, precision)
    return _flash_backward(
        q, k, v, out, lse3, do, causal, block_q, block_k, interpret, prec
    )


flash_attention.defvjp(_fa_fwd, _fa_bwd)
