"""Flash attention — Pallas TPU kernel for the transformer's hot op.

The framework's attention tier so far: a dense jnp reference
(parallel/attention.py:attention_reference) and the ring/Ulysses
sequence-parallel forms whose INNER block math is plain XLA einsums.  This
module adds the single-chip hot op those forms sit on: a tiled
flash-attention forward in Pallas — Q blocks resident in VMEM, K/V streamed
block-by-block with a running stable-softmax (max/denominator carries), so
attention memory is O(block²) instead of O(T²) and the MXU runs back-to-back
``q·kᵀ`` / ``p·v`` contractions without materializing scores in HBM.

Causal masking skips fully-masked K blocks entirely (the loop bound per Q
block is derived from its last query position), halving causal work.  In
the single-chip kernels the BlockSpec index maps additionally CLAMP the
streamed operand's block index to the last live block on masked grid
steps, so the skipped step issues no new DMA either — without the clamp a
dense causal grid still moves every K/V (or Q/dO) block through HBM twice
over, and the bwd kernels are bandwidth-bound (r6 MFU work).

Kernel dtype policy (r6): the kernels contract in the OPERANDS' dtype with
f32 accumulators (``preferred_element_type``), instead of casting every
block to f32 in-kernel.  ``precision="default"`` on f32 inputs casts
q/k/v (and dO in the backward) to bf16 ONCE at the XLA level, so the
kernels stream HALF the HBM bytes — the bytes bf16 training would actually
move — while the softmax statistics, accumulators and emitted gradients
stay f32.  ``precision="highest"`` still streams f32 and runs true-f32
(multi-pass) MXU contractions, matching the dense reference to ~5e-5.

Gradients: ``flash_attention`` carries a ``jax.custom_vjp`` whose backward
is ALSO tiled Pallas (FlashAttention-2 structure): the forward saves the
per-row logsumexp, the backward recomputes each score block from it (the
flash trade — FLOPs for memory) and runs two kernels, one accumulating dq
across k blocks and one accumulating dk/dv across q blocks, so training
memory stays O(T) + O(block²) — the full [T, T] probability matrix is
never materialized in either direction.  The logsumexp residual and the
``delta = rowsum(dO ∘ O)`` operand ride compact ``[B*H, T, 1]`` columns
end-to-end (forward kernel emits, backward kernels consume) — never the
``[bq, 128]`` lane-broadcast tiles of r5 that carried 128× the bytes.

Mosaic constraints mirror ops/mandelbrot.py: no ±inf mask arithmetic in the
carry path (a −1e30 additive mask keeps every exp finite) and accumulators
derived from computed values, not constants.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

__all__ = ["flash_attention", "flash_attention_parts",
           "flash_attention_bwd_parts", "auto_block", "default_blocks",
           "fused_qkv", "fused_qkv_attention"]

_NEG = -1e30  # finite "-inf": exp(_NEG - m) == 0 without nan hazards

# Smallest block the MXU fills a full 128-lane tile with: below this the
# per-block softmax VPU work dominates and dense XLA attention wins (the
# auto_block docstring's measured cliff) — default-argument calls fall
# back to dense rather than run sub-128 tiles.
_DENSE_FLOOR = 128


def auto_block(T: int, target: int = 512, floor: int = 8) -> int | None:
    """Largest power-of-two block ≤ ``target`` dividing ``T``, or None when
    only degenerate tiles (< ``floor``) divide it — callers should fall
    back to dense attention then (a (1, D)-tile grid of T² steps is far
    slower than the dense einsum it replaces).

    The 512 default target comes from an on-chip block sweep (T=4096,
    D=64, f32): small 128² blocks leave the MXU ~6% utilized (the
    per-block softmax VPU work dominates); 256-1024 element blocks are
    1.5-3x faster, with q=512/k=512 the fwd+bwd sweet spot (r5
    full-gradient sweep)."""
    blk = math.gcd(T, target)
    return blk if blk >= floor else None


def default_blocks(Tq: int, Tk: int | None = None,
                   target: int = 512) -> tuple[int, int] | None:
    """Block policy for DEFAULT-argument :func:`flash_attention` calls:
    the measured 512 target degraded by gcd, or ``None`` — meaning "run
    dense attention" — when only sub-128 (sub-MXU-tile) blocks divide a
    sequence length (e.g. T=96 → 32, T=4104 → 8).  Callers that pass
    blocks explicitly keep the strict :func:`_blocks_for` contract
    (degrade to its floor, then raise)."""
    Tk = Tq if Tk is None else Tk
    bq = math.gcd(Tq, target)
    bk = math.gcd(Tk, target)
    if min(bq, bk) < _DENSE_FLOOR:
        return None
    return bq, bk


def _fa_kernel(*refs, scale, block_q, block_k, n_kb, causal, precision,
               parts=False, with_lse=False):
    """One (bh, q-block, k-block) grid step.

    The k dimension is the MINOR grid axis: Pallas runs it sequentially per
    q block and auto-pipelines the K/V block DMA behind compute (double
    buffering — the kernel never holds more than one K/V block in VMEM, so
    sequence length is unbounded).  Running max / denominator / output
    accumulate in VMEM scratch across the k steps; the final k step
    normalizes into the output block.

    Contractions run in the operands' dtype (bf16 inputs → single-pass
    bf16 MXU) with f32 accumulators; the probability block is cast to the
    V dtype for the second contraction — the standard flash trade.  The
    scale folds into the f32 score block after the first contraction, so
    no operand needs an in-kernel cast.

    ``parts=True`` is the ring-attention inner form: two extra SMEM scalars
    (global position offsets of this chip's Q and the in-flight K/V block,
    runtime values — the ring rotates them) shift the causal mask, and the
    kernel emits the UNNORMALIZED accumulator plus running max/denominator
    so ring steps merge stable-softmax state across chips."""
    if parts:
        q_off_ref, k_off_ref = refs[0], refs[1]
        q_ref, k_ref, v_ref = refs[2:5]
        o_ref, m_ref, l_ref = refs[5:8]
        m_scr, l_scr, acc_scr = refs[8:]
        q_pos0 = q_off_ref[0, 0]
        k_pos0 = k_off_ref[0, 0]
    elif with_lse:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs[:5]
        m_scr, l_scr, acc_scr = refs[5:]
        q_pos0 = k_pos0 = 0
    else:
        q_ref, k_ref, v_ref, o_ref = refs[:4]
        m_scr, l_scr, acc_scr = refs[4:]
        q_pos0 = k_pos0 = 0
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: the last query of block qi attends keys at global positions
    # <= its own; blocks wholly beyond that are skipped (no FLOPs, and in
    # the non-parts kernels the clamped index map re-targets the same
    # live block so no DMA moves either)
    live = (
        (k_pos0 + kj * block_k <= q_pos0 + qi * block_q + block_q - 1)
        if causal
        else True
    )

    @pl.when(live)
    def _step():
        q = q_ref[0]                                  # (bq, D), native dtype
        kb = k_ref[0]                                 # (bk, D)
        vb = v_ref[0]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ) * scale                                     # (bq, bk) f32
        if causal:
            q_pos = q_pos0 + qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = k_pos0 + kj * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(k_pos <= q_pos, s, _NEG)
        one_shot = n_kb == 1 and not parts
        if one_shot:
            # single k block: the running-max state is degenerate
            # (m_prev == _NEG, alpha == 1, acc == 0), so the softmax
            # one-shots — no scratch read, no rescale multiply, no
            # accumulate add.  Value-identical to the running form:
            # max(_NEG, s.max) == s.max and 0·1 + dot == dot.  The
            # tuner selects this variant whenever it engages
            # block_k == Tk.
            m_new = s.max(axis=-1)
        else:
            m_prev = m_scr[:, 0]
            m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        # "highest": keep p f32 (upcast v); "default": p joins the
        # operands' (bf16) MXU pass — the standard flash trade
        if precision == lax.Precision.HIGHEST:
            p2, vb2 = p, vb.astype(jnp.float32)
        else:
            p2, vb2 = p.astype(vb.dtype), vb
        dot = jax.lax.dot_general(
            p2, vb2, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        if one_shot:
            acc_scr[...] = dot
            l_scr[:, 0] = p.sum(axis=-1)
        else:
            alpha = jnp.exp(m_prev - m_new)
            acc_scr[...] = acc_scr[...] * alpha[:, None] + dot
            l_scr[:, 0] = l_scr[:, 0] * alpha + p.sum(axis=-1)
        m_scr[:, 0] = m_new

    @pl.when(kj == n_kb - 1)
    def _finish():
        if parts:
            o_ref[0] = acc_scr[...]
            m_ref[0] = m_scr[...]
            l_ref[0] = l_scr[...]
        else:
            o_ref[0] = (
                acc_scr[...] / jnp.maximum(l_scr[:, 0], 1e-30)[:, None]
            ).astype(o_ref.dtype)
            if with_lse:
                lse_ref[0] = m_scr[...] + jnp.log(
                    jnp.maximum(l_scr[...], 1e-30)
                )



def _resolve(interpret, precision):
    """One place for the interpret default (Pallas interpreter off-TPU)
    and the precision-string -> lax.Precision mapping — used by the
    primal, parts, fwd, and bwd paths so they can never diverge."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    precision = _precision_str(precision)  # validate enum/string spellings
    prec = (
        lax.Precision.HIGHEST if precision == "highest"
        else lax.Precision.DEFAULT
    )
    return interpret, prec


def _precision_str(precision) -> str:
    """Normalize a precision spelling to the module's canonical strings —
    ``lax.Precision.DEFAULT`` and ``"default"`` must select the SAME
    path (``_stream_cast`` keys on the string; an enum slipping through
    would silently stream f32 at bf16-trade accuracy).  Anything outside
    the two documented modes is rejected loudly: quietly mapping e.g.
    ``Precision.HIGH`` or a typo onto the bf16 trade would hand a caller
    ~1e-2 error where they asked for accuracy."""
    if precision in ("highest", "default"):
        return precision
    if precision == lax.Precision.HIGHEST:
        return "highest"
    if precision == lax.Precision.DEFAULT:
        return "default"
    raise ValueError(
        f"flash_attention precision must be 'highest' or 'default' "
        f"(or the matching lax.Precision), got {precision!r}"
    )


def _stream_cast(precision, *arrays):
    """The r6 bandwidth lever: ``precision="default"`` on f32 operands
    casts them to bf16 ONCE at the XLA level so the kernels stream half
    the HBM bytes (softmax statistics, accumulators, and emitted
    gradients stay f32).  Sub-f32 inputs and the "highest" mode pass
    through untouched."""
    if precision == "default":
        return tuple(
            a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a
            for a in arrays
        )
    return arrays


def _mosaic_params(interpret, pltpu):
    """Megacore partitioning hint: the (bh, major) grid axes are
    embarrassingly parallel, only the minor streaming axis is a
    sequential reduction.  Without the hint Mosaic serializes the whole
    grid on one core (half the chip idle on v5e)."""
    if interpret:
        return {}
    CP = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None)
    if CP is None:  # pragma: no cover - very old pallas
        return {}
    return {"compiler_params": CP(
        dimension_semantics=("parallel", "parallel", "arbitrary"))}


def _stream_idx(bq: int, bk: int, causal: bool, minor: str):
    """BlockSpec index map for the MINOR-axis streamed operand, with the
    causal DMA-elision clamp: masked grid steps re-target the nearest
    LIVE block, and Pallas issues no DMA when the block index repeats —
    so the causal skip saves the bytes, not just the FLOPs.  The clamp
    bounds mirror the kernels' ``live`` mask exactly (live iff
    ``kj*bk <= qi*bq + bq - 1``): ``minor="k"`` (grid (b, qi, kj)
    streaming k/v) clamps to the LAST live k block, ``minor="q"``
    (grid (b, kj, qi) streaming q/dO/lse/delta) clamps to the FIRST
    live q block.  One definition so the three call sites can never
    drift from each other or the mask."""
    if minor == "k":
        if not causal:
            return lambda b, i, j: (b, j, 0)
        return lambda b, i, j: (b, jnp.minimum(j, (i * bq + bq - 1) // bk), 0)
    assert minor == "q"
    if not causal:
        return lambda b, j, i: (b, i, 0)
    return lambda b, j, i: (b, jnp.maximum(i, (j * bk) // bq), 0)


def _blocks_for(Tq: int, Tk: int, block_q: int, block_k: int):
    """Effective (bq, bk) for EXPLICITLY-requested blocks: the largest
    divisors of the sequence lengths not exceeding the requested blocks
    (gcd) — so a 32-block request on T=48 degrades gracefully to 16-wide
    tiles.

    The degradation floor is a quarter of the smaller requested block,
    capped at 32 rows/columns: explicitly-requested tiny blocks (e.g.
    16/16 in tests) are honored, and genuinely awkward lengths (T=4104
    with a 512 request → 8-wide tiles, ~100x slower than the dense
    einsum this replaces) raise loudly rather than run silently
    degenerate.  DEFAULT-argument calls never reach this error:
    :func:`flash_attention` routes them through :func:`default_blocks`,
    which falls back to dense attention instead (r6, ADVICE r4 /
    VERDICT #7)."""
    bq = math.gcd(Tq, block_q)
    bk = math.gcd(Tk, block_k)
    floor = min(32, max(8, min(block_q, block_k) // 4))
    if bq < floor or bk < floor:
        raise ValueError(
            f"sequence lengths (Tq={Tq}, Tk={Tk}) admit only degenerate "
            f"tiles ({bq}, {bk}) for requested blocks ({block_q}, "
            f"{block_k}); use auto_block()/default args (dense fallback) "
            f"or pad the sequence"
        )
    return bq, bk


def _vma_sds(*operands):
    """ShapeDtypeStruct factory carrying the union of the operands'
    varying-axes sets — under shard_map every pallas_call output must
    declare how it varies over mesh axes (a replicated q attending
    sharded k/v still produces per-shard-varying output)."""
    try:
        vma = frozenset().union(*(jax.typeof(o).vma for o in operands))
        return functools.partial(jax.ShapeDtypeStruct, vma=vma)
    except (TypeError, AttributeError):
        return jax.ShapeDtypeStruct


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret", "precision",
                     "with_lse"),
)
def _flash_forward(q, k, v, causal, block_q, block_k, interpret, precision,
                   with_lse=False):
    """Forward pass; ``with_lse=True`` also emits the per-row logsumexp
    (m + log l) as a compact [B*H, Tq, 1] f32 column — the O(T) residual
    the tiled backward reconstructs probabilities from — plus the
    STREAM-CAST q/k/v (bf16 under "default"), so the vjp saves those as
    residuals: the backward re-casts nothing and the fwd→bwd interval
    holds half the bytes."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    bq, bk = _blocks_for(Tq, Tk, block_q, block_k)
    if causal and Tq != Tk:
        raise ValueError("causal flash attention requires Tq == Tk")
    precision = _precision_str(precision)
    interpret, prec = _resolve(interpret, precision)
    out_dtype = q.dtype
    q, k, v = _stream_cast(precision, q, k, v)
    # [B, T, H, D] -> [B*H, T, D]: one grid row per (batch, head)
    q3 = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    k3 = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    v3 = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    n_kb = Tk // bk
    kernel = functools.partial(
        _fa_kernel, scale=scale, block_q=bq, block_k=bk, n_kb=n_kb,
        causal=causal, precision=prec, with_lse=with_lse,
    )
    from jax.experimental.pallas import tpu as pltpu

    sds = _vma_sds(q3, k3, v3)
    kv_idx = _stream_idx(bq, bk, causal, "k")
    out_specs = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))
    out_shape = sds((B * H, Tq, D), out_dtype)
    if with_lse:
        out_specs = [out_specs,
                     pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0))]
        out_shape = [out_shape, sds((B * H, Tq, 1), jnp.float32)]
    res = pl.pallas_call(
        kernel,
        grid=(B * H, Tq // bq, n_kb),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), kv_idx),
            pl.BlockSpec((1, bk, D), kv_idx),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),    # running max
            pltpu.VMEM((bq, 1), jnp.float32),    # running denominator
            pltpu.VMEM((bq, D), jnp.float32),    # output accumulator
        ],
        interpret=interpret,
        **_mosaic_params(interpret, pltpu),
    )(q3, k3, v3)
    if with_lse:
        out, lse = res
        return (
            out.reshape(B, H, Tq, D).transpose(0, 2, 1, 3),
            lse,  # [B*H, Tq, 1] f32 — compact, fed to the backward as-is
            (q, k, v),  # stream-cast operands — the vjp's residuals
        )
    return res.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret", "precision"),
)
def flash_attention_parts(
    q, k, v, q_pos0=0, k_pos0=0, causal=False, block_q=128, block_k=128,
    interpret=None, precision="highest",
):
    """Ring-attention inner: UNNORMALIZED flash accumulation of q against
    one K/V block with runtime global position offsets for the causal
    mask.  Returns ``(acc, m, l)`` — acc f32 [B, Tq, H, D], running max
    and denominator f32 [B, Tq, H] — which ring steps merge with the
    standard stable-softmax combine (parallel/attention.py).  Forward
    only (no custom_vjp): training uses the einsum ring path."""
    from jax.experimental.pallas import tpu as pltpu

    interpret, prec = _resolve(interpret, precision)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    if Tq % bq or Tk % bk:
        raise ValueError(
            f"sequence lengths (Tq={Tq}, Tk={Tk}) must be multiples of the "
            f"blocks (bq={bq}, bk={bk})"
        )
    q3 = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    k3 = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    v3 = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    n_kb = Tk // bk
    kernel = functools.partial(
        _fa_kernel, scale=scale, block_q=bq, block_k=bk, n_kb=n_kb,
        causal=causal, precision=prec, parts=True,
    )
    scalar_spec = pl.BlockSpec((1, 1), lambda b, i, j: (0, 0),
                               memory_space=pltpu.SMEM)
    tile_q = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))
    tile_k = pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0))
    tile_ml = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0))
    try:
        vma = frozenset(
            jax.typeof(q3).vma | jax.typeof(k3).vma | jax.typeof(v3).vma
        )
        sds = functools.partial(jax.ShapeDtypeStruct, vma=vma)
    except (TypeError, AttributeError):
        sds = jax.ShapeDtypeStruct
    acc, m, l = pl.pallas_call(
        kernel,
        grid=(B * H, Tq // bq, n_kb),
        in_specs=[scalar_spec, scalar_spec, tile_q, tile_k, tile_k],
        out_specs=[tile_q, tile_ml, tile_ml],
        out_shape=[
            sds((B * H, Tq, D), jnp.float32),
            sds((B * H, Tq, 1), jnp.float32),
            sds((B * H, Tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
        **_mosaic_params(interpret, pltpu),
    )(
        jnp.asarray(q_pos0, jnp.int32).reshape(1, 1),
        jnp.asarray(k_pos0, jnp.int32).reshape(1, 1),
        q3, k3, v3,
    )
    acc = acc.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)
    m = m[..., 0].reshape(B, H, Tq).transpose(0, 2, 1)
    l = l[..., 0].reshape(B, H, Tq).transpose(0, 2, 1)
    return acc, m, l


def _fa_bwd_dq_kernel(*refs, scale, block_q, block_k, n_kb, causal, precision,
                      parts=False):
    """Backward dq: grid (bh, q-block, k-block minor).  Recomputes each
    score block from q/k and the saved logsumexp, accumulates
    dq += ds · K in VMEM scratch across the k steps.  Contractions run in
    the operands' dtype (f32 accumulate); ds absorbs the softmax scale so
    the accumulated dq needs no finish-time rescale.

    ``parts=True`` prepends two SMEM scalars (global position offsets of
    this chip's Q and the in-flight K/V block) shifting the causal mask —
    the ring backward's analogue of the parts forward kernel."""
    if parts:
        q_off_ref, k_off_ref = refs[0], refs[1]
        q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dq_ref = refs[2:9]
        (dq_scr,) = refs[9:]
        q_pos0 = q_off_ref[0, 0]
        k_pos0 = k_off_ref[0, 0]
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dq_ref = refs[:7]
        (dq_scr,) = refs[7:]
        q_pos0 = k_pos0 = 0
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    live = (
        (k_pos0 + kj * block_k <= q_pos0 + qi * block_q + block_q - 1)
        if causal
        else True
    )

    @pl.when(live)
    def _step():
        q = q_ref[0]                                   # (bq, D)
        kb = k_ref[0]                                  # (bk, D)
        vb = v_ref[0]
        do = do_ref[0]                                 # (bq, D)
        lse = lse_ref[0][:, 0]                         # (bq,)
        dlt = dlt_ref[0][:, 0]                         # (bq,)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ) * scale
        if causal:
            q_pos = q_pos0 + qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_pos0 + kj * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG)
        p = jnp.exp(s - lse[:, None])                  # (bq, bk)
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        ds = p * (dp - dlt[:, None]) * scale
        if precision == lax.Precision.HIGHEST:
            ds2, kb2 = ds, kb.astype(jnp.float32)
        else:
            ds2, kb2 = ds.astype(kb.dtype), kb
        dot = jax.lax.dot_general(
            ds2, kb2, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        if n_kb == 1 and not parts:
            # single (always-live) k step: direct store, no zeros
            # read-modify-write — value-identical to 0 + dot
            dq_scr[...] = dot
        else:
            dq_scr[...] = dq_scr[...] + dot

    @pl.when(kj == n_kb - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)   # scale folded in ds


def _fa_bwd_dkv_kernel(*refs, scale, block_q, block_k, n_qb, causal,
                       precision, parts=False):
    """Backward dk/dv: grid (bh, k-block, q-block minor).  Accumulates
    dv += pᵀ · dO and dk += dsᵀ · q in VMEM scratch across the q steps
    (operand-dtype contractions, f32 accumulate; ds absorbs the scale).

    ``parts=True``: SMEM global position offsets, as in the dq kernel."""
    if parts:
        q_off_ref, k_off_ref = refs[0], refs[1]
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dk_ref,
         dv_ref) = refs[2:10]
        dk_scr, dv_scr = refs[10:]
        q_pos0 = q_off_ref[0, 0]
        k_pos0 = k_off_ref[0, 0]
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dk_ref,
         dv_ref) = refs[:8]
        dk_scr, dv_scr = refs[8:]
        q_pos0 = k_pos0 = 0
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    live = (
        (k_pos0 + kj * block_k <= q_pos0 + qi * block_q + block_q - 1)
        if causal
        else True
    )

    @pl.when(live)
    def _step():
        q = q_ref[0]
        kb = k_ref[0]
        vb = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]
        dlt = dlt_ref[0][:, 0]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ) * scale
        if causal:
            q_pos = q_pos0 + qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_pos0 + kj * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG)
        p = jnp.exp(s - lse[:, None])                  # (bq, bk)
        if precision == lax.Precision.HIGHEST:
            p2, do2 = p, do.astype(jnp.float32)
        else:
            p2, do2 = p.astype(do.dtype), do
        # single q step AND every step live (non-causal non-parts only:
        # a causal single-q grid can dead-step high k blocks, which
        # must then finish from the _init zeros): direct store instead
        # of the zeros read-modify-write — value-identical to 0 + dot
        direct = n_qb == 1 and not parts and not causal
        dv_dot = jax.lax.dot_general(
            p2, do2, (((0,), (0,)), ((), ())),         # pᵀ·do
            preferred_element_type=jnp.float32, precision=precision,
        )
        dv_scr[...] = dv_dot if direct else dv_scr[...] + dv_dot
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        ds = p * (dp - dlt[:, None]) * scale
        if precision == lax.Precision.HIGHEST:
            ds2, q2 = ds, q.astype(jnp.float32)
        else:
            ds2, q2 = ds.astype(q.dtype), q
        dk_dot = jax.lax.dot_general(
            ds2, q2, (((0,), (0,)), ((), ())),         # dsᵀ · q -> (bk, D)
            preferred_element_type=jnp.float32, precision=precision,
        )
        dk_scr[...] = dk_dot if direct else dk_scr[...] + dk_dot

    @pl.when(qi == n_qb - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)   # scale folded in ds
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret", "precision",
                     "grad_dtypes"),
)
def _flash_backward(q, k, v, out, lse3, do, causal, block_q, block_k,
                    interpret, precision, grad_dtypes=None):
    """Tiled flash backward: dq in one pallas_call (k minor), dk/dv in a
    second (q minor).  ``lse3`` arrives AND is consumed in compact
    [B*H, Tq, 1] layout (the residual held across the fwd→bwd interval
    and the bytes the kernels stream are both O(T), not O(128·T) — r4
    advisor note + r6 MFU fix); delta = rowsum(dO ∘ O) is a cheap XLA
    reduction emitted in the same compact column.  Under
    ``precision="default"`` the streamed operands (q/k/v/dO) are bf16;
    gradients are emitted in ``grad_dtypes`` — the PRIMAL (pre-cast)
    dtypes per operand, defaulting to the cotangent dtype — so each
    cotangent matches its primal even for mixed-dtype q/k/v."""
    from jax.experimental.pallas import tpu as pltpu

    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    bq, bk = _blocks_for(Tq, Tk, block_q, block_k)
    scale = 1.0 / math.sqrt(D)
    precision = _precision_str(precision)
    interpret, prec = _resolve(interpret, precision)
    # delta_i = sum_d dO_id * O_id in f32, BEFORE the bandwidth cast
    delta = jnp.einsum(
        "bqhd,bqhd->bhq", do.astype(jnp.float32), out.astype(jnp.float32)
    ).reshape(B * H, Tq)
    dlt3 = delta[..., None]                       # [B*H, Tq, 1]
    dq_dtype, dk_dtype, dv_dtype = grad_dtypes or (do.dtype,) * 3
    q, k, v, do = _stream_cast(precision, q, k, v, do)
    q3 = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    k3 = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    v3 = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    do3 = do.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    sds = _vma_sds(q3, k3, v3, do3)
    n_qb, n_kb = Tq // bq, Tk // bk
    mosaic = _mosaic_params(interpret, pltpu)
    tile_q = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))
    tile_ml = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0))
    tile_k_minor = pl.BlockSpec((1, bk, D), _stream_idx(bq, bk, causal, "k"))
    dq = pl.pallas_call(
        functools.partial(
            _fa_bwd_dq_kernel, scale=scale, block_q=bq, block_k=bk,
            n_kb=n_kb, causal=causal, precision=prec,
        ),
        grid=(B * H, n_qb, n_kb),
        in_specs=[tile_q, tile_k_minor, tile_k_minor, tile_q, tile_ml,
                  tile_ml],
        out_specs=tile_q,
        out_shape=sds((B * H, Tq, D), dq_dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
        **mosaic,
    )(q3, k3, v3, do3, lse3, dlt3)
    # dk/dv: k-block is the 2nd grid axis, q streams as the minor axis
    q_idx = _stream_idx(bq, bk, causal, "q")
    tile_q_minor = pl.BlockSpec((1, bq, D), q_idx)
    tile_ml_minor = pl.BlockSpec((1, bq, 1), q_idx)
    tile_k = pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _fa_bwd_dkv_kernel, scale=scale, block_q=bq, block_k=bk,
            n_qb=n_qb, causal=causal, precision=prec,
        ),
        grid=(B * H, n_kb, n_qb),
        in_specs=[tile_q_minor, tile_k, tile_k, tile_q_minor, tile_ml_minor,
                  tile_ml_minor],
        out_specs=[tile_k, tile_k],
        out_shape=[
            sds((B * H, Tk, D), dk_dtype),
            sds((B * H, Tk, D), dv_dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
        **mosaic,
    )(q3, k3, v3, do3, lse3, dlt3)
    reshape = lambda a, T: a.reshape(B, H, T, D).transpose(0, 2, 1, 3)
    return reshape(dq, Tq), reshape(dk, Tk), reshape(dv, Tk)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret", "precision"),
)
def flash_attention_bwd_parts(
    q, k, v, do, lse, delta, q_pos0=0, k_pos0=0, causal=False,
    block_q=128, block_k=128, interpret=None, precision="highest",
):
    """Ring-attention inner BACKWARD: gradients of one chip's queries
    against one in-flight K/V block, with runtime global position offsets
    for the causal mask — the bwd analogue of
    :func:`flash_attention_parts` (same tiled kernels as the single-chip
    backward, SMEM offsets added).

    ``lse`` and ``delta`` are per-row [B, Tq, H] f32: the ring-global
    logsumexp (m + log l merged across ALL ring steps) and
    rowsum(dO ∘ O); the kernels consume them as compact [B*H, Tq, 1]
    columns.  Returns ``(dq_partial, dk_block, dv_block)`` in **f32**
    regardless of input dtype — the caller accumulates partials across
    ring steps, and rounding each partial to a low-precision input dtype
    would add n independent roundings the single-chip backward doesn't
    have (it rounds once from f32 scratch).  The caller sums dq over ring
    steps and rotates dk/dv accumulators with their blocks
    (parallel/attention.py:_raf_bwd)."""
    from jax.experimental.pallas import tpu as pltpu

    interpret, prec = _resolve(interpret, precision)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    if Tq % bq or Tk % bk:
        raise ValueError(
            f"sequence lengths (Tq={Tq}, Tk={Tk}) must be multiples of the "
            f"blocks (bq={bq}, bk={bk})"
        )
    q3 = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    k3 = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    v3 = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    do3 = do.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    to_col = lambda a: a.astype(jnp.float32).transpose(0, 2, 1).reshape(
        B * H, Tq, 1)
    lse3 = to_col(lse)
    dlt3 = to_col(delta)
    offs = (
        jnp.asarray(q_pos0, jnp.int32).reshape(1, 1),
        jnp.asarray(k_pos0, jnp.int32).reshape(1, 1),
    )
    sds = _vma_sds(q3, k3, v3, do3)
    n_qb, n_kb = Tq // bq, Tk // bk
    mosaic = _mosaic_params(interpret, pltpu)
    scalar_spec = pl.BlockSpec((1, 1), lambda b, i, j: (0, 0),
                               memory_space=pltpu.SMEM)
    tile_q = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))
    tile_ml = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0))
    tile_k_minor = pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0))
    dq = pl.pallas_call(
        functools.partial(
            _fa_bwd_dq_kernel, scale=scale, block_q=bq, block_k=bk,
            n_kb=n_kb, causal=causal, precision=prec, parts=True,
        ),
        grid=(B * H, n_qb, n_kb),
        in_specs=[scalar_spec, scalar_spec, tile_q, tile_k_minor,
                  tile_k_minor, tile_q, tile_ml, tile_ml],
        out_specs=tile_q,
        out_shape=sds((B * H, Tq, D), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
        **mosaic,
    )(*offs, q3, k3, v3, do3, lse3, dlt3)
    tile_q_minor = pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0))
    tile_ml_minor = pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0))
    tile_k = pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0))
    scalar_spec_m = pl.BlockSpec((1, 1), lambda b, j, i: (0, 0),
                                 memory_space=pltpu.SMEM)
    dk, dv = pl.pallas_call(
        functools.partial(
            _fa_bwd_dkv_kernel, scale=scale, block_q=bq, block_k=bk,
            n_qb=n_qb, causal=causal, precision=prec, parts=True,
        ),
        grid=(B * H, n_kb, n_qb),
        in_specs=[scalar_spec_m, scalar_spec_m, tile_q_minor, tile_k,
                  tile_k, tile_q_minor, tile_ml_minor, tile_ml_minor],
        out_specs=[tile_k, tile_k],
        out_shape=[
            sds((B * H, Tk, D), jnp.float32),
            sds((B * H, Tk, D), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
        **mosaic,
    )(*offs, q3, k3, v3, do3, lse3, dlt3)
    reshape = lambda a, T: a.reshape(B, H, T, D).transpose(0, 2, 1, 3)
    return reshape(dq, Tq), reshape(dk, Tk), reshape(dv, Tk)


def _dense_attention(q, k, v, causal, precision):
    """Dense XLA attention — the documented fallback for
    default-argument calls whose sequence lengths admit only sub-MXU
    tiles (:func:`default_blocks` → None).  Delegates to the ONE
    reference implementation (lazy import — parallel.attention imports
    this module lazily too, so there is no cycle), passing the caller's
    precision trade through.  Differentiable via plain autodiff."""
    from ..parallel.attention import attention_reference

    _, prec = _resolve(False, precision)
    return attention_reference(q, k, v, causal=causal, precision=prec)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_tiled(q, k, v, causal, block_q, block_k, interpret,
                           precision):
    interpret, _ = _resolve(interpret, precision)
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret,
                          precision)


def _fa_fwd(q, k, v, causal, block_q, block_k, interpret, precision):
    interpret, _ = _resolve(interpret, precision)
    out, lse3, (qs, ks, vs) = _flash_forward(
        q, k, v, causal, block_q, block_k, interpret, precision,
        with_lse=True
    )
    # the kernel emits the logsumexp as a compact [B*H, Tq, 1] f32 column
    # (true O(T)) — the residual saved across the whole forward→backward
    # interval AND the operand layout the backward kernels stream.  The
    # SAVED q/k/v are the stream-cast versions (bf16 under "default"):
    # half the residual bytes, and the backward re-casts nothing.  Two
    # zero-size carriers preserve k/v's PRIMAL dtypes so each cotangent
    # can match its primal even for mixed-dtype operands (q's rides on
    # the cotangent itself: out keeps q's dtype).
    return out, (qs, ks, vs, out, lse3,
                 jnp.zeros((0,), k.dtype), jnp.zeros((0,), v.dtype))


def _fa_bwd(causal, block_q, block_k, interpret, precision, res, do):
    q, k, v, out, lse3, zk, zv = res
    # honor the caller's precision trade in the backward too — it is the
    # dominant training cost, so "default" (bf16 streams + bf16 MXU
    # passes) must actually apply here, not just in the forward kernel
    interpret, _ = _resolve(interpret, precision)
    return _flash_backward(
        q, k, v, out, lse3, do, causal, block_q, block_k, interpret,
        precision, grad_dtypes=(do.dtype, zk.dtype, zv.dtype)
    )


_flash_attention_tiled.defvjp(_fa_fwd, _fa_bwd)


def flash_attention(q, k, v, causal=False, block_q=None, block_k=None,
                    interpret=None, precision="highest"):
    """Tiled flash attention on TPU (Pallas), fwd AND bwd kernels.

    Shapes match :func:`parallel.attention.attention_reference`:
    q [B, Tq, H, D], k/v [B, Tk, H, D] → [B, Tq, H, D].
    ``interpret=None`` auto-selects the Pallas interpreter off-TPU.
    ``precision``: "highest" (true-f32 MXU passes, matches the dense
    reference bit-for-bit-ish) or "default" (bf16 end-to-end: f32 inputs
    are cast to bf16 once at the XLA level, the kernels stream and
    contract bf16 with f32 accumulators — the usual flash-attention
    trade, ~1e-2 relative on f32 inputs, ~2x the MFU).

    Default-argument blocks come from the MEASURED block autotuner
    (``core/blocktuner.TUNER``): warm starts from the kernel-profile
    store, measured walls take over as they arrive, and the static
    :func:`default_blocks` pair — the r5-sweep 512/512 sweet spot
    degraded by gcd — remains the cold-start fallback.  The tuner and
    the static policy agree on WHEN tiling is legal (both gate on a
    >= 128 divisor), so the DENSE-attention fallback for awkward
    sequence lengths (e.g. T=96, T=4104 — sub-MXU tiles are slower than
    the dense einsum they replace; ADVICE r4 / VERDICT #7) is unchanged.
    Explicitly-passed blocks BYPASS tuning entirely and keep the strict
    contract: degrade by gcd to the :func:`_blocks_for` floor, then
    raise.  Training memory is O(T) residuals (out + per-row logsumexp,
    both compact) + O(block²) tiles — no [T, T] materialization in
    either direction."""
    precision = _precision_str(precision)
    if block_q is None and block_k is None:
        blocks = _tuned_blocks(q.shape, k.shape, precision)
        if blocks is None:
            return _dense_attention(q, k, v, causal, precision)
        block_q, block_k = blocks
    elif block_q is None or block_k is None:
        block_q = block_q or block_k
        block_k = block_k or block_q
    return _flash_attention_tiled(
        q, k, v, causal, block_q, block_k, interpret, precision
    )


def _tuned_blocks(q_shape, k_shape,
                  precision: str) -> tuple[int, int] | None:
    """Default-argument block choice: ask the measured autotuner, with
    the static :func:`default_blocks` pair as its cold-start fallback
    (and as the answer outright if the tuner is unavailable — the flash
    path must never fail because telemetry plumbing did).  None means
    "no legal tile, run dense" — the tuner's empty-grid condition and
    ``default_blocks``' None are the same predicate by construction."""
    Tq, Tk = int(q_shape[1]), int(k_shape[1])
    fallback = default_blocks(Tq, Tk)
    try:
        from ..core.blocktuner import TUNER

        sig = ("flash_attention.highest" if precision == "highest"
               else "flash_attention.bf16_default")
        choice = TUNER.choose(sig, Tq, Tk, shape=tuple(q_shape),
                              fallback=fallback)
    except Exception:  # noqa: BLE001 - tuner trouble must not sink math
        return fallback
    return choice if choice is not None else fallback


def fused_qkv(x, wq, wk, wv, precision=None):
    """The three attention input projections as ONE concatenated GEMM:
    ``x @ [wq | wk | wv]`` split back into (q, k, v).

    One MXU pass over x instead of three (one x read from HBM, one
    weight stream, 3x the N dimension per launch — the kernel-level MFU
    lever for the projection stage), and BIT-IDENTICAL to the three
    separate matmuls: every output column is an independent dot product
    over the same contraction order, so concatenating columns changes
    which results land where, never what any result is.

    ``x`` is [..., E]; each ``w*`` is [E, F*] (the F's may differ, e.g.
    grouped-query K/V heads).  Returns views of one buffer — slice
    copies only materialize if a consumer forces them."""
    w = jnp.concatenate([wq, wk, wv], axis=-1)
    qkv = jnp.matmul(x, w, precision=precision)
    fq, fk = wq.shape[-1], wk.shape[-1]
    return (qkv[..., :fq], qkv[..., fq:fq + fk], qkv[..., fq + fk:])


def fused_qkv_attention(x, wq, wk, wv, num_heads, causal=False,
                        interpret=None, precision="highest"):
    """Fused projection + tuned flash attention: ``x`` [B, T, E] through
    :func:`fused_qkv` (one GEMM), heads split to [B, T, H, D], then the
    DEFAULT-argument :func:`flash_attention` path — i.e. the block
    autotuner picks the tile geometry.  The fused-GEMM and one-shot-
    softmax variants this module grew are both on this path: the first
    unconditionally, the second whenever the tuner engages
    ``block_k == Tk``."""
    B, T, _ = x.shape
    q, k, v = fused_qkv(x, wq, wk, wv)
    q = q.reshape(B, T, num_heads, -1)
    k = k.reshape(B, T, num_heads, -1)
    v = v.reshape(B, T, num_heads, -1)
    return flash_attention(q, k, v, causal=causal, interpret=interpret,
                           precision=precision)
