"""Benchmark/validation workloads: mandelbrot, n-body, streaming vector add.

The reference ships these as its demo/benchmark set — ``Tester.nBody``
(Tester.cs:7682-7799, also the device-ranking micro-benchmark used by
``devicesWithHighestDirectNbodyPerformance``, ClObjectApi.cs:1222-1244),
``stream_C_equals_A_plus_B_1M_elements`` (Tester.cs:7806-7843), and a
mandelbrot demo distributed only as a Windows binary
(mandelbrot_bench_v4.rar).  Here they are first-class workloads written in
the kernel language, with host reference implementations for self-checking
(the reference's ±0.01f nBody tolerance pattern) and timing helpers that
feed BASELINE.md's metrics: Mpixels/sec, load-balance convergence
iterations.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from .arrays.clarray import ClArray
from .core.cruncher import NumberCruncher
from .hardware import Devices

__all__ = [
    "MANDELBROT_SRC",
    "NBODY_SRC",
    "STREAM_SRC",
    "mandelbrot_host",
    "nbody_host_step",
    "MandelbrotResult",
    "run_mandelbrot",
    "run_nbody",
    "run_stream",
    "convergence_iterations",
    "WAVE_SRC",
    "lowering_faceoff",
    "marker_overhead",
    "dispatch_floor_sweep",
    "duplex_ceiling",
]


# One pixel per work item; escape-iteration count written as float so a
# single dtype covers TPU (no int32 penalty) and matches the reference demo's
# colorable output.
MANDELBROT_SRC = """
__kernel void mandelbrot(__global float* out,
                         float x0, float y0, float dx, float dy,
                         int width, int maxIter) {
    int i = get_global_id(0);
    float cx = x0 + dx * (float)(i % width);
    float cy = y0 + dy * (float)(i / width);
    float zx = 0.0f;
    float zy = 0.0f;
    int it = 0;
    while (zx*zx + zy*zy < 4.0f && it < maxIter) {
        float t = zx*zx - zy*zy + cx;
        zy = 2.0f*zx*zy + cy;
        zx = t;
        it++;
    }
    out[i] = (float)it;
}
"""

# Direct O(n^2) gravity step (reference: Tester.nBody kernel shape,
# Tester.cs:7682-7799).  Positions are read whole on every chip; velocities
# are updated only for the chip's own range slice.
NBODY_SRC = """
__kernel void nBody(__global float* x, __global float* y, __global float* z,
                    __global float* vx, __global float* vy, __global float* vz,
                    int n, float dt) {
    int i = get_global_id(0);
    float ax = 0.0f;
    float ay = 0.0f;
    float az = 0.0f;
    float xi = x[i];
    float yi = y[i];
    float zi = z[i];
    for (int j = 0; j < n; j++) {
        float ddx = x[j] - xi;
        float ddy = y[j] - yi;
        float ddz = z[j] - zi;
        float r2 = ddx*ddx + ddy*ddy + ddz*ddz + 0.0001f;
        float inv = 1.0f / (r2 * sqrt(r2));
        ax += ddx * inv;
        ay += ddy * inv;
        az += ddz * inv;
    }
    vx[i] += ax * dt;
    vy[i] += ay * dt;
    vz[i] += az * dt;
}
"""

# Streaming c = a + b (reference: Tester.cs:7806-7843, PIPELINE_DRIVER,
# zero-copy inputs).
STREAM_SRC = """
__kernel void streamAdd(__global float* a, __global float* b, __global float* c) {
    int i = get_global_id(0);
    c[i] = a[i] + b[i];
}
"""

# Compute-heavy stream: per-element iteration loop so blob compute time is
# commensurate with blob transfer time — the regime where the pipeline
# engines' read/compute/write overlap is actually measurable (on a slow
# host link, plain streamAdd is ~99% transfer and overlap is unobservable).
# The accumulation is EXACT in f32 (quarter-integer partial sums well below
# 2^24), so the result has a closed form the caller can assert against —
# a decaying recurrence has f32 fixed points a float64 model cannot predict.
STREAM_HEAVY_SRC = """
__kernel void streamHeavy(__global float* a, __global float* b, __global float* c,
                          int iters) {
    int i = get_global_id(0);
    float acc = a[i];
    for (int k = 0; k < iters; k++) {
        acc = acc + b[i] * 0.25f;
    }
    c[i] = acc;
}
"""


def mandelbrot_pallas_kernel(interpret: bool | None = None):
    """The mandelbrot workload as a raw-Pallas :class:`PythonKernel` —
    the hand-tiled hot path (ops/mandelbrot.py) plugged into the same
    compute()/balancer machinery as the C-subset kernel.

    ``interpret`` must be True when the kernel will run on CPU devices
    (the default-backend autodetect can't see which chips the scheduler
    dispatches to)."""
    import jax.lax

    from .kernel.registry import kernel
    from .ops.mandelbrot import mandelbrot_pallas

    @kernel(name="mandelbrot", static_values=True)
    def mandelbrot(gid, out, x0=0.0, y0=0.0, dx=0.0, dy=0.0, width=0, maxIter=0):
        chunk = gid.shape[0]
        piece = mandelbrot_pallas(
            chunk, x0, y0, dx, dy, width, maxIter, offset=gid[0],
            interpret=interpret,
        )
        if out.shape[0] == chunk:
            # whole-buffer launch (single chip, no blobbing): the result IS
            # the buffer — skip the read-modify-write update pass (~16% of
            # the headline iteration on v5e)
            return piece
        return jax.lax.dynamic_update_slice(out, piece, (gid[0],))

    return mandelbrot


def mandelbrot_host(
    width: int, height: int, x0: float, y0: float, dx: float, dy: float, max_iter: int
) -> np.ndarray:
    """Host reference implementation (vectorized numpy) for self-checking."""
    # all arithmetic in f32, matching the kernel's single-precision orbit
    px = np.arange(width * height, dtype=np.int64)
    cx = np.float32(x0) + np.float32(dx) * (px % width).astype(np.float32)
    cy = np.float32(y0) + np.float32(dy) * (px // width).astype(np.float32)
    zx = np.zeros_like(cx)
    zy = np.zeros_like(cy)
    it = np.zeros(width * height, dtype=np.int32)
    active = np.ones(width * height, dtype=bool)
    for _ in range(max_iter):
        zx2 = zx * zx
        zy2 = zy * zy
        active = active & (zx2 + zy2 < 4.0)
        if not active.any():
            break
        t = zx2 - zy2 + cx
        zy = np.where(active, 2.0 * zx * zy + cy, zy)
        zx = np.where(active, t, zx)
        it = it + active.astype(np.int32)
    return it.astype(np.float32)


def nbody_host_step(x, y, z, vx, vy, vz, dt: float):
    """Host reference for one nBody velocity update (numpy O(n^2))."""
    xs = x.astype(np.float64)
    ys = y.astype(np.float64)
    zs = z.astype(np.float64)
    ddx = xs[None, :] - xs[:, None]
    ddy = ys[None, :] - ys[:, None]
    ddz = zs[None, :] - zs[:, None]
    r2 = ddx * ddx + ddy * ddy + ddz * ddz + 0.0001
    inv = 1.0 / (r2 * np.sqrt(r2))
    vx2 = vx + (ddx * inv).sum(axis=1).astype(np.float32) * dt
    vy2 = vy + (ddy * inv).sum(axis=1).astype(np.float32) * dt
    vz2 = vz + (ddz * inv).sum(axis=1).astype(np.float32) * dt
    return vx2, vy2, vz2


@dataclass
class MandelbrotResult:
    mpixels_per_sec: float
    per_iter_ms: list[float] = field(default_factory=list)
    ranges_per_iter: list[list[int]] = field(default_factory=list)
    convergence_iters: int | None = None
    image: np.ndarray | None = None


def run_mandelbrot(
    devices: Devices | None = None,
    width: int = 2048,
    height: int = 2048,
    max_iter: int = 256,
    iters: int = 12,
    warmup: int = 2,
    pipeline: bool = False,
    pipeline_blobs: int = 8,
    local_range: int = 256,
    keep_image: bool = False,
    cruncher: NumberCruncher | None = None,
    use_pallas: bool = False,
    readback: str = "every",
    sync_every: int = 1,
) -> MandelbrotResult:
    """Timed, load-balanced mandelbrot over all selected chips.

    ``use_pallas`` swaps the kernel-language program for the hand-tiled
    Pallas kernel (same name, same compute path).  ``readback="final"``
    runs in enqueue mode — the image stays in HBM, iterations sync to a
    device barrier every ``sync_every`` steps (amortizing per-sync latency
    on tunneled backends), and one flush at the end writes the host array
    (the device-throughput view; "every" includes a full D2H per
    iteration).
    Returns Mpixels/sec over the timed iterations plus per-iteration wall
    times and the balancer's range trajectory (for the convergence metric
    in BASELINE.md).
    """
    from .hardware import all_devices

    own = cruncher is None
    devs = devices or all_devices()
    if use_pallas:
        source = mandelbrot_pallas_kernel(
            interpret=not all(d.is_tpu for d in devs)
        )
    else:
        source = MANDELBROT_SRC
    cr = cruncher or NumberCruncher(devs, source)
    n = width * height
    out = ClArray(n, np.float32, name="mandel_out", read=False, write=True)
    vals = (-2.0, -1.25, 2.5 / width, 2.5 / height, width, max_iter)
    per_iter: list[float] = []
    ranges: list[list[int]] = []
    if readback == "final":
        cr.enqueue_mode = True
    try:
        for k in range(warmup + iters):
            t0 = time.perf_counter()
            out.compute(
                cr, 7001, "mandelbrot", n, local_range,
                pipeline=pipeline, pipeline_blobs=pipeline_blobs, values=vals,
            )
            last = k == warmup + iters - 1
            if readback == "final" and ((k + 1) % sync_every == 0 or last):
                cr.barrier()
            dt_ms = (time.perf_counter() - t0) * 1000.0
            ranges.append(cr.ranges_of(7001))
            if k >= warmup:
                per_iter.append(dt_ms)
            elif k == warmup - 1 and readback == "final":
                # fence: warmup dispatches must retire OUTSIDE the timed
                # window or their device time deflates the metric
                cr.barrier()
        mpix = (n * len(per_iter)) / (sum(per_iter) / 1000.0) / 1e6
        step = local_range * (pipeline_blobs if pipeline else 1)
        if readback == "final":
            cr.enqueue_mode = False  # flush: one readback for the image
        return MandelbrotResult(
            mpixels_per_sec=mpix,
            per_iter_ms=per_iter,
            ranges_per_iter=ranges,
            convergence_iters=_converged_at(ranges, step),
            image=out.host().reshape(height, width).copy() if keep_image else None,
        )
    finally:
        # never leave a caller-supplied cruncher stuck in enqueue mode
        # (deferred readbacks would silently stop updating host arrays)
        if cr.enqueue_mode:
            try:
                cr.enqueue_mode = False
            except Exception:
                pass
        if own:
            cr.dispose()


def _converged_at(ranges: list[list[int]], step: int) -> int | None:
    """First iteration index after which every later re-balance moves no
    share by more than ``step`` (BASELINE.md convergence metric)."""
    for k in range(1, len(ranges)):
        if all(
            max(abs(a - b) for a, b in zip(ranges[j], ranges[j - 1])) <= step
            for j in range(k, len(ranges))
        ):
            return k
    return None


def run_nbody(
    devices: Devices | None = None,
    n: int = 8192,
    iters: int = 10,
    dt: float = 0.0001,
    local_range: int = 256,
    check: bool = True,
    tolerance: float = 0.01,
    use_jnp: bool = False,
) -> dict:
    """Load-balanced n-body velocity updates; self-checks the first step
    against the host O(n^2) reference within ``tolerance`` (the reference's
    ±0.01f pattern, Tester.cs:7682-7799).

    ``use_jnp`` swaps the C-subset kernel for the fused-XLA fast path
    (ops/nbody.py) — same name, same compute()/balancer machinery, the
    per-j gather loop replaced by one pairwise tile program."""
    from .hardware import all_devices

    rng = np.random.default_rng(42)
    pos = (rng.random((3, n), dtype=np.float32) - 0.5) * 2.0
    x = ClArray(pos[0].copy(), name="x", read_only=True)
    y = ClArray(pos[1].copy(), name="y", read_only=True)
    z = ClArray(pos[2].copy(), name="z", read_only=True)
    vel = [ClArray(n, np.float32, name=f"v{c}", partial_read=True) for c in "xyz"]
    expected = None
    if check:
        expected = nbody_host_step(
            pos[0], pos[1], pos[2],
            np.zeros(n, np.float32), np.zeros(n, np.float32), np.zeros(n, np.float32),
            dt,
        )
    if use_jnp:
        from .ops.nbody import nbody_jnp_kernel

        source = nbody_jnp_kernel()
    else:
        source = NBODY_SRC
    cr = NumberCruncher(devices or all_devices(), source)
    group = x.next_param(y, z, *vel)
    times: list[float] = []
    try:
        for k in range(iters):
            t0 = time.perf_counter()
            group.compute(cr, 7002, "nBody", n, local_range, values=(n, dt))
            times.append((time.perf_counter() - t0) * 1000.0)
            if k == 0 and check and expected is not None:
                for got, want, label in zip(vel, expected, "xyz"):
                    err = float(np.abs(got.host() - want).max())
                    if err > tolerance:
                        raise AssertionError(
                            f"nBody v{label} mismatch: max err {err} > {tolerance}"
                        )
        pairs_per_sec = n * n * len(times[1:]) / (sum(times[1:]) / 1000.0 + 1e-12)
        return {
            "n": n,
            "per_iter_ms": times,
            "gpairs_per_sec": pairs_per_sec / 1e9,
            "checked": bool(check),
        }
    finally:
        cr.dispose()


def nbody_e2e(
    devices: Devices | None = None,
    n: int = 8192,
    iters: int = 150,
    window: int = 50,
    dt: float = 0.0001,
    local_range: int = 256,
    tolerance: float = 0.01,
    attribution: bool = False,
    probe_iters: int | None = None,
    device_timeline_dir: str | None = None,
    fused: bool = True,
) -> dict:
    """The reference's flagship numeric loop END-TO-END (VERDICT r4 #7):
    n-body at reference scale (n=8k, 150 load-balanced iterations, ±0.01f
    host check — Tester.cs:7682-7799) through the full ``compute()``
    path: scheduler, balancer, uploads, ladder launches, readbacks.

    Departures from the reference loop, both TPU-idiomatic:

    - **enqueue windows** (``window`` computes per barrier) instead of a
      sync per iteration: over the tunnel a per-iteration sync measures
      RTT (r3's 0.37 Gpairs/s mistake); the barrier measures per-lane
      retirement and arms the sync-point rebalance — the production mode
      for repeated same-shape work.
    - on a single-chip host the range is balanced across **2 partition
      lanes** of the chip (the reference's CPU-fission analogue,
      ClDevice.cs:85-95): the balancer genuinely moves shares between
      lanes on real hardware rather than being vacuous on one device.

    Correctness is the reference's own pattern: the first step's
    velocities against the host O(n²) reference within ±``tolerance``
    (checked synchronously, before the timed window loop; velocities then
    keep accumulating — per-iteration work is identical).

    ``attribution=True`` (VERDICT r5 #3) records the timed loop through
    ``cekirdekler_tpu.trace`` and NAMES each factor of the e2e-vs-device
    throughput gap with a measurement in the result's ``attribution``
    key: **window RTT** (barrier fence spans — the per-window sync
    cost), **ladder launch** (host-side kernel dispatch spans),
    **upload/download** (transfer spans), **scheduler dispatch** (the
    enqueue spans' residue over the phases inside them), the
    **unattributed host gap**, and **lane interference** (a short
    single-lane probe run after the timed loop: factor = multi-lane
    per-iteration time × lanes / single-lane per-iteration time — 1.0
    means the lanes split the work perfectly, 2.0 means two partition
    lanes of one chip fully serialized against each other).
    ``device_timeline_dir`` additionally runs a SHORT separate enqueue
    window after the timed loop under a device-attribution capture
    (trace/device.py): an Xprof trace with per-launch correlation
    marks, reconciled against that probe window's wall and reported as
    the attribution's ``kernel_profile`` block (per-kernel device wall,
    op counts, idle gaps, coverage fraction, roofline row; a named
    ``{"absent": reason}`` on CPU-only rigs).  The headline wall itself
    is NEVER produced under the profiler — profiling perturbs it, and
    the gpairs key is regression-watched against unprofiled rounds.

    ``fused`` (default True — the production mode) lets the fused
    dispatch path collapse each window's repeated identical computes
    into batched single-ladder dispatches per lane (core/cores.py); the
    result's ``fused`` key reports windows/iterations/disengages, and
    with attribution on, a ``fused_dispatch`` factor accounts the ladder
    flush cost.  Note the factor semantics shift under fusion: iteration
    work dispatches in batches, so the barrier fence (``window_rtt``)
    absorbs device-drain wait the per-iteration path hid inside its
    dispatch stream — read ``window_rtt + ladder_launch +
    scheduler_dispatch`` together against wall, not fence alone.
    ``fused=False`` restores per-iteration dispatch exactly (the two
    paths are bit-identical; tests/test_fused.py pins it)."""
    from .hardware import all_devices

    devs = devices if devices is not None else all_devices()
    if len(devs.tpus()):
        devs = devs.tpus()
    lanes = len(devs)
    probe_devs = devs.subset(1)  # un-partitioned: the 1-lane probe rig
    single_chip_partitions = lanes == 1
    if single_chip_partitions:
        devs = devs[0].as_partitions(2)
        lanes = 2
    pos, (x, y, z), vel = _nbody_rig(n, "e")
    expected = nbody_host_step(
        pos[0], pos[1], pos[2],
        np.zeros(n, np.float32), np.zeros(n, np.float32),
        np.zeros(n, np.float32), dt,
    )
    cid = 7010
    cr = NumberCruncher(devs, NBODY_SRC)
    cr.fused_dispatch = fused
    group = x.next_param(y, z, *vel)
    try:
        # synchronous first step: the ±0.01 host check
        group.compute(cr, cid, "nBody", n, local_range, values=(n, dt))
        max_err = max(
            float(np.abs(got.host() - want).max())
            for got, want in zip(vel, expected)
        )
        if max_err > tolerance:
            raise AssertionError(
                f"nBody e2e mismatch: max err {max_err} > {tolerance}"
            )
        # warm the fused ladder executable OUTSIDE the timed loop: XLA
        # compiles it at its first dispatch, and a compile inside the
        # window would charge seconds to ladder_launch/wall that no
        # steady-state run pays (the per-call ladder was warmed by the
        # sync step above).  Three extra untimed iterations — the window
        # engages on the first consecutive repeat, so call 3 is the
        # first DEFERRED one and the barrier's flush is what compiles
        # the ladder; physically identical work, velocities simply keep
        # accumulating.
        if fused:
            cr.enqueue_mode = True
            for _ in range(3):
                group.compute(cr, cid, "nBody", n, local_range, values=(n, dt))
            cr.barrier()
        # stats snapshot so the artifact counts the TIMED loop only —
        # including disengages: a warm-phase disengage must not read as
        # a fall-back inside the measured run
        fstats0 = {
            k: cr.cores.fused_stats[k]
            for k in ("windows", "fused_iters", "deferred_iters")
        }
        fstats0["disengaged"] = dict(cr.cores.fused_stats["disengaged"])
        # timed: the 150-iteration balanced loop in enqueue windows
        from .trace.spans import TRACER

        was_tracing = TRACER.enabled
        if attribution and not was_tracing:
            TRACER.enable(clear=True)
        traj: list[list[int]] = []
        cr.enqueue_mode = True
        t0 = time.perf_counter()
        wall = 0.0
        t_end = t0
        try:
            for k in range(iters):
                group.compute(cr, cid, "nBody", n, local_range, values=(n, dt))
                traj.append(cr.ranges_of(cid))
                if (k + 1) % window == 0:
                    cr.barrier()
            cr.enqueue_mode = False  # flush
            # wall closes inside the try: the finally's tracer disable
            # (and any exception bookkeeping) must not inflate the
            # headline.  The profiler never runs here — the device
            # capture lives in _nbody_device_profile's separate probe
            # window so Xprof cannot perturb the watched gpairs number.
            wall = time.perf_counter() - t0
            t_end = time.perf_counter()
        finally:
            # a failed loop must not leave the global tracer enabled,
            # taxing everything that runs after
            if attribution and not was_tracing:
                TRACER.disable()
        fstats = cr.cores.fused_stats
        out = {
            "n": n,
            "iters": iters,
            "lanes": lanes,
            "window": window,
            "gpairs_per_sec": round(n * n * iters / wall / 1e9, 3),
            "wall_ms": round(wall * 1e3, 1),
            "checked": True,
            "host_check_max_err": round(max_err, 5),
            "ranges_first": traj[0],
            "ranges_final": traj[-1],
            "convergence_iters": _converged_at(traj, local_range),
            # fused-dispatch observability: how much of the window rode
            # the single-ladder path, and every disengage by name — a
            # silent fall-back to per-iteration dispatch would otherwise
            # read as device slowness
            "fused": {
                "enabled": bool(fused),
                "windows": fstats["windows"] - fstats0["windows"],
                "fused_iters": fstats["fused_iters"] - fstats0["fused_iters"],
                "deferred_iters": (
                    fstats["deferred_iters"] - fstats0["deferred_iters"]
                ),
                "disengaged": {
                    k: v - fstats0["disengaged"].get(k, 0)
                    for k, v in fstats["disengaged"].items()
                    if v - fstats0["disengaged"].get(k, 0) > 0
                },
            },
        }
        if attribution:
            out["attribution"] = _nbody_attribution(
                TRACER.spans_between(t0, t_end), t0, t_end, wall, iters,
                lanes, probe_devs, n, dt, local_range, window,
                probe_iters,
                ring_wrapped=TRACER.total_recorded > TRACER.capacity,
                dropped_spans=TRACER.dropped_spans,
                single_chip_partitions=single_chip_partitions,
                fused=fused,
                lane_kinds=list(cr.cores.lane_kinds),
            )
            if device_timeline_dir:
                out["attribution"].update(_nbody_device_profile(
                    cr, group, cid, n, dt, local_range, window, iters,
                    device_timeline_dir,
                ))
        return out
    finally:
        if cr.enqueue_mode:
            try:
                cr.enqueue_mode = False  # flush replays deferred work
            except Exception:  # noqa: BLE001 - must not mask the root
                pass           # cause or skip the dispose below
        cr.dispose()


def _nbody_device_profile(
    cr, group, cid: int, n: int, dt: float, local_range: int,
    window: int, iters: int, trace_dir: str,
) -> dict:
    """The profiler-backed device/host split for nbody_e2e — measured
    in a SHORT separate enqueue window run AFTER the timed loop (the
    flash section's discipline): the headline gpairs number is never
    produced under the profiler, which perturbs it, so the watched
    ``nbody_e2e_enqueue_gpairs`` trajectory stays comparable with the
    unprofiled rounds.  Returns the keys merged into the attribution
    block; degrades to ``kernel_profile: {"absent": reason}`` on rigs
    whose backend exposes no device tracks."""
    from .core.stream import plan_signature
    from .trace.device import STORE, DeviceCapture, roofline_row

    probe_iters = max(2, min(iters, window))
    cap = DeviceCapture(trace_dir)
    with cap:
        cr.enqueue_mode = True
        for _ in range(probe_iters):
            group.compute(cr, cid, "nBody", n, local_range, values=(n, dt))
        cr.barrier()
        cr.enqueue_mode = False
    rep = cap.report
    out: dict = {
        "device_events": rep.n_ops,
        "device_busy_ms": round(rep.device_busy_ms, 3),
        "device_busy_frac_of_wall": (
            round(rep.device_busy_ms / rep.wall_ms, 4)
            if rep.wall_ms > 0 and rep.absent is None else None
        ),
        # the per-kernel device report: device wall per kernel, op
        # counts, inter-op idle, per-lane overlap, coverage fraction —
        # or {"absent": <reason>} on CPU-only rigs
        "kernel_profile": (
            {"absent": rep.absent} if rep.absent is not None
            else {
                **rep.to_dict(),
                "profiled_iters": probe_iters,
                "note": ("profiled in a separate short window after "
                         "the timed loop — the headline wall ran "
                         "unprofiled"),
            }
        ),
    }
    if rep.absent is None:
        nb_prof = rep.kernel("nBody")
        if nb_prof is not None and nb_prof.device_ms > 0:
            # roofline/MFU row from the workload's analytic counts:
            # ~20 flops per pair interaction (3 sub, 6 FMA for r²,
            # rsqrt + scale, 6 FMA into v), and 9 array passes of
            # 4 B/element per iteration (x/y/z read, vx/vy/vz rw)
            rl = roofline_row(
                20.0 * float(n) * float(n) * probe_iters,
                9.0 * float(n) * 4 * probe_iters,
                nb_prof.device_ms,
            )
            out["kernel_profile"]["roofline"] = rl
            # store key blocks = the per-lane range geometry (each
            # active lane's share determines its launch ladder) via the
            # ONE geometry-signature helper, per the store contract
            ranges = [r for r in cr.ranges_of(cid) if r > 0]
            STORE.put(
                "nBody", (n,), (plan_signature(ranges), local_range),
                {"device_ms": round(nb_prof.device_ms, 3),
                 "op_count": nb_prof.op_count,
                 "launches": nb_prof.launches,
                 "mfu": rl["mfu"], "bound": rl["bound"],
                 "probe_wall_ms": round(rep.wall_ms, 3),
                 "probe_iters": probe_iters, "window": window},
            )
    return out


def _nbody_rig(n: int, prefix: str):
    """The nbody_e2e array rig — ONE construction shared by the measured
    run and the lane-interference probe, so the two cannot silently
    desynchronize (same seed, same operand layout, same flags)."""
    rng = np.random.default_rng(42)
    pos = (rng.random((3, n), dtype=np.float32) - 0.5) * 2.0
    xyz = [
        ClArray(pos[i].copy(), name=f"{prefix}{c}", read_only=True)
        for i, c in enumerate("xyz")
    ]
    vel = [
        ClArray(n, np.float32, name=f"{prefix}v{c}", partial_read=True)
        for c in "xyz"
    ]
    return pos, xyz, vel


def _nbody_attribution(
    spans, t0, t_end, wall, iters, lanes, probe_devs, n, dt,
    local_range, window, probe_iters, ring_wrapped=False,
    dropped_spans=0, single_chip_partitions=False, fused=True,
    lane_kinds=None,
) -> dict:
    """Name each factor of the nbody_e2e gap with a measurement
    (VERDICT r5 #3).  Fractions are of the e2e wall; they need not sum
    to 1 — launches/uploads overlap device execution by design, and the
    lane-interference factor is a ratio, not a time share."""
    from .trace.attribution import union_ms, window_report

    rep = window_report(spans, t0, t_end, ring_wrapped=ring_wrapped,
                        dropped_spans=dropped_spans,
                        lane_kinds=lane_kinds)

    def _kind(kind):
        # the report's window-clipped totals — the same numbers its own
        # per_kind table shows, so the factor rows cannot disagree with it
        v = rep.per_kind.get(kind, {"ms": 0.0, "count": 0})
        return v["ms"], v["count"]

    def _tagged_fence(tag_prefix):
        # same clipping rule as the report: re-reduce the tag-filtered
        # subset through window_report itself so the window_rtt factor
        # can never diverge from the per_kind fence convention
        sub = window_report(
            [s for s in spans
             if s.kind == "fence" and (s.tag or "").startswith(tag_prefix)],
            t0, t_end,
        ).per_kind.get("fence", {"ms": 0.0, "count": 0})
        return sub["ms"], sub["count"]

    wall_ms = wall * 1000.0
    fence_ms, n_barriers = _tagged_fence("barrier")
    launch_ms, n_launches = _kind("launch")
    upload_ms, n_uploads = _kind("upload")
    download_ms, n_downloads = _kind("download")
    up_chunk_ms, n_up_chunks = _kind("upload-chunk")
    down_chunk_ms, n_down_chunks = _kind("download-chunk")
    fused_ms, n_fused = _kind("fused")
    # scheduler residue: per enqueue span, its wall minus the UNION of
    # phase intervals inside it — raw per-kind sums double-count
    # concurrent lanes (2 lanes x 1 ms launch > a 1.5 ms enqueue wall)
    # and phases outside any enqueue span (the flush's downloads) are
    # not this residue's business
    phases = [
        s for s in spans
        if s.kind in (
            "launch", "upload", "download", "upload-chunk", "download-chunk",
        )
    ]
    sched_ms = 0.0
    for e in spans:
        if e.kind != "enqueue":
            continue
        inner = [
            (max(s.t0, e.t0), min(s.t1, e.t1))
            for s in phases
            if s.t1 > e.t0 and s.t0 < e.t1
        ]
        sched_ms += max(e.dur_ms - union_ms(inner), 0.0)

    def factor(ms, count=None):
        d = {"ms": round(ms, 3), "frac": round(ms / wall_ms, 4) if wall_ms else None}
        if count is not None:
            d["count"] = count
        return d

    out = {
        "wall_ms": round(wall_ms, 3),
        "factors": {
            "window_rtt": factor(fence_ms, n_barriers),
            "ladder_launch": factor(launch_ms, n_launches),
            "upload": factor(upload_ms, n_uploads),
            "download_flush": factor(download_ms, n_downloads),
            # the STREAMED transfer path's chunks (zero on runs where the
            # monolithic path served every transfer): chunk time overlaps
            # compute by design, so a large ms with a small wall frac is
            # the pipeline WORKING, not a regression
            "upload_chunks": factor(up_chunk_ms, n_up_chunks),
            "download_chunks": factor(down_chunk_ms, n_down_chunks),
            "scheduler_dispatch": factor(sched_ms),
            "fused_dispatch": factor(fused_ms, n_fused),
            "host_gap": factor(rep.gap_ms),
        },
        "per_kind_ms": {
            k: round(v["ms"], 3) for k, v in rep.per_kind.items()
        },
        # heterogeneous fleets (ISSUE 20): where the window's lane-
        # tagged time went per DEVICE KIND — on a mixed TPU + host-CPU
        # Cores this is the split's per-silicon account; homogeneous
        # fleets see one row
        "per_lane_kind_ms": {
            k: {"ms": round(v["ms"], 3), "count": v["count"],
                "lanes": sorted(v["lanes"])}
            for k, v in rep.per_lane_kind.items()
        },
        "ring_wrapped": ring_wrapped,  # True = factors undercount
        "dropped_spans": dropped_spans,  # exactly how many spans wrapped away
        "note": (
            "fracs are of e2e wall and overlap device time by design; "
            "window_rtt = barrier fences (sync cost per enqueue window), "
            "ladder_launch = host-side kernel dispatch, fused_dispatch = "
            "fused-window ladder flushes, host_gap = wall no span "
            "explains; lane_interference is a ratio (1.0 = lanes split "
            "the work perfectly, lanes_count = fully serialized)"
            + (
                "; FUSED path: iteration work dispatches in batches, so "
                "barrier fences absorb device-drain wait the "
                "per-iteration path hid inside its dispatch stream — "
                "judge window_rtt+ladder_launch+scheduler_dispatch "
                "against wall, not the fence alone"
                if fused else ""
            )
        ),
    }
    # lane interference: short single-lane probe on the un-partitioned
    # device — perfect lane scaling predicts multi-lane per-iter =
    # single-lane per-iter / lanes
    p_iters = probe_iters if probe_iters is not None else max(
        window, min(iters // 3, 2 * window)
    )
    try:
        _, (x1, y1, z1), vel1 = _nbody_rig(n, "pe")
        cr1 = NumberCruncher(probe_devs, NBODY_SRC)
        cr1.fused_dispatch = fused  # probe rides the same dispatch mode
        g1 = x1.next_param(y1, z1, *vel1)
        try:
            g1.compute(cr1, 7011, "nBody", n, local_range, values=(n, dt))
            cr1.enqueue_mode = True
            if fused:
                # same untimed fused-ladder warm as the measured run (a
                # fresh cruncher means a fresh executable cache; 3 calls
                # = seed + engage + one deferred iteration to dispatch)
                for _ in range(3):
                    g1.compute(cr1, 7011, "nBody", n, local_range,
                               values=(n, dt))
                cr1.barrier()
            t1 = time.perf_counter()
            for k in range(p_iters):
                g1.compute(cr1, 7011, "nBody", n, local_range, values=(n, dt))
                if (k + 1) % window == 0:
                    cr1.barrier()
            cr1.enqueue_mode = False
            single_wall = time.perf_counter() - t1
        finally:
            if cr1.enqueue_mode:
                cr1.enqueue_mode = False
            cr1.dispose()
        per_iter_multi = wall_ms / iters
        per_iter_single = single_wall * 1000.0 / p_iters
        out["lane_interference"] = {
            "factor": round(per_iter_multi * lanes / max(per_iter_single, 1e-9), 3),
            "per_iter_ms_multi": round(per_iter_multi, 3),
            "per_iter_ms_single_lane": round(per_iter_single, 3),
            "lanes": lanes,
            "probe_iters": p_iters,
            "single_chip_partitions": single_chip_partitions,
        }
        if single_chip_partitions:
            # on the partition fallback both runs share ONE TensorCore,
            # so factor ≈ lanes is the EXPECTED floor (partition lanes
            # split a chip, they don't add one) — the factor then
            # measures partition-scheduling overhead ABOVE that floor,
            # not cross-chip interference; say so in the artifact before
            # someone chases a scheduler defect the metric can't see here
            out["lane_interference"]["note"] = (
                f"single-chip partition lanes: both runs share one core, "
                f"factor ≈ {lanes} is the expected floor; read the excess "
                f"over {lanes}, not the absolute value"
            )
    except Exception as e:  # noqa: BLE001 - probe failure must not kill e2e
        out["lane_interference"] = {"error": f"{type(e).__name__}: {e}"[:200]}
    return out


def run_stream(
    devices: Devices | None = None,
    n: int = 1 << 20,
    reps: int = 10,
    blobs: int = 8,
    local_range: int = 256,
    fast: bool = True,
) -> dict:
    """Streaming c = a + b with the driver-pipeline analogue
    (reference: Tester.cs:7806-7843 — 1M floats, 8 blobs, 10 reps,
    zero-copy FastArr inputs)."""
    from .hardware import all_devices

    a = ClArray(n, np.float32, name="a", fast=fast, partial_read=True, read_only=True, zero_copy=fast)
    b = ClArray(n, np.float32, name="b", fast=fast, partial_read=True, read_only=True, zero_copy=fast)
    c = ClArray(n, np.float32, name="c", fast=fast, write_only=True)
    a.host()[:] = np.arange(n, dtype=np.float32) % 97
    b.host()[:] = np.arange(n, dtype=np.float32) % 89
    cr = NumberCruncher(devices or all_devices(), STREAM_SRC)
    group = a.next_param(b, c)
    times: list[float] = []
    try:
        for _ in range(reps):
            t0 = time.perf_counter()
            group.compute(cr, 7003, "streamAdd", n, local_range, pipeline=True, pipeline_blobs=blobs)
            times.append((time.perf_counter() - t0) * 1000.0)
        want = a.host() + b.host()
        if not np.allclose(c.host(), want):
            raise AssertionError("stream add mismatch")
        best = min(times)
        # 3 arrays × 4 bytes move per element per rep
        return {
            "n": n,
            "per_rep_ms": times,
            "gb_per_sec": (3 * 4 * n) / (best / 1000.0) / 1e9,
        }
    finally:
        cr.dispose()
        for arr in (a, b, c):
            arr.dispose()


def measure_stream_overlap(
    devices: Devices | None = None,
    n: int = 1 << 22,
    blobs: int = 8,
    local_range: int = 256,
    pipeline_type: int | None = None,
    reps: int = 3,
    heavy_iters: int | str = 0,
    compute_factor: float = 1.0,
    duplex_probe: bool = False,
    streamed: bool = False,
) -> dict:
    """Measure the realized read/compute/write overlap fraction of the
    pipelined path on ONE chip (BASELINE.md metric 2; the engineered
    property behind the reference's 3× pipelining claim, Cores.cs:467).

    ``heavy_iters`` > 0 swaps the plain add for a per-element iteration
    kernel so blob compute is commensurate with blob transfer — on a slow
    host link plain streamAdd is ~99% transfer and r/c/w overlap is
    unobservable regardless of scheduling.  ``heavy_iters="auto"``
    CALIBRATES the iteration count to the link measured right now
    (compute ≈ read + write; capped at 150k to keep the exactness
    self-check's quarter-integer sums representable in f32) — a count
    tuned for one day's bandwidth measures the wrong regime after the
    tunnel drifts 100x.  The chosen count is reported as
    ``heavy_iters`` in the result.

    Method (VERDICT r2 #3 — comparable phases, no clipping): ``reps``
    INTERLEAVED rounds, each measuring every phase once (idle fence RTT
    sampled per round and subtracted from fence-terminated phases), and the
    per-phase MEDIAN across rounds is reported — host-link bandwidth
    drifts by ~2x over minutes, so separate multi-rep windows per phase
    let drift masquerade as ±overlap (round-2's isolated phases were
    additionally fence-dominated, making the ratio >1 and meaningless).
    ``sample_spread`` reports max per-phase (max-min)/median so the
    artifact shows how noisy the link was.

    ``compute_factor`` scales the ``"auto"`` calibration target: 1.0 is
    the balanced regime (compute ≈ read + write), 3.0 the compute-bound
    regime the reference's 3x claim describes (Cores.cs:467).

    ``duplex_probe=True`` interleaves pure H2D / D2H / duplex transfer
    samples INTO THE SAME rounds (VERDICT r4 #3: the ceiling and the
    achieved overlap must share a measurement window — judged minutes
    apart on a link that drifts 100x, "both are weather").  The ceiling
    is then computed PER REP from that rep's own complete sample by
    ``trace/ceiling.py`` (VERDICT r5 #4: the r5 cross-rep-median model
    read 1.15 — achieved above "ceiling" means the ruler was broken):
    each rep derives its duplex capacity, models
    ``p_model = max(c, r + w − dc·min(r, w)) + (r + w)/blobs``, and
    clamps the ceiling to the rep's own measured pipelined time (a run
    that happened is an existence proof the ceiling cannot exceed), so
    ``achieved_vs_ceiling`` — the MEDIAN of per-rep ratios, reported
    with ``achieved_vs_ceiling_spread`` — is structurally ≤ 1.0, and
    the BASELINE ≥0.9 target is judged against a real bound.

    ``streamed=True`` measures the STREAMED plain path instead of a
    pipeline engine: the "pipelined" phase becomes an ordinary
    ``compute()`` whose partition transfers ride the chunked
    double-buffered wavefront (``Cores._run_streamed`` — ladder-aligned
    chunks, autotuned count, depth-2 stream driver).  With
    ``duplex_probe`` on, the autotuner is seeded from a duplex sample
    taken BEFORE the timed rounds (the same link weather the rounds will
    see), and the result reports the chosen ``stream_chunks`` next to
    the overlap so the artifact shows WHAT the autotuner picked under
    the measured conditions.

    With median phase times r, c, w and pipelined total p::

        overlap = (r + c + w - p) / (r + c + w - max(r, c, w))

    1.0 = the pipelined total equals the slowest phase (perfect overlap);
    0.0 = fully serial.  The RAW ratio is returned — values < 0 mean
    pipeline overhead exceeded any overlap, values > 1 mean the phase
    decomposition was wrong; neither is hidden.  On tunneled backends the
    device timeline exposes no DMA events (utils/timeline.py), so this
    host-window method with fence-cost subtraction is the honest
    alternative; ``rtt_ms`` is included so the artifact shows the scale of
    what was subtracted.
    """
    from .core.cores import PIPELINE_EVENT
    from .hardware import all_devices

    if pipeline_type is None:
        pipeline_type = PIPELINE_EVENT
    devs = (devices or all_devices()).subset(1)
    kname = "streamHeavy" if heavy_iters else "streamAdd"
    auto_balance = heavy_iters == "auto"
    if auto_balance:
        heavy_iters = 1000  # placeholder until calibration below
    kvals = (heavy_iters,) if heavy_iters else ()
    cr = NumberCruncher(devs, STREAM_HEAVY_SRC if heavy_iters else STREAM_SRC)
    w = cr.cores.workers[0]
    a = ClArray(n, np.float32, name="ov_a", partial_read=True, read_only=True)
    b = ClArray(n, np.float32, name="ov_b", partial_read=True, read_only=True)
    c = ClArray(n, np.float32, name="ov_c", write_only=True)
    a.host()[:] = np.arange(n, dtype=np.float32) % 97
    b.host()[:] = np.arange(n, dtype=np.float32) % 89
    blob = n // blobs

    def fence():
        cr.barrier()

    def phase_read() -> None:
        for arr in (a, b):
            w.invalidate(arr)
        for k in range(blobs):
            for arr in (a, b):
                w.upload(arr, k * blob, blob, False)

    def phase_compute() -> None:
        # data already resident from the last read phase
        w.ensure_resident(c)
        for k in range(blobs):
            w.launch(
                cr.program, [kname], [a, b, c], kvals,
                k * blob, blob, local_range, n, local_range,
            )

    def phase_write() -> None:
        from .core.worker import Worker

        handles = [
            w.download_async(c, k * blob, blob, False) for k in range(blobs)
        ]
        for h in handles:
            Worker.finish_download(h)

    def phase_pipelined() -> None:
        for arr in (a, b, c):
            w.invalidate(arr)
        a.next_param(b, c).compute(
            cr, 7004, kname, n, local_range,
            pipeline=True, pipeline_blobs=blobs, pipeline_type=pipeline_type,
            values=kvals,
        )

    def phase_streamed() -> None:
        # the PLAIN path: partition transfers ride the chunked
        # double-buffered wavefront (Cores._run_streamed) — no pipeline
        # engine, no blob step change, same compile-once ladder
        for arr in (a, b, c):
            w.invalidate(arr)
        a.next_param(b, c).compute(
            cr, 7004, kname, n, local_range, values=kvals,
        )

    phase_pipe = phase_streamed if streamed else phase_pipelined

    def timed(fn, needs_fence: bool, rtt: float) -> float:
        t0 = time.perf_counter()
        fn()
        if needs_fence:
            fence()
        total = (time.perf_counter() - t0) * 1000.0
        if needs_fence:
            total -= rtt
        return max(total, 1e-6)

    try:
        # warmup: compile + first-touch, and all four paths exercised once
        phase_read()
        phase_compute()
        fence()
        phase_write()
        phase_pipe()
        if auto_balance:
            # calibrate iters so compute ~= read + write ON THIS LINK —
            # a fixed iteration count tuned for one link speed measures
            # the transfer-bound regime on a slower link (r3's 30000 was
            # right for ~1 GB/s; the tunnel drifts 100x), and overlap of
            # a mismatched regime says nothing about the engine
            t0 = time.perf_counter()
            fence()
            rtt0 = (time.perf_counter() - t0) * 1000.0

            def t_read_once() -> float:
                t0 = time.perf_counter()
                phase_read()
                fence()
                return (time.perf_counter() - t0) * 1000.0 - rtt0

            # min-of-2 like the compute probes: one drift spike on the
            # single read sample would otherwise floor/ceil the result
            t_r0 = max(min(t_read_once(), t_read_once()), 1e-3)

            def t_compute_at(iters: int) -> float:
                t0 = time.perf_counter()
                w.ensure_resident(c)
                for k in range(blobs):
                    w.launch(
                        cr.program, [kname], [a, b, c], (iters,),
                        k * blob, blob, local_range, n, local_range,
                    )
                fence()
                return (time.perf_counter() - t0) * 1000.0 - rtt0

            c1 = min(t_compute_at(2000), t_compute_at(2000))
            c2 = min(t_compute_at(6000), t_compute_at(6000))
            if c2 - c1 <= 0:
                # drift/noise spike inverted the two samples: keep the
                # r3 default rather than calibrating into an extreme
                heavy_iters = 30000
            else:
                # compute-phase model: intercept + slope*iters — the
                # intercept (fixed dispatch cost per phase) matters on a
                # fast link where it rivals the transfer time
                slope = (c2 - c1) / 4000.0  # ms per iteration
                intercept = max(c1 - 2000.0 * slope, 0.0)
                # target: compute ~= compute_factor * (read + write),
                # read + write ~= 2*t_r0
                # cap 150k: the exactness self-check below needs the
                # quarter-integer accumulation to stay < 2^22
                # (150k iters x 0.25 x max(b)=88 ~= 3.3M), and beyond it
                # the regime is compute-bound anyway
                heavy_iters = int(min(
                    max(
                        (compute_factor * 2.0 * t_r0 - intercept) / slope,
                        1000,
                    ),
                    150_000,
                ))
            kvals = (heavy_iters,)
        # INTERLEAVED rounds (VERDICT-honest methodology note: tunnel
        # bandwidth drifts by 2x over minutes, so measuring each phase in
        # its own multi-rep window lets drift masquerade as ±overlap;
        # round-robin sampling keeps every phase's samples seconds apart
        # and the per-phase MEDIAN cancels the drift)
        samples: dict[str, list[float]] = {
            "r": [], "c": [], "w": [], "p": [], "rtt": [],
            "h2d": [], "d2h": [], "dup": [],
        }
        if duplex_probe:
            import jax
            import jax.numpy as jnp

            jdev = devs[0].jax_device
            dup_host = np.arange(n, dtype=np.float32)
            dup_base = jax.device_put(jnp.zeros(n, jnp.float32), jdev)
            jax.block_until_ready(dup_base)
            dup_k = [0]

            def _fresh_host():
                dup_k[0] += 1
                dup_host[0] = dup_k[0]
                return dup_host

            def _fresh_dev():
                dup_k[0] += 1
                y = dup_base + np.float32(dup_k[0])
                jax.block_until_ready(y)
                return y

            def probe_duplex(rtt: float, into: dict | None = None) -> None:
                """One H2D, one D2H, one duplex sample — fresh payloads so
                the transport cannot elide, same 4n bytes as the phases.
                ``into`` redirects the samples (the autotuner's seeding
                probe must not enter the per-rep pairing)."""
                dst = samples if into is None else into
                h = _fresh_host()
                t0 = time.perf_counter()
                jax.block_until_ready(jax.device_put(h, jdev))
                w1 = (time.perf_counter() - t0) * 1000.0
                dst["h2d"].append(max(w1 - rtt, w1 * 0.05))
                y = _fresh_dev()
                t0 = time.perf_counter()
                np.asarray(y)
                w2 = (time.perf_counter() - t0) * 1000.0
                dst["d2h"].append(max(w2 - rtt, w2 * 0.05))
                y = _fresh_dev()
                h = _fresh_host()
                t0 = time.perf_counter()
                x = jax.device_put(h, jdev)  # async H2D
                np.asarray(y)                # D2H
                jax.block_until_ready(x)
                w3 = (time.perf_counter() - t0) * 1000.0
                dst["dup"].append(max(w3 - rtt, w3 * 0.05))

            if streamed:
                # seed the transfer autotuner from a duplex sample taken
                # under the SAME link weather the timed rounds will see
                # (per-MiB cost each direction; the seeding sample stays
                # out of the per-rep ceiling pairing)
                t0 = time.perf_counter()
                fence()
                rtt_seed = (time.perf_counter() - t0) * 1000.0
                scratch: dict = {"h2d": [], "d2h": [], "dup": []}
                probe_duplex(rtt_seed, into=scratch)
                mib = (4.0 * n) / float(1 << 20)
                cr.cores.transfer_tuner.seed_link(
                    w.index, scratch["h2d"][0] / mib, scratch["d2h"][0] / mib
                )

        if streamed:
            # the warmup's measuring run observed the PRE-calibration
            # workload (with heavy_iters="auto" it ran the 1000-iter
            # placeholder): drop it, or the first chunked settle run
            # below would blame the calibration's extra compute on
            # per-chunk overhead, freeze the tuner at 1 chunk, and the
            # timed rounds would silently measure the monolithic path
            # while reporting transfer_path="streamed-ladder"
            cr.cores.transfer_tuner.on_repartition()
            # this deliberate drop is NOT a balancer re-partition: take
            # the baseline after it so the reported count stays "re-tunes
            # forced by re-partitions" (and keeps agreeing with
            # ck_stream_retune_total, which only the balancer path incs)
            retunes0 = cr.cores.transfer_tuner.retunes
            # untimed tuner-settle runs: the first streamed call is the
            # tuner's monolithic measuring run (at the CALIBRATED
            # workload), the next pays the chunked exploration that
            # teaches the lane's REAL per-chunk overhead (sub-ms on a
            # TPU lane, tens of ms on a CPU interpreter) — the timed
            # rounds then measure the SETTLED configuration, not the
            # learning transient
            phase_pipe()
            phase_pipe()
        for _ in range(reps):
            t0 = time.perf_counter()
            fence()
            rtt = (time.perf_counter() - t0) * 1000.0
            samples["rtt"].append(rtt)
            samples["r"].append(timed(phase_read, True, rtt))
            samples["c"].append(timed(phase_compute, True, rtt))
            samples["w"].append(timed(phase_write, False, rtt))
            samples["p"].append(timed(phase_pipe, False, rtt))
            if duplex_probe:
                probe_duplex(rtt)

        def med(key: str) -> float:
            vals = sorted(samples[key])
            return vals[len(vals) // 2]

        t_r, t_c, t_w, t_p = med("r"), med("c"), med("w"), med("p")
        serial = t_r + t_c + t_w
        ideal = serial - max(t_r, t_c, t_w)
        overlap = (serial - t_p) / ideal if ideal > 1e-9 else 0.0
        spread = max(
            (max(samples[k]) - min(samples[k])) / max(med(k), 1e-9)
            for k in ("r", "w", "p")
        )
        ceiling_keys: dict = {}
        if duplex_probe:
            # per-rep ceilings from each rep's OWN complete sample
            # (trace/ceiling.py: same-rep duplex capacity + fill/drain
            # edge + witness clamp), reduced to median ± spread — the
            # r5 cross-rep-median model could read >1; this cannot
            from .trace.ceiling import RepSample, ceiling_report

            reps_full = [
                RepSample(
                    r=samples["r"][i], c=samples["c"][i], w=samples["w"][i],
                    p=samples["p"][i], h2d=samples["h2d"][i],
                    d2h=samples["d2h"][i], dup=samples["dup"][i],
                )
                for i in range(len(samples["p"]))
                if i < len(samples["dup"])
            ]
            # the fill/drain edge term scales with the schedule's actual
            # chunk granularity: the engine's blob count, or the chunk
            # count the autotuner picked for the streamed path
            eff_blobs = blobs
            if streamed:
                eff_blobs = max(
                    cr.cores.last_stream_chunks.get(w.index, 1), 1
                )
            ceiling_keys = {
                "duplex_h2d_ms": round(med("h2d"), 3),
                "duplex_d2h_ms": round(med("d2h"), 3),
                "duplex_ms": round(med("dup"), 3),
                "compute_transfer_ratio": round(t_c / max(t_r + t_w, 1e-9), 2),
                **ceiling_report(reps_full, eff_blobs),
            }
        if heavy_iters:
            # acc = a + iters*(b/4), exact in f32 (quarter-integer sums
            # below 2^24) — the timing numbers are only publishable if the
            # pipelined path computed the right thing
            want = a.host() + heavy_iters * 0.25 * b.host()
            np.testing.assert_allclose(c.host(), want, rtol=1e-6)
        else:
            np.testing.assert_allclose(c.host(), a.host() + b.host())
        stream_keys: dict = {}
        if streamed:
            stream_keys = {
                "transfer_path": "streamed-ladder",
                "stream_chunks": cr.cores.last_stream_chunks.get(
                    w.index, 1
                ),
                "autotuner_retunes": (
                    cr.cores.transfer_tuner.retunes - retunes0
                ),
            }
        return {
            "t_read_ms": t_r,
            "t_compute_ms": t_c,
            "t_write_ms": t_w,
            "t_pipelined_ms": t_p,
            "t_serial_ms": serial,
            "rtt_ms": med("rtt"),
            "overlap_fraction": overlap,  # RAW — see docstring
            "sample_spread": spread,  # >1 = tunnel drift swamps the signal
            "n": n,
            "blobs": blobs,
            "reps": reps,
            "heavy_iters": int(heavy_iters) if heavy_iters else 0,
            **stream_keys,
            **ceiling_keys,
        }
    finally:
        cr.dispose()


def overlap_chunk_sweep(
    devices: Devices | None = None,
    ns: tuple[int, ...] = (1 << 20, 1 << 22),
    chunk_counts: tuple[int, ...] = (1, 2, 4, 8, 16, 32),
    local_range: int = 256,
    reps: int = 3,
    heavy_iters: int = 400,
) -> dict:
    """Chunk-count × array-size sweep of the STREAMED plain path
    (``tools/overlap_sweep.py``'s measurement): for each size, time the
    streamed compute with the chunk count PINNED at each candidate, then
    let the autotuner choose — by that point it has honest monolithic
    observations (the pinned c=1 rows) plus chunked refinements from the
    rest of the sweep, exactly the inputs it sees in production — and
    report its chosen point against the sweep optimum.

    Per size: ``rows`` (chunks → median wall ms), ``sweep_best_chunks``
    / ``sweep_best_ms`` (the measured argmin), ``autotuner_chunks`` /
    ``autotuner_ms`` (the choice and its measured wall), and
    ``choice_vs_optimum`` = autotuner wall / optimum wall (1.0 = the
    tuner found the measured optimum; the grid's discreteness and link
    drift make ~1.1 normal).  Walls are raw comparative medians — same
    rig, same rounds, so the ratio is the honest signal."""
    from .hardware import all_devices

    devs = (devices or all_devices()).subset(1)
    kname = "streamHeavy" if heavy_iters else "streamAdd"
    kvals = (heavy_iters,) if heavy_iters else ()
    bad = [n for n in ns if n < local_range or n % local_range]
    if bad:
        raise ValueError(
            f"sweep sizes {bad} are not multiples of local_range "
            f"{local_range} — compute() would reject them; pass --local"
        )
    # chunks=1 (the monolithic identity baseline) is always swept: it is
    # valid at any n, so the rows list can never end up empty when every
    # user-passed count exceeds n//local_range
    chunk_counts = tuple(sorted({1, *(int(c) for c in chunk_counts)}))
    sizes_out: list[dict] = []
    for n in ns:
        cr = NumberCruncher(
            devs, STREAM_HEAVY_SRC if heavy_iters else STREAM_SRC
        )
        w = cr.cores.workers[0]
        a = ClArray(n, np.float32, name="sw_a", partial_read=True,
                    read_only=True)
        b = ClArray(n, np.float32, name="sw_b", partial_read=True,
                    read_only=True)
        c = ClArray(n, np.float32, name="sw_c", write_only=True)
        a.host()[:] = np.arange(n, dtype=np.float32) % 97
        b.host()[:] = np.arange(n, dtype=np.float32) % 89

        def run_once() -> float:
            for arr in (a, b, c):
                w.invalidate(arr)
            t0 = time.perf_counter()
            a.next_param(b, c).compute(
                cr, 7104, kname, n, local_range, values=kvals
            )
            return (time.perf_counter() - t0) * 1000.0

        try:
            rows: list[dict] = []
            # chunks=1 is the monolithic path — valid at ANY n, so the
            # floor keeps a sub-local_range size from emptying the sweep
            max_chunks = max(1, n // local_range)
            for cc in chunk_counts:
                if cc > max_chunks:
                    continue
                cr.stream_chunks = cc  # 1 pins the monolithic path
                run_once()  # warm: ladder compile + tuner observation
                wall = float(np.median([run_once() for _ in range(reps)]))
                rows.append({"chunks": cc, "wall_ms": round(wall, 3)})
            best = min(rows, key=lambda r: r["wall_ms"])
            cr.stream_chunks = 0  # autotune from the sweep's observations
            run_once()  # the choice lands in last_stream_chunks
            auto_wall = float(np.median([run_once() for _ in range(reps)]))
            chosen = cr.cores.last_stream_chunks.get(w.index, 1)
            sizes_out.append({
                "n": n,
                "mib": round((3 * 4 * n) / float(1 << 20), 1),
                "rows": rows,
                "sweep_best_chunks": best["chunks"],
                "sweep_best_ms": best["wall_ms"],
                "autotuner_chunks": chosen,
                "autotuner_ms": round(auto_wall, 3),
                "choice_vs_optimum": round(
                    auto_wall / max(best["wall_ms"], 1e-9), 3
                ),
            })
        finally:
            cr.dispose()
            for arr in (a, b, c):
                arr.dispose()
    return {
        "note": (
            "streamed-path walls (ms, median of reps) per pinned chunk "
            "count; autotuner row = the count Cores.transfer_tuner "
            "chooses AFTER the sweep taught it this rig's link"
        ),
        "heavy_iters": heavy_iters,
        "local_range": local_range,
        "reps": reps,
        "sizes": sizes_out,
    }


def convergence_iterations(
    devices: Devices | None = None, max_iter: int = 192, width: int = 1024, height: int = 1024
) -> int | None:
    """Measure load-balance convergence on the mandelbrot workload
    (BASELINE.md: 'iterations until max share delta < step')."""
    res = run_mandelbrot(devices, width=width, height=height, max_iter=max_iter, iters=16, warmup=0)
    return res.convergence_iters


# ---------------------------------------------------------------------------
# lowering faceoff: the two kernel-language lowerings compared at device
# throughput, tunnel-robustly
# ---------------------------------------------------------------------------

# 8-tap wave-equation stencil (reference: Kamera.cs waveEquation shape,
# Kamera.cs:233-268) — static shifts crossing rows and lanes; exercises
# the Pallas halo-block path.
WAVE_SRC = """
__kernel void wave(__global float* p, __global float* pold, __global float* pnew) {
    int i = get_global_id(0);
    float lap = p[i-1] + p[i+1] + p[i-128] + p[i+128] + p[i-129] + p[i+129]
              + p[i-127] + p[i+127] - 8.0f*p[i];
    pnew[i] = 2.0f*p[i] - pold[i] + 0.2f*lap;
}
"""



def measure_rtt(reps: int = 5) -> float:
    """Best-of-``reps`` tunnel round-trip time: one tiny device op + 4-byte
    D2H.  The shared probe for every RTT-subtracting measurement here and
    in bench.py — fix it once, every correction moves together."""
    import jax.numpy as jnp

    t = jnp.zeros(8, jnp.float32)
    np.asarray(t)
    return min(
        (lambda t0: (np.asarray(t + 1.0), time.perf_counter() - t0)[1])(
            time.perf_counter()
        )
        for _ in range(reps)
    )


def lowering_faceoff(
    nbody_n: int = 8192,
    wave_n: int = 1 << 24,
    mandel_wh: int = 2048,
    reps: int = 16,
    wave_reps: int = 192,
    nbody_reps: int = 64,
) -> dict:
    """Device-throughput comparison of the XLA and Pallas lowerings on the
    three subset shapes: mandelbrot (elementwise + divergent loop), n-body
    (lane-uniform gather loop -> SMEM operand), wave stencil (static
    shifts -> halo blocks).

    Tunnel-robust methodology: each measurement runs ``reps`` DEPENDENT
    steps INSIDE one jitted ``lax.fori_loop`` (each step's output feeds
    the next step's input, so steps cannot be elided, and the per-launch
    dispatch floor — several ms over a tunneled backend — is paid once,
    not per step) with exactly ONE host materialization at the end; the
    measured tunnel RTT is subtracted once.  This reports DEVICE
    throughput of the lowering itself — the compute()-harness benches
    (run_mandelbrot / run_nbody) include scheduler + transfer + sync costs
    on top and answer a different question.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from .kernel import codegen, lang
    from .kernel.pallas_backend import build_kernel_fn_pallas

    rtt = measure_rtt()

    def chain(fn, arrs, make_vals, rotate, touch, nreps):
        """Best-of-3 seconds per step: nreps dependent steps in ONE jitted
        fori_loop, one host sync, RTT subtracted (clamped at 5% of wall:
        an RTT sample larger than the run must not produce negative or
        near-zero times).  Only valid when each step READS the previous
        step's output — a write-only chain would be dead-code-eliminated
        down to its last step.  The best-of-3 samples are themselves
        chained (each run's outputs are the next run's inputs) so no two
        samples are identical executions either — a replayed/elided
        sample would otherwise win the min()."""

        @jax.jit
        def run(arrs):
            def step(j, cur):
                out = fn(0, cur, make_vals(j))
                return rotate(cur, out)

            return lax.fori_loop(0, nreps, step, tuple(arrs))

        cur = run(tuple(arrs))
        np.asarray(touch(cur)[:8])
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            cur = run(tuple(cur))
            np.asarray(touch(cur)[:8])
            wall = time.perf_counter() - t0
            best = min(best, max(wall - rtt, wall * 0.05) / nreps)
        return best

    def faceoff(kdef, arrs, make_vals, rotate, touch, nreps):
        n = arrs[0].shape[0]
        xla_fn, _ = codegen.build_kernel_fn(kdef, n, 256, n)
        # force=True: measure the Pallas path even where the routing
        # policy (informed by THIS bench) prefers XLA — the faceoff is
        # the evidence the policy rests on
        pl_fn, _ = build_kernel_fn_pallas(kdef, n, 256, n, force=True)
        dt_x = chain(xla_fn, arrs, make_vals, rotate, touch, nreps)
        dt_p = chain(pl_fn, arrs, make_vals, rotate, touch, nreps)
        v0 = make_vals(0)
        ox = jax.jit(xla_fn)(0, tuple(arrs), v0)
        op = jax.jit(pl_fn)(0, tuple(arrs), v0)
        match = all(
            np.allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)
            for a, b in zip(ox, op)
        )
        return dt_x, dt_p, match

    rng = np.random.default_rng(42)
    out: dict = {"rtt_ms": round(rtt * 1e3, 1), "reps": reps,
                 "wave_reps": wave_reps, "nbody_reps": nbody_reps}

    # mandelbrot writes a fresh image each launch (out is write-only, so a
    # dependent in-jit chain is impossible — it would dead-code-eliminate);
    # instead: reps separate launches with DISTINCT x0 args (distinct args
    # defeat transport-level caching), floor paid per launch.  The Pallas
    # time is 3-4x the dispatch floor, so the ratio is mildly compressed
    # toward 1 — reported as-is.
    kdef = {k.name: k for k in lang.parse_kernels(MANDELBROT_SRC)}["mandelbrot"]
    N = mandel_wh * mandel_wh
    marrs = (jnp.zeros(N, jnp.float32),)

    def mandel_time(fn):
        f = jax.jit(fn)
        mk = lambda j: (
            np.float32(-2.0 - 1e-4 * j), np.float32(-1.25),
            np.float32(2.5 / mandel_wh), np.float32(2.5 / mandel_wh),
            np.int32(mandel_wh), np.int32(256),
        )
        o = f(0, marrs, mk(999))
        np.asarray(o[0][:8])
        best = float("inf")
        # x0 values are distinct across ALL launches of ALL best-of
        # samples (j counts globally) — a transport replaying any earlier
        # identical execution would need a matching x0, and there is none
        j = 0
        for _ in range(3):
            t0 = time.perf_counter()
            o = None
            for _ in range(reps):
                o = f(0, marrs, mk(j))
                j += 1
            np.asarray(o[0][:8])
            wall = time.perf_counter() - t0
            best = min(best, max(wall - rtt, wall * 0.05) / reps)
        return best

    xla_fn, _ = codegen.build_kernel_fn(kdef, N, 256, N)
    pl_fn, _ = build_kernel_fn_pallas(kdef, N, 256, N)
    dt_x, dt_p = mandel_time(xla_fn), mandel_time(pl_fn)
    out["mandelbrot"] = {
        "xla_mpix_s": round(N / dt_x / 1e6, 1),
        "pallas_mpix_s": round(N / dt_p / 1e6, 1),
        "speedup": round(dt_x / dt_p, 2),
    }

    # n-body: leapfrog chain — positions drift by the updated velocities
    # between steps (the kernel itself updates velocities only, matching
    # the reference; a static-positions chain would let XLA hoist the
    # loop-invariant O(n^2) accel pass out of the rep loop)
    kdef = {k.name: k for k in lang.parse_kernels(NBODY_SRC)}["nBody"]
    narrs = tuple(
        jnp.asarray(rng.standard_normal(nbody_n).astype(np.float32))
        for _ in range(6)
    )
    nvals = (np.int32(nbody_n), np.float32(1e-4))
    dt_x, dt_p, match = faceoff(
        kdef, narrs, lambda j: nvals,
        rotate=lambda cur, o: (
            cur[0] + o[3] * 1e-4, cur[1] + o[4] * 1e-4, cur[2] + o[5] * 1e-4,
            o[3], o[4], o[5],
        ),
        touch=lambda o: o[3],
        nreps=nbody_reps,
    )
    gp = nbody_n * nbody_n / 1e9
    out["nbody"] = {
        "xla_gpairs_s": round(gp / dt_x, 3),
        "pallas_gpairs_s": round(gp / dt_p, 3),
        "speedup": round(dt_x / dt_p, 2),
        "match": match,
    }

    # wave: leapfrog chain (pnew -> p -> pold)
    kdef = {k.name: k for k in lang.parse_kernels(WAVE_SRC)}["wave"]
    warrs = tuple(
        jnp.asarray((rng.standard_normal(wave_n) * 0.5).astype(np.float32))
        for _ in range(3)
    )
    dt_x, dt_p, match = faceoff(
        kdef, warrs, lambda j: (),
        rotate=lambda cur, o: (o[2], cur[0], cur[1]),
        touch=lambda o: o[2],
        nreps=wave_reps,
    )
    out["wave_stencil"] = {
        "xla_ms": round(dt_x * 1e3, 3),
        "pallas_ms": round(dt_p * 1e3, 3),
        "xla_gelem_s": round(wave_n / dt_x / 1e9, 2),
        "pallas_gelem_s": round(wave_n / dt_p / 1e9, 2),
        "speedup": round(dt_x / dt_p, 2),
        "match": match,
    }
    return out


def marker_overhead(n: int = 4096, dispatches: int = 200) -> dict:
    """Per-dispatch host gap with fine-grained markers OFF vs ON — the
    reference quantifies this cost as 2-3 µs -> 150-200 µs per light
    kernel (ClNumberCruncher.cs:79; Cores.cs:447 says 200-300 µs).

    Methodology: a light kernel (tiny saxpy) dispatched ``dispatches``
    times in enqueue mode (no per-call sync — the loop measures pure host
    dispatch cost, which is what markers tax: every launch additionally
    increments the native counter and enqueues a completion join).  One
    barrier closes each run; its cost is excluded by timing only the
    dispatch loop.  Reported per-dispatch, best of 3 runs each."""
    from .hardware import all_devices

    src = """
    __kernel void light(__global float* x, __global float* y, float a) {
        int i = get_global_id(0);
        y[i] = a * x[i] + y[i];
    }
    """
    devs = all_devices().tpus() or all_devices().cpus().subset(1)
    # ckprove flag fix (partial-safe advisory): the light kernel reads
    # x only at [i], so each lane needs only its slice — the old full
    # read paid whole-array H2D per lane per dispatch in a benchmark
    # whose entire point is per-dispatch cost.  Bit-identity with the
    # full read is pinned by test_partial_read_fix_is_bit_identical.
    x = ClArray(np.arange(n, dtype=np.float32), name="mx",
                partial_read=True, read_only=True)
    y = ClArray(n, np.float32, name="my", partial_read=True)
    cr = NumberCruncher(devs, src)
    out: dict = {"dispatches": dispatches}
    try:
        cr.enqueue_mode = True
        for label, markers in (("markers_off", False), ("markers_on", True)):
            cr.fine_grained_queue_control = markers
            # warm (compile + caches), then measure the dispatch loop only
            for _ in range(8):
                x.next_param(y).compute(cr, 501, "light", n, 256, values=(1.0,))
            cr.barrier()
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(dispatches):
                    x.next_param(y).compute(
                        cr, 501, "light", n, 256, values=(1.0,)
                    )
                dt = (time.perf_counter() - t0) / dispatches
                cr.barrier()
                best = min(best, dt)
            out[label + "_us"] = round(best * 1e6, 1)
            if markers:
                cr.count_markers_remaining()  # exercise the query path
        out["marker_cost_us"] = round(
            out["markers_on_us"] - out["markers_off_us"], 1
        )
        out["reference_claim_us"] = "light-kernel gap 2-3 -> 150-200 (ClNumberCruncher.cs:79)"
    finally:
        cr.enqueue_mode = False
        cr.dispose()
    return out


def dispatch_floor_sweep(
    devices: Devices | None = None,
    ks: Sequence[int] = (1, 8, 32, 128),
    n: int = 1 << 14,
    local_range: int = 256,
    reps: int = 3,
    modes: Sequence[bool] = (False, True),
) -> dict:
    """Per-dispatch overhead vs enqueue-window size K, per-iteration vs
    FUSED dispatch — the measurement behind the dispatch-floor collapse
    (bench.py ``dispatch_floor`` section, tools/dispatch_floor.py CLI).

    Methodology: a light kernel (device work negligible next to the
    dispatch floor) runs windows of K computes + one barrier under the
    span tracer; per row the BEST of ``reps`` windows reports

    - ``per_dispatch_ms`` — (window wall − barrier fence) / K: the host
      cost each compute call pays.  On the per-iteration path this is
      the floor the tunnel charges ~K times per window; on the fused
      path calls 2..K are counter increments and the ladder dispatches
      in batches, so it collapses toward wall/K of a few batched
      launches;
    - ``launch_spans`` / ``launch_ms`` — actual ladder dispatches seen
      by the tracer (the O(K) → O(K/fused_batch) evidence);
    - ``fence_ms`` — the barrier's fence span (excluded from the floor:
      it is the sync cost, not the dispatch cost; note the fused path
      dispatches late, so its fence absorbs device drain the
      per-iteration path paid during the window);
    - ``fused_windows`` — fused ladder flushes inside the window.

    Every row keeps the spans' own counts next to the derived number so
    a regression names its factor instead of hiding in an average."""
    from .hardware import all_devices
    from .trace.attribution import window_report
    from .trace.spans import TRACER

    src = """
    __kernel void light(__global float* x) {
        int i = get_global_id(0);
        x[i] = x[i] + 1.0f;
    }
    """
    devs = devices if devices is not None else (
        all_devices().tpus() or all_devices().cpus()
    )
    devs = devs.subset(1)  # the floor is per-lane host cost; 1 lane is clean
    out: dict = {
        "n": n,
        "reps": reps,
        "note": (
            "per_dispatch_ms = (window wall - barrier fence)/K, best of "
            f"{reps} windows; light kernel, device work negligible. "
            "fused rows defer calls 2..K and dispatch batched ladders — "
            "launch_spans is the dispatch-count evidence; their fence "
            "absorbs device drain the per-iteration path paid mid-window"
        ),
        "rows": [],
    }
    for fused in modes:
        cr = NumberCruncher(devs, src)
        cr.fused_dispatch = fused
        x = ClArray(np.zeros(n, np.float32), name="df", partial_read=True)
        was_tracing = TRACER.enabled
        try:
            cr.enqueue_mode = True
            # warm: compile both the per-call ladder and (fused mode) the
            # fused executable outside every timed window
            for _ in range(3):
                x.compute(cr, 551, "light", n, local_range)
            cr.barrier()
            if not was_tracing:
                TRACER.enable(clear=True)
            for K in ks:
                best = None
                for _ in range(max(1, reps)):
                    w0 = cr.cores.fused_stats["windows"]
                    t0 = time.perf_counter()
                    for _ in range(K):
                        x.compute(cr, 551, "light", n, local_range)
                    cr.barrier()
                    t1 = time.perf_counter()
                    rep = window_report(
                        TRACER.spans_between(t0, t1), t0, t1
                    )
                    fence = rep.per_kind.get("fence", {"ms": 0.0})["ms"]
                    launch = rep.per_kind.get(
                        "launch", {"ms": 0.0, "count": 0}
                    )
                    wall_ms = (t1 - t0) * 1e3
                    row = {
                        "fused": bool(fused),
                        "K": K,
                        "wall_ms": round(wall_ms, 3),
                        "fence_ms": round(fence, 3),
                        "per_dispatch_ms": round(
                            max(wall_ms - fence, 0.0) / K, 4
                        ),
                        "launch_spans": launch.get("count", 0),
                        "launch_ms": round(launch["ms"], 3),
                        "fused_windows": (
                            cr.cores.fused_stats["windows"] - w0
                        ),
                    }
                    if best is None or row["per_dispatch_ms"] < best[
                        "per_dispatch_ms"
                    ]:
                        best = row
                out["rows"].append(best)
            cr.enqueue_mode = False
        finally:
            if not was_tracing:
                TRACER.disable()
            if cr.enqueue_mode:
                cr.enqueue_mode = False
            cr.dispose()
    # headline ratio: the floor collapse at the largest K
    k_max = max(ks)
    per = {
        (r["fused"], r["K"]): r["per_dispatch_ms"] for r in out["rows"]
    }
    if (False, k_max) in per and (True, k_max) in per:
        out["floor_collapse_at_kmax"] = round(
            per[(False, k_max)] / max(per[(True, k_max)], 1e-6), 2
        )
    return out


def fori_chain_bench(step, args, reps, trials=3, rtt=0.0, carry=None):
    """Per-step seconds for ``step(*args) -> pytree``, tunnel-robustly.

    The one dependent-chain harness (shared by bench.py's flash faceoff
    and the tools/ sweeps — the elision traps were each found once and
    must stay fixed in ONE place):

    - the chain runs INSIDE one jitted ``lax.fori_loop`` (a python loop
      of dispatches measures the link's per-launch latency, ~RTT each on
      a bad day); each iteration feeds EVERY output leaf back into the
      carry — when the output leaves pair up with the carry by shape
      (e.g. grads (dq, dk, dv) against (q, k, v)) each input is
      perturbed by its own gradient, otherwise every same-shaped carry
      takes the leading leaf.  Feeding back only one leaf would let XLA
      dead-code-eliminate the computations producing the others (the dkv
      backward kernel, the dense dk/dv einsums) right out of the loop;
    - ``carry`` overrides the feedback rule: ``carry(c, out) -> tuple``
      for steps whose natural chaining is structural (e.g. a stencil's
      output becomes the next input) rather than perturbative;
    - trials are THEMSELVES chained (each consumes the previous trial's
      carry): re-dispatching identical args gets elided by the transport
      — observed printing f32 rows above the f32 MXU roofline;
    - the fence materializes 16 bytes sliced DEVICE-side (np.asarray on
      a full output would measure the link's drifting bandwidth);
    - the measured ``rtt`` is subtracted once, floored at 5% of wall.
    """
    import jax
    from jax import lax

    @jax.jit
    def chain(*a):
        def body(_, c):
            out = step(*c)
            if carry is not None:
                return tuple(carry(c, out))
            leaves = jax.tree_util.tree_leaves(out)
            if len(leaves) == len(c) and all(
                l.shape == x.shape for l, x in zip(leaves, c)
            ):
                return tuple(
                    x + 1e-6 * l.astype(x.dtype)
                    for x, l in zip(c, leaves)
                )
            # fallback: every same-shaped carry takes the LEADING leaf —
            # sound ONLY when that covers every output leaf.  A step with
            # extra output leaves (they'd be dropped → the computations
            # producing them DCE right out of the loop), no output leaves
            # at all, or a lead that matches no carry (the whole step
            # DCEs) is the exact elision trap this harness exists to
            # prevent — refuse loudly instead of silently benchmarking a
            # subset (ADVICE r5 #5)
            fed = (
                [x.shape == leaves[0].shape for x in c] if leaves else []
            )
            if len(leaves) != 1 or not any(fed):
                raise ValueError(
                    "fori_chain_bench fallback feedback would leave output "
                    f"leaves DCE-able: {len(leaves)} output leaf(s) vs "
                    f"{len(c)} carry leaf(s), shapes do not pair and only "
                    "the leading leaf would feed back — pass carry=(c, out)"
                    " -> tuple to define the chaining explicitly"
                )
            lead = leaves[0]
            return tuple(
                x + 1e-6 * lead.astype(x.dtype)
                if x.shape == lead.shape else x
                for x in c
            )
        return lax.fori_loop(0, reps, body, a)

    def fence(x):
        np.asarray(x[tuple(0 for _ in x.shape[:-1])][:4])

    c = tuple(chain(*args))
    fence(c[0])
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        out = tuple(chain(*c))
        fence(out[0])
        wall = time.perf_counter() - t0
        best = min(best, max(wall - rtt, wall * 0.05) / reps)
        c = out
    return best


def dtype_lowering_matrix(
    n: int = 4096,
    local_range: int = 256,
    budget_sec: float = 420.0,
) -> dict:
    """Systematic dtype × lowering × mode sweep ON THE CURRENT BACKEND
    (VERDICT r4 #5): the reference's Tester type grid
    (Tester.cs:6763-7065) as a driver-runnable gate, so the next
    Mosaic-only dtype break is a table cell, not a hand discovery.

    Per cell, a generator kernel ``b[i] = (ct)2 * a[i] + (ct)3`` declared
    in the dtype's ctype is compiled and matched against the numpy oracle
    computed in the same dtype:

    - ``xla`` / ``pallas``: the two kernel-language lowerings directly
      (Pallas with ``force=True`` — the routing veto is itself a recorded
      outcome, not an error);
    - ``harness``: the full ``compute()`` path (NumberCruncher + ClArray
      of the dtype) with the blob pipeline enabled.

    Cell outcomes: ``pass`` (matched the dtype-true oracle), ``pass-x32``
    (64-bit dtype in an x32 process — matched the x32-canonicalized
    oracle, the documented real-TPU regime), ``veto`` (PallasUnsupported:
    the measured routing policy refused, e.g. f16 off Mosaic),
    ``fail: <err>`` otherwise; cells after the soft ``budget_sec`` are
    ``skipped`` (a partial table beats a dead artifact).  The two
    ``mixed-*`` rows drive the r4 boundary contract (storage dtype ≠
    declared ctype: f16/bf16 arrays into a float-declared kernel)."""
    import jax
    import jax.numpy as jnp
    import ml_dtypes

    from .kernel import codegen, lang
    from .kernel.pallas_backend import PallasUnsupported, build_kernel_fn_pallas

    x64 = bool(jax.config.jax_enable_x64)
    rows = [
        # (label, storage numpy dtype, declared ctype)
        ("int8", np.int8, "char"),
        ("uint8", np.uint8, "uchar"),
        ("int16", np.int16, "short"),
        ("int32", np.int32, "int"),
        ("uint32", np.uint32, "uint"),
        ("int64", np.int64, "long"),
        ("float32", np.float32, "float"),
        ("float64", np.float64, "double"),
        ("float16", np.float16, "half"),
        ("bfloat16", ml_dtypes.bfloat16, "float"),   # mixed-boundary row
        ("mixed-f16-float", np.float16, "float"),    # mixed-boundary row
    ]
    t_start = time.monotonic()
    table: dict = {label: {} for label, _, _ in rows}

    def oracle(a_host, storage, ct):
        # compute in the declared type, store back in the storage type —
        # the boundary contract (kernel/codegen.py _loaded/_store)
        decl_np = {
            "char": np.int8, "uchar": np.uint8, "short": np.int16,
            "int": np.int32, "uint": np.uint32, "long": np.int64,
            "float": np.float32, "double": np.float64, "half": np.float16,
        }[ct]
        if not x64 and decl_np in (np.int64, np.float64):
            decl_np = np.int32 if decl_np is np.int64 else np.float32
        acc = a_host.astype(decl_np) * decl_np(2) + decl_np(3)
        return acc.astype(storage)

    def prep(label, storage, ct):
        src = (
            f"__kernel void gen(__global {ct}* a, __global {ct}* b) "
            "{ int i = get_global_id(0); "
            f"b[i] = (({ct})2) * a[i] + (({ct})3); }}"
        )
        kdef = {k.name: k for k in lang.parse_kernels(src)}["gen"]
        rng = np.random.default_rng(7)
        a_host = rng.integers(0, 10, n).astype(storage)
        want = oracle(a_host, storage, ct)
        sdt = np.dtype(storage)
        want_x32 = want
        if not x64 and sdt.itemsize == 8:
            # the x32 process canonicalizes 64-bit payloads on device
            want_x32 = want.astype(
                np.int32 if sdt.kind in "iu" else np.float32
            )

        def match(got) -> str:
            got = np.asarray(got)
            ref = want_x32 if got.dtype != sdt else want
            if got.dtype == ref.dtype and np.array_equal(got, ref):
                return "pass" if got.dtype == sdt else "pass-x32"
            # SUB-32-bit float storage only (f16/bf16 and the mixed
            # rows): declared-type arithmetic may round differently on
            # the VPU — accept small error there.  f32/f64 cells compute
            # 2*a+3 on small ints, exactly representable, and must be
            # EXACT (ADVICE r5 #1: a 2%-wrong f32 cell must not 'pass').
            sub32_float = (
                np.issubdtype(ref.dtype, np.floating)
                and ref.dtype.itemsize < 4
            ) or str(ref.dtype) == "bfloat16"
            if sub32_float:
                err = np.abs(
                    got.astype(np.float64) - ref.astype(np.float64)
                ).max()
                tol = max(np.abs(ref.astype(np.float64)).max(), 1.0) * 2e-2
                if err <= tol:
                    return ("pass" if got.dtype == sdt else "pass-x32")
            return f"fail: mismatch (got {got.dtype}, want {ref.dtype})"

        return src, kdef, a_host, storage, match, label

    def lowered_cell(build, p):
        src, kdef, a_host, storage, match, label = p
        fn, _ = build(kdef, n, local_range, n)
        arrs = (jnp.asarray(a_host), jnp.zeros(n, jnp.asarray(a_host).dtype))
        out = jax.jit(fn)(0, arrs, ())
        return match(out[1])

    xla_cell = functools.partial(lowered_cell, codegen.build_kernel_fn)
    pallas_cell = functools.partial(
        lowered_cell,
        functools.partial(build_kernel_fn_pallas, force=True),
    )

    def harness_cell(p):
        from .hardware import all_devices

        src, kdef, a_host, storage, match, label = p
        devs = all_devices()
        devs = devs.tpus() or devs.cpus().subset(1)
        a = ClArray(a_host.copy(), name=f"dm_a_{label}",
                    partial_read=True, read_only=True)
        b = ClArray(np.zeros(n, storage), name=f"dm_b_{label}",
                    write_only=True)
        cr = NumberCruncher(devs, src)
        try:
            a.next_param(b).compute(
                cr, 7300, "gen", n, local_range,
                pipeline=True, pipeline_blobs=4,
            )
            return match(b.host())
        finally:
            cr.dispose()

    prepped = {label: prep(label, storage, ct) for label, storage, ct in rows}
    # MODE-major iteration: when the budget bites mid-sweep, full dtype
    # coverage of the earlier lowerings survives and only the trailing
    # mode column degrades — losing whole dtype ROWS (the r5 first cut's
    # dtype-major order) throws away exactly the breadth the table is for
    for mode, cell in (("xla", xla_cell), ("pallas", pallas_cell),
                       ("harness_pipelined", harness_cell)):
        for label, _, _ in rows:
            if time.monotonic() - t_start > budget_sec:
                table[label][mode] = "skipped (budget)"
                continue
            try:
                table[label][mode] = cell(prepped[label])
            except PallasUnsupported as e:
                table[label][mode] = f"veto: {e}"[:80]
            except Exception as e:  # noqa: BLE001 - the cell IS the report
                table[label][mode] = f"fail: {type(e).__name__}: {e}"[:120]

    n_pass = sum(
        1 for r in table.values() for v in r.values()
        if str(v).startswith("pass")
    )
    n_veto = sum(
        1 for r in table.values() for v in r.values()
        if str(v).startswith("veto")
    )
    n_fail = sum(
        1 for r in table.values() for v in r.values()
        if str(v).startswith("fail")
    )
    return {
        "backend": jax.default_backend(),
        "x64": x64,
        "cells_pass": n_pass,
        "cells_veto": n_veto,
        "cells_fail": n_fail,
        "table": table,
    }


def duplex_ceiling(n: int = 1 << 22, reps: int = 3) -> dict:
    """Host-link duplex capacity: pure H2D ∥ D2H with NO compute, against
    each direction alone — the physical ceiling for read/write overlap
    that the pipeline engines can never beat (VERDICT r3 #2: if this is
    < 0.9, achieved overlap must be judged against IT, not against 1.0).

    ceiling = (h2d + d2h - duplex) / (h2d + d2h - max(h2d, d2h)):
    1.0 = the link runs both directions concurrently at full rate;
    0.0 = fully serial link.  Fresh values every rep (a mutated host
    array for H2D, a freshly computed device array for D2H) so no
    transport/runtime cache can elide a transfer; RTT subtracted."""
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    host_a = np.arange(n, dtype=np.float32)
    base = jax.device_put(jnp.zeros(n, jnp.float32), dev)
    jax.block_until_ready(base)
    rtt = measure_rtt()
    k = [0]

    def fresh_host():
        k[0] += 1
        host_a[0] = k[0]
        return host_a

    def fresh_dev():
        k[0] += 1
        y = base + np.float32(k[0])
        jax.block_until_ready(y)
        return y

    def sub_rtt(wall):
        # floor at 5% of wall: an RTT sample larger than the transfer must
        # not produce nonpositive times (same discipline as the faceoff
        # chains), which would otherwise print absurd GB/s and push the
        # ceiling outside [0, 1]
        return max(wall - rtt, wall * 0.05)

    def t_h2d_once():
        h = fresh_host()
        t0 = time.perf_counter()
        jax.block_until_ready(jax.device_put(h, dev))
        return sub_rtt(time.perf_counter() - t0)

    def t_d2h_once():
        y = fresh_dev()
        t0 = time.perf_counter()
        np.asarray(y)
        return sub_rtt(time.perf_counter() - t0)

    def t_duplex_once():
        y = fresh_dev()
        h = fresh_host()
        t0 = time.perf_counter()
        x = jax.device_put(h, dev)  # async H2D
        np.asarray(y)               # D2H
        jax.block_until_ready(x)
        return sub_rtt(time.perf_counter() - t0)

    h2d = min(t_h2d_once() for _ in range(reps))
    d2h = min(t_d2h_once() for _ in range(reps))
    dup = min(t_duplex_once() for _ in range(reps))
    denom = h2d + d2h - max(h2d, d2h)
    ceiling = (h2d + d2h - dup) / denom if denom > 0 else 0.0
    ceiling = min(max(ceiling, 0.0), 1.0)  # jitter must not report >1
    gb = n * 4 / 1e9
    return {
        "h2d_ms": round(h2d * 1e3, 1),
        "d2h_ms": round(d2h * 1e3, 1),
        "duplex_ms": round(dup * 1e3, 1),
        "h2d_gbps": round(gb / max(h2d, 1e-9), 3),
        "d2h_gbps": round(gb / max(d2h, 1e-9), 3),
        "ceiling": round(ceiling, 3),
        "rtt_ms": round(rtt * 1e3, 1),
        "bytes": n * 4,
    }
