"""Benchmark/validation workloads: mandelbrot, n-body, streaming vector add.

The reference ships these as its demo/benchmark set — ``Tester.nBody``
(Tester.cs:7682-7799, also the device-ranking micro-benchmark used by
``devicesWithHighestDirectNbodyPerformance``, ClObjectApi.cs:1222-1244),
``stream_C_equals_A_plus_B_1M_elements`` (Tester.cs:7806-7843), and a
mandelbrot demo distributed only as a Windows binary
(mandelbrot_bench_v4.rar).  Here they are first-class workloads written in
the kernel language, with host reference implementations for self-checking
(the reference's ±0.01f nBody tolerance pattern) and timing helpers that
feed BASELINE.md's metrics: Mpixels/sec, load-balance convergence
iterations.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from .arrays.clarray import ClArray
from .core.cruncher import NumberCruncher
from .hardware import Devices

__all__ = [
    "MANDELBROT_SRC",
    "NBODY_SRC",
    "STREAM_SRC",
    "mandelbrot_host",
    "nbody_host_step",
    "MandelbrotResult",
    "run_mandelbrot",
    "run_nbody",
    "run_stream",
    "convergence_iterations",
]


# One pixel per work item; escape-iteration count written as float so a
# single dtype covers TPU (no int32 penalty) and matches the reference demo's
# colorable output.
MANDELBROT_SRC = """
__kernel void mandelbrot(__global float* out,
                         float x0, float y0, float dx, float dy,
                         int width, int maxIter) {
    int i = get_global_id(0);
    float cx = x0 + dx * (float)(i % width);
    float cy = y0 + dy * (float)(i / width);
    float zx = 0.0f;
    float zy = 0.0f;
    int it = 0;
    while (zx*zx + zy*zy < 4.0f && it < maxIter) {
        float t = zx*zx - zy*zy + cx;
        zy = 2.0f*zx*zy + cy;
        zx = t;
        it++;
    }
    out[i] = (float)it;
}
"""

# Direct O(n^2) gravity step (reference: Tester.nBody kernel shape,
# Tester.cs:7682-7799).  Positions are read whole on every chip; velocities
# are updated only for the chip's own range slice.
NBODY_SRC = """
__kernel void nBody(__global float* x, __global float* y, __global float* z,
                    __global float* vx, __global float* vy, __global float* vz,
                    int n, float dt) {
    int i = get_global_id(0);
    float ax = 0.0f;
    float ay = 0.0f;
    float az = 0.0f;
    float xi = x[i];
    float yi = y[i];
    float zi = z[i];
    for (int j = 0; j < n; j++) {
        float ddx = x[j] - xi;
        float ddy = y[j] - yi;
        float ddz = z[j] - zi;
        float r2 = ddx*ddx + ddy*ddy + ddz*ddz + 0.0001f;
        float inv = 1.0f / (r2 * sqrt(r2));
        ax += ddx * inv;
        ay += ddy * inv;
        az += ddz * inv;
    }
    vx[i] += ax * dt;
    vy[i] += ay * dt;
    vz[i] += az * dt;
}
"""

# Streaming c = a + b (reference: Tester.cs:7806-7843, PIPELINE_DRIVER,
# zero-copy inputs).
STREAM_SRC = """
__kernel void streamAdd(__global float* a, __global float* b, __global float* c) {
    int i = get_global_id(0);
    c[i] = a[i] + b[i];
}
"""

# Compute-heavy stream: per-element iteration loop so blob compute time is
# commensurate with blob transfer time — the regime where the pipeline
# engines' read/compute/write overlap is actually measurable (on a slow
# host link, plain streamAdd is ~99% transfer and overlap is unobservable).
# The accumulation is EXACT in f32 (quarter-integer partial sums well below
# 2^24), so the result has a closed form the caller can assert against —
# a decaying recurrence has f32 fixed points a float64 model cannot predict.
STREAM_HEAVY_SRC = """
__kernel void streamHeavy(__global float* a, __global float* b, __global float* c,
                          int iters) {
    int i = get_global_id(0);
    float acc = a[i];
    for (int k = 0; k < iters; k++) {
        acc = acc + b[i] * 0.25f;
    }
    c[i] = acc;
}
"""


def mandelbrot_pallas_kernel(interpret: bool | None = None):
    """The mandelbrot workload as a raw-Pallas :class:`PythonKernel` —
    the hand-tiled hot path (ops/mandelbrot.py) plugged into the same
    compute()/balancer machinery as the C-subset kernel.

    ``interpret`` must be True when the kernel will run on CPU devices
    (the default-backend autodetect can't see which chips the scheduler
    dispatches to)."""
    import jax.lax

    from .kernel.registry import kernel
    from .ops.mandelbrot import mandelbrot_pallas

    @kernel(name="mandelbrot", static_values=True)
    def mandelbrot(gid, out, x0=0.0, y0=0.0, dx=0.0, dy=0.0, width=0, maxIter=0):
        chunk = gid.shape[0]
        piece = mandelbrot_pallas(
            chunk, x0, y0, dx, dy, width, maxIter, offset=gid[0],
            interpret=interpret,
        )
        if out.shape[0] == chunk:
            # whole-buffer launch (single chip, no blobbing): the result IS
            # the buffer — skip the read-modify-write update pass (~16% of
            # the headline iteration on v5e)
            return piece
        return jax.lax.dynamic_update_slice(out, piece, (gid[0],))

    return mandelbrot


def mandelbrot_host(
    width: int, height: int, x0: float, y0: float, dx: float, dy: float, max_iter: int
) -> np.ndarray:
    """Host reference implementation (vectorized numpy) for self-checking."""
    # all arithmetic in f32, matching the kernel's single-precision orbit
    px = np.arange(width * height, dtype=np.int64)
    cx = np.float32(x0) + np.float32(dx) * (px % width).astype(np.float32)
    cy = np.float32(y0) + np.float32(dy) * (px // width).astype(np.float32)
    zx = np.zeros_like(cx)
    zy = np.zeros_like(cy)
    it = np.zeros(width * height, dtype=np.int32)
    active = np.ones(width * height, dtype=bool)
    for _ in range(max_iter):
        zx2 = zx * zx
        zy2 = zy * zy
        active = active & (zx2 + zy2 < 4.0)
        if not active.any():
            break
        t = zx2 - zy2 + cx
        zy = np.where(active, 2.0 * zx * zy + cy, zy)
        zx = np.where(active, t, zx)
        it = it + active.astype(np.int32)
    return it.astype(np.float32)


def nbody_host_step(x, y, z, vx, vy, vz, dt: float):
    """Host reference for one nBody velocity update (numpy O(n^2))."""
    xs = x.astype(np.float64)
    ys = y.astype(np.float64)
    zs = z.astype(np.float64)
    ddx = xs[None, :] - xs[:, None]
    ddy = ys[None, :] - ys[:, None]
    ddz = zs[None, :] - zs[:, None]
    r2 = ddx * ddx + ddy * ddy + ddz * ddz + 0.0001
    inv = 1.0 / (r2 * np.sqrt(r2))
    vx2 = vx + (ddx * inv).sum(axis=1).astype(np.float32) * dt
    vy2 = vy + (ddy * inv).sum(axis=1).astype(np.float32) * dt
    vz2 = vz + (ddz * inv).sum(axis=1).astype(np.float32) * dt
    return vx2, vy2, vz2


@dataclass
class MandelbrotResult:
    mpixels_per_sec: float
    per_iter_ms: list[float] = field(default_factory=list)
    ranges_per_iter: list[list[int]] = field(default_factory=list)
    convergence_iters: int | None = None
    image: np.ndarray | None = None


def run_mandelbrot(
    devices: Devices | None = None,
    width: int = 2048,
    height: int = 2048,
    max_iter: int = 256,
    iters: int = 12,
    warmup: int = 2,
    pipeline: bool = False,
    pipeline_blobs: int = 8,
    local_range: int = 256,
    keep_image: bool = False,
    cruncher: NumberCruncher | None = None,
    use_pallas: bool = False,
    readback: str = "every",
    sync_every: int = 1,
) -> MandelbrotResult:
    """Timed, load-balanced mandelbrot over all selected chips.

    ``use_pallas`` swaps the kernel-language program for the hand-tiled
    Pallas kernel (same name, same compute path).  ``readback="final"``
    runs in enqueue mode — the image stays in HBM, iterations sync to a
    device barrier every ``sync_every`` steps (amortizing per-sync latency
    on tunneled backends), and one flush at the end writes the host array
    (the device-throughput view; "every" includes a full D2H per
    iteration).
    Returns Mpixels/sec over the timed iterations plus per-iteration wall
    times and the balancer's range trajectory (for the convergence metric
    in BASELINE.md).
    """
    from .hardware import all_devices

    own = cruncher is None
    devs = devices or all_devices()
    if use_pallas:
        source = mandelbrot_pallas_kernel(
            interpret=not all(d.is_tpu for d in devs)
        )
    else:
        source = MANDELBROT_SRC
    cr = cruncher or NumberCruncher(devs, source)
    n = width * height
    out = ClArray(n, np.float32, name="mandel_out", read=False, write=True)
    vals = (-2.0, -1.25, 2.5 / width, 2.5 / height, width, max_iter)
    per_iter: list[float] = []
    ranges: list[list[int]] = []
    if readback == "final":
        cr.enqueue_mode = True
    try:
        for k in range(warmup + iters):
            t0 = time.perf_counter()
            out.compute(
                cr, 7001, "mandelbrot", n, local_range,
                pipeline=pipeline, pipeline_blobs=pipeline_blobs, values=vals,
            )
            last = k == warmup + iters - 1
            if readback == "final" and ((k + 1) % sync_every == 0 or last):
                cr.barrier()
            dt_ms = (time.perf_counter() - t0) * 1000.0
            ranges.append(cr.ranges_of(7001))
            if k >= warmup:
                per_iter.append(dt_ms)
            elif k == warmup - 1 and readback == "final":
                # fence: warmup dispatches must retire OUTSIDE the timed
                # window or their device time deflates the metric
                cr.barrier()
        mpix = (n * len(per_iter)) / (sum(per_iter) / 1000.0) / 1e6
        step = local_range * (pipeline_blobs if pipeline else 1)
        if readback == "final":
            cr.enqueue_mode = False  # flush: one readback for the image
        return MandelbrotResult(
            mpixels_per_sec=mpix,
            per_iter_ms=per_iter,
            ranges_per_iter=ranges,
            convergence_iters=_converged_at(ranges, step),
            image=out.host().reshape(height, width).copy() if keep_image else None,
        )
    finally:
        # never leave a caller-supplied cruncher stuck in enqueue mode
        # (deferred readbacks would silently stop updating host arrays)
        if cr.enqueue_mode:
            try:
                cr.enqueue_mode = False
            except Exception:
                pass
        if own:
            cr.dispose()


def _converged_at(ranges: list[list[int]], step: int) -> int | None:
    """First iteration index after which every later re-balance moves no
    share by more than ``step`` (BASELINE.md convergence metric)."""
    for k in range(1, len(ranges)):
        if all(
            max(abs(a - b) for a, b in zip(ranges[j], ranges[j - 1])) <= step
            for j in range(k, len(ranges))
        ):
            return k
    return None


def run_nbody(
    devices: Devices | None = None,
    n: int = 8192,
    iters: int = 10,
    dt: float = 0.0001,
    local_range: int = 256,
    check: bool = True,
    tolerance: float = 0.01,
    use_jnp: bool = False,
) -> dict:
    """Load-balanced n-body velocity updates; self-checks the first step
    against the host O(n^2) reference within ``tolerance`` (the reference's
    ±0.01f pattern, Tester.cs:7682-7799).

    ``use_jnp`` swaps the C-subset kernel for the fused-XLA fast path
    (ops/nbody.py) — same name, same compute()/balancer machinery, the
    per-j gather loop replaced by one pairwise tile program."""
    from .hardware import all_devices

    rng = np.random.default_rng(42)
    pos = (rng.random((3, n), dtype=np.float32) - 0.5) * 2.0
    x = ClArray(pos[0].copy(), name="x", read_only=True)
    y = ClArray(pos[1].copy(), name="y", read_only=True)
    z = ClArray(pos[2].copy(), name="z", read_only=True)
    vel = [ClArray(n, np.float32, name=f"v{c}", partial_read=True) for c in "xyz"]
    expected = None
    if check:
        expected = nbody_host_step(
            pos[0], pos[1], pos[2],
            np.zeros(n, np.float32), np.zeros(n, np.float32), np.zeros(n, np.float32),
            dt,
        )
    if use_jnp:
        from .ops.nbody import nbody_jnp_kernel

        source = nbody_jnp_kernel()
    else:
        source = NBODY_SRC
    cr = NumberCruncher(devices or all_devices(), source)
    group = x.next_param(y, z, *vel)
    times: list[float] = []
    try:
        for k in range(iters):
            t0 = time.perf_counter()
            group.compute(cr, 7002, "nBody", n, local_range, values=(n, dt))
            times.append((time.perf_counter() - t0) * 1000.0)
            if k == 0 and check and expected is not None:
                for got, want, label in zip(vel, expected, "xyz"):
                    err = float(np.abs(got.host() - want).max())
                    if err > tolerance:
                        raise AssertionError(
                            f"nBody v{label} mismatch: max err {err} > {tolerance}"
                        )
        pairs_per_sec = n * n * len(times[1:]) / (sum(times[1:]) / 1000.0 + 1e-12)
        return {
            "n": n,
            "per_iter_ms": times,
            "gpairs_per_sec": pairs_per_sec / 1e9,
            "checked": bool(check),
        }
    finally:
        cr.dispose()


def run_stream(
    devices: Devices | None = None,
    n: int = 1 << 20,
    reps: int = 10,
    blobs: int = 8,
    local_range: int = 256,
    fast: bool = True,
) -> dict:
    """Streaming c = a + b with the driver-pipeline analogue
    (reference: Tester.cs:7806-7843 — 1M floats, 8 blobs, 10 reps,
    zero-copy FastArr inputs)."""
    from .hardware import all_devices

    a = ClArray(n, np.float32, name="a", fast=fast, partial_read=True, read_only=True, zero_copy=fast)
    b = ClArray(n, np.float32, name="b", fast=fast, partial_read=True, read_only=True, zero_copy=fast)
    c = ClArray(n, np.float32, name="c", fast=fast, write_only=True)
    a.host()[:] = np.arange(n, dtype=np.float32) % 97
    b.host()[:] = np.arange(n, dtype=np.float32) % 89
    cr = NumberCruncher(devices or all_devices(), STREAM_SRC)
    group = a.next_param(b, c)
    times: list[float] = []
    try:
        for _ in range(reps):
            t0 = time.perf_counter()
            group.compute(cr, 7003, "streamAdd", n, local_range, pipeline=True, pipeline_blobs=blobs)
            times.append((time.perf_counter() - t0) * 1000.0)
        want = a.host() + b.host()
        if not np.allclose(c.host(), want):
            raise AssertionError("stream add mismatch")
        best = min(times)
        # 3 arrays × 4 bytes move per element per rep
        return {
            "n": n,
            "per_rep_ms": times,
            "gb_per_sec": (3 * 4 * n) / (best / 1000.0) / 1e9,
        }
    finally:
        cr.dispose()
        for arr in (a, b, c):
            arr.dispose()


def measure_stream_overlap(
    devices: Devices | None = None,
    n: int = 1 << 22,
    blobs: int = 8,
    local_range: int = 256,
    pipeline_type: int | None = None,
    reps: int = 3,
    heavy_iters: int = 0,
) -> dict:
    """Measure the realized read/compute/write overlap fraction of the
    pipelined path on ONE chip (BASELINE.md metric 2; the engineered
    property behind the reference's 3× pipelining claim, Cores.cs:467).

    ``heavy_iters`` > 0 swaps the plain add for a per-element iteration
    kernel so blob compute is commensurate with blob transfer — on a slow
    host link plain streamAdd is ~99% transfer and r/c/w overlap is
    unobservable regardless of scheduling.

    Method (VERDICT r2 #3 — comparable phases, no clipping): ``reps``
    INTERLEAVED rounds, each measuring every phase once (idle fence RTT
    sampled per round and subtracted from fence-terminated phases), and the
    per-phase MEDIAN across rounds is reported — host-link bandwidth
    drifts by ~2x over minutes, so separate multi-rep windows per phase
    let drift masquerade as ±overlap (round-2's isolated phases were
    additionally fence-dominated, making the ratio >1 and meaningless).
    ``sample_spread`` reports max per-phase (max-min)/median so the
    artifact shows how noisy the link was.  With median phase times r, c,
    w and pipelined total p::

        overlap = (r + c + w - p) / (r + c + w - max(r, c, w))

    1.0 = the pipelined total equals the slowest phase (perfect overlap);
    0.0 = fully serial.  The RAW ratio is returned — values < 0 mean
    pipeline overhead exceeded any overlap, values > 1 mean the phase
    decomposition was wrong; neither is hidden.  On tunneled backends the
    device timeline exposes no DMA events (utils/timeline.py), so this
    host-window method with fence-cost subtraction is the honest
    alternative; ``rtt_ms`` is included so the artifact shows the scale of
    what was subtracted.
    """
    from .core.cores import PIPELINE_EVENT
    from .hardware import all_devices

    if pipeline_type is None:
        pipeline_type = PIPELINE_EVENT
    devs = (devices or all_devices()).subset(1)
    kname = "streamHeavy" if heavy_iters else "streamAdd"
    kvals = (heavy_iters,) if heavy_iters else ()
    cr = NumberCruncher(devs, STREAM_HEAVY_SRC if heavy_iters else STREAM_SRC)
    w = cr.cores.workers[0]
    a = ClArray(n, np.float32, name="ov_a", partial_read=True, read_only=True)
    b = ClArray(n, np.float32, name="ov_b", partial_read=True, read_only=True)
    c = ClArray(n, np.float32, name="ov_c", write_only=True)
    a.host()[:] = np.arange(n, dtype=np.float32) % 97
    b.host()[:] = np.arange(n, dtype=np.float32) % 89
    blob = n // blobs

    def fence():
        cr.barrier()

    def phase_read() -> None:
        for arr in (a, b):
            w.invalidate(arr)
        for k in range(blobs):
            for arr in (a, b):
                w.upload(arr, k * blob, blob, False)

    def phase_compute() -> None:
        # data already resident from the last read phase
        w.ensure_resident(c)
        for k in range(blobs):
            w.launch(
                cr.program, [kname], [a, b, c], kvals,
                k * blob, blob, local_range, n, local_range,
            )

    def phase_write() -> None:
        from .core.worker import Worker

        handles = [
            w.download_async(c, k * blob, blob, False) for k in range(blobs)
        ]
        for h in handles:
            Worker.finish_download(h)

    def phase_pipelined() -> None:
        for arr in (a, b, c):
            w.invalidate(arr)
        a.next_param(b, c).compute(
            cr, 7004, kname, n, local_range,
            pipeline=True, pipeline_blobs=blobs, pipeline_type=pipeline_type,
            values=kvals,
        )

    def timed(fn, needs_fence: bool, rtt: float) -> float:
        t0 = time.perf_counter()
        fn()
        if needs_fence:
            fence()
        total = (time.perf_counter() - t0) * 1000.0
        if needs_fence:
            total -= rtt
        return max(total, 1e-6)

    try:
        # warmup: compile + first-touch, and all four paths exercised once
        phase_read()
        phase_compute()
        fence()
        phase_write()
        phase_pipelined()
        # INTERLEAVED rounds (VERDICT-honest methodology note: tunnel
        # bandwidth drifts by 2x over minutes, so measuring each phase in
        # its own multi-rep window lets drift masquerade as ±overlap;
        # round-robin sampling keeps every phase's samples seconds apart
        # and the per-phase MEDIAN cancels the drift)
        samples: dict[str, list[float]] = {"r": [], "c": [], "w": [], "p": [], "rtt": []}
        for _ in range(reps):
            t0 = time.perf_counter()
            fence()
            rtt = (time.perf_counter() - t0) * 1000.0
            samples["rtt"].append(rtt)
            samples["r"].append(timed(phase_read, True, rtt))
            samples["c"].append(timed(phase_compute, True, rtt))
            samples["w"].append(timed(phase_write, False, rtt))
            samples["p"].append(timed(phase_pipelined, False, rtt))

        def med(key: str) -> float:
            vals = sorted(samples[key])
            return vals[len(vals) // 2]

        t_r, t_c, t_w, t_p = med("r"), med("c"), med("w"), med("p")
        serial = t_r + t_c + t_w
        ideal = serial - max(t_r, t_c, t_w)
        overlap = (serial - t_p) / ideal if ideal > 1e-9 else 0.0
        spread = max(
            (max(samples[k]) - min(samples[k])) / max(med(k), 1e-9)
            for k in ("r", "w", "p")
        )
        if heavy_iters:
            # acc = a + iters*(b/4), exact in f32 (quarter-integer sums
            # below 2^24) — the timing numbers are only publishable if the
            # pipelined path computed the right thing
            want = a.host() + heavy_iters * 0.25 * b.host()
            np.testing.assert_allclose(c.host(), want, rtol=1e-6)
        else:
            np.testing.assert_allclose(c.host(), a.host() + b.host())
        return {
            "t_read_ms": t_r,
            "t_compute_ms": t_c,
            "t_write_ms": t_w,
            "t_pipelined_ms": t_p,
            "t_serial_ms": serial,
            "rtt_ms": med("rtt"),
            "overlap_fraction": overlap,  # RAW — see docstring
            "sample_spread": spread,  # >1 = tunnel drift swamps the signal
            "n": n,
            "blobs": blobs,
            "reps": reps,
        }
    finally:
        cr.dispose()


def convergence_iterations(
    devices: Devices | None = None, max_iter: int = 192, width: int = 1024, height: int = 1024
) -> int | None:
    """Measure load-balance convergence on the mandelbrot workload
    (BASELINE.md: 'iterations until max share delta < step')."""
    res = run_mandelbrot(devices, width=width, height=height, max_iter=max_iter, iters=16, warmup=0)
    return res.convergence_iters
