// kutuphane_tpu — native host-side runtime support for cekirdekler_tpu.
//
// TPU-native replacement for the capabilities the reference keeps in its
// C++ KutuphaneCL.dll host-array layer (contract recovered from the P/Invoke
// surface at CSpaceArrays.cs:108-147: sizeOf / createArray / alignedArrHead /
// deleteArray / copyMemory), the command-queue marker counters
// (ClCommandQueue.cs:99-115: addMarkerToCommandQueue /
// getMarkerCounterOfCommandQueue / resetMarkerCounterOfCommandQueue),
// the event objects (ClEvent.cs:30-34 createEvent/deleteEvent;
// ClUserEvent.cs:30-47 createUserEvent/triggerUserEvent/
// incrementUserEvent/decrementUserEvent), and the host side of the async
// copy machinery (ClBuffer.cs:316-475 event-carrying enqueueRead/Write —
// here a worker-thread copy engine whose jobs complete native events).
//
// Provides:
//   * page-aligned host allocations (4096 B like the reference) for
//     fast, DMA-friendly host staging buffers ("FastArr" backing store),
//   * bulk memcpy / fill helpers that release the Python GIL implicitly
//     (plain C calls through ctypes),
//   * condition-variable events with user-event counter semantics,
//   * an async copy engine: N worker threads draining a job queue, each
//     job a memcpy completing an event — host staging copies overlap
//     Python-side work and each other (the GIL is released for the whole
//     ctypes call),
//   * a parallel synchronous copy (range split across the pool) for big
//     D2H writebacks,
//   * atomic marker counters used for fine-grained progress observation by
//     the pool scheduler and enqueue mode,
//   * allocation statistics for leak tests.
//
// Exposed as flat C symbols consumed via ctypes (arrays/fastarr.py,
// native/build.py).  See native/DESIGN.md for the tier boundary: why the
// device path itself stays behind JAX/XLA's PJRT client rather than a
// bespoke PJRT C-API client.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <new>
#include <thread>
#include <vector>

#if defined(_WIN32)
#define EXPORT extern "C" __declspec(dllexport)
#else
#define EXPORT extern "C" __attribute__((visibility("default")))
#endif

namespace {

constexpr std::size_t kDefaultAlignment = 4096;  // page/DMA alignment, matches reference

std::atomic<std::int64_t> g_live_allocations{0};
std::atomic<std::int64_t> g_live_bytes{0};

struct MarkerCounter {
  std::atomic<std::int64_t> added{0};
  std::atomic<std::int64_t> reached{0};
};

std::mutex g_counter_mutex;
std::map<std::int64_t, MarkerCounter*> g_counters;
std::int64_t g_next_counter_id = 1;

}  // namespace

// ---------------------------------------------------------------------------
// element sizes (reference: native `sizeOf`, type codes ARR_FLOAT..ARR_CHAR,
// CSpaceArrays.cs:48-109)
// ---------------------------------------------------------------------------

// type codes — kept numerically identical to the reference's ARR_* constants
// so serialized cluster traffic stays self-describing.
enum TypeCode : int {
  ARR_FLOAT = 0,
  ARR_DOUBLE = 1,
  ARR_INT = 2,
  ARR_LONG = 3,
  ARR_UINT = 4,
  ARR_BYTE = 5,
  ARR_CHAR = 6,
  ARR_BFLOAT16 = 7,  // TPU-native addition
  ARR_BOOL = 8,
};

EXPORT int ck_sizeOf(int type_code) {
  switch (type_code) {
    case ARR_FLOAT: return 4;
    case ARR_DOUBLE: return 8;
    case ARR_INT: return 4;
    case ARR_LONG: return 8;
    case ARR_UINT: return 4;
    case ARR_BYTE: return 1;
    case ARR_CHAR: return 2;  // reference char is UTF-16 (C#); kept for wire parity
    case ARR_BFLOAT16: return 2;
    case ARR_BOOL: return 1;
    default: return -1;
  }
}

// ---------------------------------------------------------------------------
// aligned host allocations (reference: createArray / alignedArrHead /
// deleteArray, CSpaceArrays.cs:119-147)
// ---------------------------------------------------------------------------

EXPORT void* ck_createArray(std::int64_t num_bytes, std::int64_t alignment) {
  if (num_bytes <= 0) return nullptr;
  std::size_t align =
      alignment > 0 ? static_cast<std::size_t>(alignment) : kDefaultAlignment;
  // round the size up so aligned_alloc's size-multiple-of-alignment rule holds
  std::size_t size = static_cast<std::size_t>(num_bytes);
  size = (size + align - 1) / align * align;
  void* p = std::aligned_alloc(align, size);
  if (p != nullptr) {
    g_live_allocations.fetch_add(1, std::memory_order_relaxed);
    g_live_bytes.fetch_add(static_cast<std::int64_t>(size),
                           std::memory_order_relaxed);
    // touch pages now so first DMA doesn't eat soft page faults
    std::memset(p, 0, size);
  }
  return p;
}

// With aligned_alloc the head pointer IS the aligned pointer; kept as a
// separate entry point for contract parity with the reference, where raw and
// aligned heads differ (CSpaceArrays.cs:239-244).
EXPORT void* ck_alignedArrHead(void* raw, std::int64_t alignment) {
  (void)alignment;
  return raw;
}

EXPORT void ck_deleteArray(void* raw, std::int64_t num_bytes,
                           std::int64_t alignment) {
  if (raw == nullptr) return;
  std::size_t align =
      alignment > 0 ? static_cast<std::size_t>(alignment) : kDefaultAlignment;
  std::size_t size = static_cast<std::size_t>(num_bytes > 0 ? num_bytes : 0);
  size = (size + align - 1) / align * align;
  std::free(raw);
  g_live_allocations.fetch_sub(1, std::memory_order_relaxed);
  g_live_bytes.fetch_sub(static_cast<std::int64_t>(size),
                         std::memory_order_relaxed);
}

EXPORT void ck_copyMemory(void* dst, const void* src, std::int64_t num_bytes) {
  if (dst == nullptr || src == nullptr || num_bytes <= 0) return;
  std::memcpy(dst, src, static_cast<std::size_t>(num_bytes));
}

EXPORT void ck_fillMemory(void* dst, int byte_value, std::int64_t num_bytes) {
  if (dst == nullptr || num_bytes <= 0) return;
  std::memset(dst, byte_value, static_cast<std::size_t>(num_bytes));
}

EXPORT std::int64_t ck_liveAllocations() {
  return g_live_allocations.load(std::memory_order_relaxed);
}

EXPORT std::int64_t ck_liveBytes() {
  return g_live_bytes.load(std::memory_order_relaxed);
}

// ---------------------------------------------------------------------------
// marker counters (reference: addMarkerToCommandQueue +
// getMarkerCounterOfCommandQueue + resetMarkerCounterOfCommandQueue,
// ClCommandQueue.cs:39-47,99-115 — native callback counts completions)
// ---------------------------------------------------------------------------

EXPORT std::int64_t ck_createMarkerCounter() {
  std::lock_guard<std::mutex> lock(g_counter_mutex);
  std::int64_t id = g_next_counter_id++;
  g_counters[id] = new MarkerCounter();
  return id;
}

EXPORT void ck_deleteMarkerCounter(std::int64_t id) {
  std::lock_guard<std::mutex> lock(g_counter_mutex);
  auto it = g_counters.find(id);
  if (it != g_counters.end()) {
    delete it->second;
    g_counters.erase(it);
  }
}

namespace {
MarkerCounter* find_counter(std::int64_t id) {
  std::lock_guard<std::mutex> lock(g_counter_mutex);
  auto it = g_counters.find(id);
  return it == g_counters.end() ? nullptr : it->second;
}
}  // namespace

EXPORT void ck_addMarker(std::int64_t id) {
  if (MarkerCounter* c = find_counter(id)) {
    c->added.fetch_add(1, std::memory_order_relaxed);
  }
}

EXPORT void ck_markerReached(std::int64_t id) {
  if (MarkerCounter* c = find_counter(id)) {
    c->reached.fetch_add(1, std::memory_order_relaxed);
  }
}

EXPORT std::int64_t ck_markersAdded(std::int64_t id) {
  MarkerCounter* c = find_counter(id);
  return c ? c->added.load(std::memory_order_relaxed) : -1;
}

EXPORT std::int64_t ck_markersReached(std::int64_t id) {
  MarkerCounter* c = find_counter(id);
  return c ? c->reached.load(std::memory_order_relaxed) : -1;
}

EXPORT std::int64_t ck_markersRemaining(std::int64_t id) {
  MarkerCounter* c = find_counter(id);
  if (c == nullptr) return -1;
  return c->added.load(std::memory_order_relaxed) -
         c->reached.load(std::memory_order_relaxed);
}

EXPORT void ck_resetMarkerCounter(std::int64_t id) {
  if (MarkerCounter* c = find_counter(id)) {
    c->added.store(0, std::memory_order_relaxed);
    c->reached.store(0, std::memory_order_relaxed);
  }
}

// ---------------------------------------------------------------------------
// events (reference: ClEvent.cs:30-34 createEvent/deleteEvent;
// ClUserEvent.cs:30-47 createUserEvent/triggerUserEvent/addUserEvent/
// incrementUserEvent/decrementUserEvent).  A user event is an event with a
// pending counter: it fires when the counter reaches zero (or on an
// explicit trigger), releasing every waiter — the host-gated dispatch
// primitive behind Worker.cs:487-557's synchronized queue start.
// ---------------------------------------------------------------------------

namespace {

struct Event {
  std::mutex m;
  std::condition_variable cv;
  bool fired = false;
  std::int64_t pending = 0;  // user-event counter; fires when it hits 0

  void trigger() {
    {
      std::lock_guard<std::mutex> lock(m);
      fired = true;
    }
    cv.notify_all();
  }

  bool wait(std::int64_t timeout_ms) {
    std::unique_lock<std::mutex> lock(m);
    if (timeout_ms < 0) {
      cv.wait(lock, [this] { return fired; });
      return true;
    }
    return cv.wait_for(lock, std::chrono::milliseconds(timeout_ms),
                       [this] { return fired; });
  }
};

// shared_ptr ownership: find_event returns a reference-holding copy, so a
// waiter blocked inside Event::wait keeps the object alive even if another
// thread deletes the id concurrently — no use-after-free window
std::mutex g_event_mutex;
std::map<std::int64_t, std::shared_ptr<Event>> g_events;
std::int64_t g_next_event_id = 1;

std::shared_ptr<Event> find_event(std::int64_t id) {
  std::lock_guard<std::mutex> lock(g_event_mutex);
  auto it = g_events.find(id);
  return it == g_events.end() ? nullptr : it->second;
}

}  // namespace

EXPORT std::int64_t ck_eventCreate() {
  std::lock_guard<std::mutex> lock(g_event_mutex);
  std::int64_t id = g_next_event_id++;
  g_events[id] = std::make_shared<Event>();
  return id;
}

EXPORT void ck_eventDelete(std::int64_t id) {
  std::shared_ptr<Event> e;
  {
    std::lock_guard<std::mutex> lock(g_event_mutex);
    auto it = g_events.find(id);
    if (it == g_events.end()) return;
    e = it->second;
    g_events.erase(it);
  }
  e->trigger();  // never leave a waiter stuck on a deleted event
  // e's refcount drops when the last waiter returns from wait()
}

EXPORT void ck_eventTrigger(std::int64_t id) {
  if (auto e = find_event(id)) e->trigger();
}

EXPORT int ck_eventFired(std::int64_t id) {
  auto e = find_event(id);
  if (e == nullptr) return -1;
  std::lock_guard<std::mutex> lock(e->m);
  return e->fired ? 1 : 0;
}

// blocks WITHOUT the GIL (ctypes releases it): Python threads keep running
EXPORT int ck_eventWait(std::int64_t id, std::int64_t timeout_ms) {
  auto e = find_event(id);
  if (e == nullptr) return -1;
  return e->wait(timeout_ms) ? 1 : 0;
}

EXPORT void ck_eventIncrement(std::int64_t id) {
  if (auto e = find_event(id)) {
    std::lock_guard<std::mutex> lock(e->m);
    e->pending += 1;
  }
}

EXPORT void ck_eventDecrement(std::int64_t id) {
  auto e = find_event(id);
  if (e == nullptr) return;
  bool fire = false;
  {
    std::lock_guard<std::mutex> lock(e->m);
    e->pending -= 1;
    if (e->pending <= 0 && !e->fired) {
      e->fired = true;
      fire = true;
    }
  }
  if (fire) e->cv.notify_all();
}

EXPORT std::int64_t ck_eventPending(std::int64_t id) {
  auto e = find_event(id);
  if (e == nullptr) return -1;
  std::lock_guard<std::mutex> lock(e->m);
  return e->pending;
}

// ---------------------------------------------------------------------------
// async copy engine (reference: the event-carrying enqueueRead/Write family,
// ClBuffer.cs:316-475 — host-side staging copies run on dedicated threads
// and complete events; the device DMA itself belongs to the PJRT/XLA layer,
// see DESIGN.md)
// ---------------------------------------------------------------------------

namespace {

struct CopyJob {
  void* dst;
  const void* src;
  std::int64_t bytes;
  std::int64_t event_id;   // 0 = none
  bool decrement = false;  // true: decrement the event's counter instead of
                           // triggering it (ck_copyParallel fan-in)
};

class CopyEngine {
 public:
  static CopyEngine& instance() {
    // intentionally leaked: destroying joinable std::threads at static
    // teardown calls std::terminate; process exit reaps them instead
    static CopyEngine* engine = new CopyEngine();
    return *engine;
  }

  void ensure_started(int threads) {
    std::lock_guard<std::mutex> lock(m_);
    if (!workers_.empty()) return;
    int n = threads > 0 ? threads : 4;
    stop_ = false;
    for (int i = 0; i < n; ++i) {
      workers_.emplace_back([this] { run(); });
    }
  }

  void submit(const CopyJob& job) {
    {
      std::lock_guard<std::mutex> lock(m_);
      jobs_.push_back(job);
    }
    cv_.notify_one();
  }

  std::int64_t queued() {
    std::lock_guard<std::mutex> lock(m_);
    return static_cast<std::int64_t>(jobs_.size()) + active_;
  }

  int thread_count() {
    std::lock_guard<std::mutex> lock(m_);
    return static_cast<int>(workers_.size());
  }

 private:
  void run() {
    for (;;) {
      CopyJob job;
      {
        std::unique_lock<std::mutex> lock(m_);
        cv_.wait(lock, [this] { return stop_ || !jobs_.empty(); });
        if (stop_ && jobs_.empty()) return;
        job = jobs_.front();
        jobs_.pop_front();
        ++active_;
      }
      if (job.dst != nullptr && job.src != nullptr && job.bytes > 0) {
        std::memcpy(job.dst, job.src, static_cast<std::size_t>(job.bytes));
      }
      if (job.event_id != 0) {
        if (job.decrement) {
          ck_eventDecrement(job.event_id);
        } else {
          ck_eventTrigger(job.event_id);
        }
      }
      {
        std::lock_guard<std::mutex> lock(m_);
        --active_;
      }
    }
  }

  std::mutex m_;
  std::condition_variable cv_;
  std::deque<CopyJob> jobs_;
  std::vector<std::thread> workers_;
  std::int64_t active_ = 0;
  bool stop_ = false;
};

}  // namespace

EXPORT void ck_copyEngineStart(int threads) {
  CopyEngine::instance().ensure_started(threads);
}

EXPORT int ck_copyEngineThreads() {
  return CopyEngine::instance().thread_count();
}

EXPORT std::int64_t ck_copyEngineQueued() {
  return CopyEngine::instance().queued();
}

// async: returns immediately; triggers event_id (if nonzero) on completion
EXPORT void ck_copyAsync(void* dst, const void* src, std::int64_t num_bytes,
                         std::int64_t event_id) {
  CopyEngine::instance().ensure_started(0);
  CopyEngine::instance().submit(CopyJob{dst, src, num_bytes, event_id});
}

// synchronous parallel copy: the range is split into chunks fanned out to
// the CopyEngine pool (no per-call thread spawn), joined through a
// counting event.  Used for big writebacks — the whole call runs GIL-free
// and saturates host memory bandwidth better than a single memcpy for
// multi-MB slices.
EXPORT void ck_copyParallel(void* dst, const void* src, std::int64_t num_bytes,
                            int threads) {
  if (dst == nullptr || src == nullptr || num_bytes <= 0) return;
  int n = threads > 1 ? threads : 2;
  constexpr std::int64_t kMinChunk = 1 << 20;  // <1 MiB/chunk isn't worth it
  if (num_bytes < 2 * kMinChunk) {
    std::memcpy(dst, src, static_cast<std::size_t>(num_bytes));
    return;
  }
  if (num_bytes / n < kMinChunk) n = static_cast<int>(num_bytes / kMinChunk);
  CopyEngine::instance().ensure_started(0);
  std::int64_t ev = ck_eventCreate();
  for (int i = 0; i < n; ++i) ck_eventIncrement(ev);
  std::int64_t chunk = num_bytes / n;
  for (int i = 0; i < n; ++i) {
    std::int64_t off = i * chunk;
    std::int64_t len = (i == n - 1) ? num_bytes - off : chunk;
    CopyEngine::instance().submit(CopyJob{static_cast<char*>(dst) + off,
                                          static_cast<const char*>(src) + off,
                                          len, ev, /*decrement=*/true});
  }
  ck_eventWait(ev, -1);
  ck_eventDelete(ev);
}

// ABI sanity probe for the ctypes loader.
EXPORT std::int64_t ck_abiVersion() { return 2; }
