// kutuphane_tpu — native host-side runtime support for cekirdekler_tpu.
//
// TPU-native replacement for the capabilities the reference keeps in its
// C++ KutuphaneCL.dll host-array layer (contract recovered from the P/Invoke
// surface at CSpaceArrays.cs:108-147: sizeOf / createArray / alignedArrHead /
// deleteArray / copyMemory) plus the command-queue marker counters
// (ClCommandQueue.cs:99-115: addMarkerToCommandQueue /
// getMarkerCounterOfCommandQueue / resetMarkerCounterOfCommandQueue).
//
// Provides:
//   * page-aligned host allocations (4096 B like the reference) for
//     fast, DMA-friendly host staging buffers ("FastArr" backing store),
//   * bulk memcpy / fill helpers that release the Python GIL implicitly
//     (plain C calls through ctypes),
//   * atomic marker counters used for fine-grained progress observation by
//     the pool scheduler and enqueue mode,
//   * allocation statistics for leak tests.
//
// Exposed as flat C symbols consumed via ctypes (arrays/fastarr.py).

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <new>

#if defined(_WIN32)
#define EXPORT extern "C" __declspec(dllexport)
#else
#define EXPORT extern "C" __attribute__((visibility("default")))
#endif

namespace {

constexpr std::size_t kDefaultAlignment = 4096;  // page/DMA alignment, matches reference

std::atomic<std::int64_t> g_live_allocations{0};
std::atomic<std::int64_t> g_live_bytes{0};

struct MarkerCounter {
  std::atomic<std::int64_t> added{0};
  std::atomic<std::int64_t> reached{0};
};

std::mutex g_counter_mutex;
std::map<std::int64_t, MarkerCounter*> g_counters;
std::int64_t g_next_counter_id = 1;

}  // namespace

// ---------------------------------------------------------------------------
// element sizes (reference: native `sizeOf`, type codes ARR_FLOAT..ARR_CHAR,
// CSpaceArrays.cs:48-109)
// ---------------------------------------------------------------------------

// type codes — kept numerically identical to the reference's ARR_* constants
// so serialized cluster traffic stays self-describing.
enum TypeCode : int {
  ARR_FLOAT = 0,
  ARR_DOUBLE = 1,
  ARR_INT = 2,
  ARR_LONG = 3,
  ARR_UINT = 4,
  ARR_BYTE = 5,
  ARR_CHAR = 6,
  ARR_BFLOAT16 = 7,  // TPU-native addition
  ARR_BOOL = 8,
};

EXPORT int ck_sizeOf(int type_code) {
  switch (type_code) {
    case ARR_FLOAT: return 4;
    case ARR_DOUBLE: return 8;
    case ARR_INT: return 4;
    case ARR_LONG: return 8;
    case ARR_UINT: return 4;
    case ARR_BYTE: return 1;
    case ARR_CHAR: return 2;  // reference char is UTF-16 (C#); kept for wire parity
    case ARR_BFLOAT16: return 2;
    case ARR_BOOL: return 1;
    default: return -1;
  }
}

// ---------------------------------------------------------------------------
// aligned host allocations (reference: createArray / alignedArrHead /
// deleteArray, CSpaceArrays.cs:119-147)
// ---------------------------------------------------------------------------

EXPORT void* ck_createArray(std::int64_t num_bytes, std::int64_t alignment) {
  if (num_bytes <= 0) return nullptr;
  std::size_t align =
      alignment > 0 ? static_cast<std::size_t>(alignment) : kDefaultAlignment;
  // round the size up so aligned_alloc's size-multiple-of-alignment rule holds
  std::size_t size = static_cast<std::size_t>(num_bytes);
  size = (size + align - 1) / align * align;
  void* p = std::aligned_alloc(align, size);
  if (p != nullptr) {
    g_live_allocations.fetch_add(1, std::memory_order_relaxed);
    g_live_bytes.fetch_add(static_cast<std::int64_t>(size),
                           std::memory_order_relaxed);
    // touch pages now so first DMA doesn't eat soft page faults
    std::memset(p, 0, size);
  }
  return p;
}

// With aligned_alloc the head pointer IS the aligned pointer; kept as a
// separate entry point for contract parity with the reference, where raw and
// aligned heads differ (CSpaceArrays.cs:239-244).
EXPORT void* ck_alignedArrHead(void* raw, std::int64_t alignment) {
  (void)alignment;
  return raw;
}

EXPORT void ck_deleteArray(void* raw, std::int64_t num_bytes,
                           std::int64_t alignment) {
  if (raw == nullptr) return;
  std::size_t align =
      alignment > 0 ? static_cast<std::size_t>(alignment) : kDefaultAlignment;
  std::size_t size = static_cast<std::size_t>(num_bytes > 0 ? num_bytes : 0);
  size = (size + align - 1) / align * align;
  std::free(raw);
  g_live_allocations.fetch_sub(1, std::memory_order_relaxed);
  g_live_bytes.fetch_sub(static_cast<std::int64_t>(size),
                         std::memory_order_relaxed);
}

EXPORT void ck_copyMemory(void* dst, const void* src, std::int64_t num_bytes) {
  if (dst == nullptr || src == nullptr || num_bytes <= 0) return;
  std::memcpy(dst, src, static_cast<std::size_t>(num_bytes));
}

EXPORT void ck_fillMemory(void* dst, int byte_value, std::int64_t num_bytes) {
  if (dst == nullptr || num_bytes <= 0) return;
  std::memset(dst, byte_value, static_cast<std::size_t>(num_bytes));
}

EXPORT std::int64_t ck_liveAllocations() {
  return g_live_allocations.load(std::memory_order_relaxed);
}

EXPORT std::int64_t ck_liveBytes() {
  return g_live_bytes.load(std::memory_order_relaxed);
}

// ---------------------------------------------------------------------------
// marker counters (reference: addMarkerToCommandQueue +
// getMarkerCounterOfCommandQueue + resetMarkerCounterOfCommandQueue,
// ClCommandQueue.cs:39-47,99-115 — native callback counts completions)
// ---------------------------------------------------------------------------

EXPORT std::int64_t ck_createMarkerCounter() {
  std::lock_guard<std::mutex> lock(g_counter_mutex);
  std::int64_t id = g_next_counter_id++;
  g_counters[id] = new MarkerCounter();
  return id;
}

EXPORT void ck_deleteMarkerCounter(std::int64_t id) {
  std::lock_guard<std::mutex> lock(g_counter_mutex);
  auto it = g_counters.find(id);
  if (it != g_counters.end()) {
    delete it->second;
    g_counters.erase(it);
  }
}

namespace {
MarkerCounter* find_counter(std::int64_t id) {
  std::lock_guard<std::mutex> lock(g_counter_mutex);
  auto it = g_counters.find(id);
  return it == g_counters.end() ? nullptr : it->second;
}
}  // namespace

EXPORT void ck_addMarker(std::int64_t id) {
  if (MarkerCounter* c = find_counter(id)) {
    c->added.fetch_add(1, std::memory_order_relaxed);
  }
}

EXPORT void ck_markerReached(std::int64_t id) {
  if (MarkerCounter* c = find_counter(id)) {
    c->reached.fetch_add(1, std::memory_order_relaxed);
  }
}

EXPORT std::int64_t ck_markersAdded(std::int64_t id) {
  MarkerCounter* c = find_counter(id);
  return c ? c->added.load(std::memory_order_relaxed) : -1;
}

EXPORT std::int64_t ck_markersReached(std::int64_t id) {
  MarkerCounter* c = find_counter(id);
  return c ? c->reached.load(std::memory_order_relaxed) : -1;
}

EXPORT std::int64_t ck_markersRemaining(std::int64_t id) {
  MarkerCounter* c = find_counter(id);
  if (c == nullptr) return -1;
  return c->added.load(std::memory_order_relaxed) -
         c->reached.load(std::memory_order_relaxed);
}

EXPORT void ck_resetMarkerCounter(std::int64_t id) {
  if (MarkerCounter* c = find_counter(id)) {
    c->added.store(0, std::memory_order_relaxed);
    c->reached.store(0, std::memory_order_relaxed);
  }
}

// ABI sanity probe for the ctypes loader.
EXPORT std::int64_t ck_abiVersion() { return 1; }
