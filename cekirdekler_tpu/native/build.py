"""Lazy builder + ctypes loader for the native host runtime library.

The reference ships its native layer as a prebuilt DLL; we build ours from
source on first use with the system toolchain and cache the shared object
next to the source.  Thread-safe; failures degrade gracefully (callers fall
back to pure-numpy host arrays).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "kutuphane_tpu.cpp"
_LIB = _HERE / "libkutuphane_tpu.so"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def _compile() -> bool:
    cmd = [
        "g++",
        "-O2",
        "-shared",
        "-fPIC",
        "-std=c++17",
        "-fvisibility=hidden",
        str(_SRC),
        "-o",
        str(_LIB),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64 = ctypes.c_int64
    p = ctypes.c_void_p
    lib.ck_sizeOf.argtypes = [ctypes.c_int]
    lib.ck_sizeOf.restype = ctypes.c_int
    lib.ck_createArray.argtypes = [i64, i64]
    lib.ck_createArray.restype = p
    lib.ck_alignedArrHead.argtypes = [p, i64]
    lib.ck_alignedArrHead.restype = p
    lib.ck_deleteArray.argtypes = [p, i64, i64]
    lib.ck_deleteArray.restype = None
    lib.ck_copyMemory.argtypes = [p, p, i64]
    lib.ck_copyMemory.restype = None
    lib.ck_fillMemory.argtypes = [p, ctypes.c_int, i64]
    lib.ck_fillMemory.restype = None
    lib.ck_liveAllocations.argtypes = []
    lib.ck_liveAllocations.restype = i64
    lib.ck_liveBytes.argtypes = []
    lib.ck_liveBytes.restype = i64
    for name in (
        "ck_createMarkerCounter",
        "ck_abiVersion",
    ):
        getattr(lib, name).argtypes = []
        getattr(lib, name).restype = i64
    for name in ("ck_deleteMarkerCounter", "ck_addMarker", "ck_markerReached", "ck_resetMarkerCounter"):
        getattr(lib, name).argtypes = [i64]
        getattr(lib, name).restype = None
    for name in ("ck_markersAdded", "ck_markersReached", "ck_markersRemaining"):
        getattr(lib, name).argtypes = [i64]
        getattr(lib, name).restype = i64
    # events (ClEvent/ClUserEvent parity)
    lib.ck_eventCreate.argtypes = []
    lib.ck_eventCreate.restype = i64
    for name in ("ck_eventDelete", "ck_eventTrigger", "ck_eventIncrement", "ck_eventDecrement"):
        getattr(lib, name).argtypes = [i64]
        getattr(lib, name).restype = None
    lib.ck_eventFired.argtypes = [i64]
    lib.ck_eventFired.restype = ctypes.c_int
    lib.ck_eventWait.argtypes = [i64, i64]
    lib.ck_eventWait.restype = ctypes.c_int
    lib.ck_eventPending.argtypes = [i64]
    lib.ck_eventPending.restype = i64
    # async copy engine
    lib.ck_copyEngineStart.argtypes = [ctypes.c_int]
    lib.ck_copyEngineStart.restype = None
    lib.ck_copyEngineThreads.argtypes = []
    lib.ck_copyEngineThreads.restype = ctypes.c_int
    lib.ck_copyEngineQueued.argtypes = []
    lib.ck_copyEngineQueued.restype = i64
    lib.ck_copyAsync.argtypes = [p, p, i64, i64]
    lib.ck_copyAsync.restype = None
    lib.ck_copyParallel.argtypes = [p, p, i64, ctypes.c_int]
    lib.ck_copyParallel.restype = None
    return lib


def load() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed:
        return None
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed:
            return None
        try:
            if not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
                if not _compile():
                    _load_failed = True
                    return None
            lib = ctypes.CDLL(str(_LIB))
            if lib.ck_abiVersion() != 2:
                raise OSError("ABI mismatch")
            _lib = _bind(lib)
            return _lib
        except Exception:
            _load_failed = True
            return None


def available() -> bool:
    return load() is not None
