from .build import available, load

__all__ = ["available", "load"]
