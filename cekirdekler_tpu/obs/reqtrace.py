"""Request-lifecycle tracing: tail-latency anatomy per request.

The observability plane explains *windows* (trace/spans), *controllers*
(obs/decisions), and *processes* (obs/flight) — this module explains a
**request**.  Every serving-tier request is stamped with a
fabric-unique ``rid`` at ``ServeFrontend.submit`` /
``ServeFabric.submit`` and records phase-transition events through its
whole life into :data:`REQTRACE`, an always-on bounded ring with the
FLIGHT discipline (obs/flight.py): plain-attribute ``enabled`` gate,
GIL-atomic deque append, disabled cost <100ns and enabled append <1µs
(both pinned by test — the PR 6 overhead family).

**The event vocabulary is the phase vocabulary.**  Events telescope: a
request's phase durations are the gaps between its consecutive events
(the later event NAMES the phase it closes), plus the explicit
``wait_s`` a chain's first event may carry (the admission wait the
frontend measures with ``perf_counter`` before any event exists to
telescope from).  Because every phase is a gap between recorded
stamps, per-request phase sums cover the measured request wall by
construction — the ≥0.95 coverage contract :func:`tail_anatomy`
reports and the acceptance test pins.

Event timestamps are WALL-CLOCK (``time.time()``, the flight-recorder
rule): a rid's chain stays ordered when it hops processes over the
fabric wire (a member kill re-routes in-flight requests onto ring
survivors — the killed shard's events and the survivor's merge into
ONE chain per rid in the cluster trace).

Everything below the recorder is PURE (ckmodel purity-linted):
:func:`fold_phases` folds an event list into per-request records,
:func:`tail_anatomy` decomposes p50/p95/p99 into per-phase
milliseconds with the explicit coverage fraction,
:func:`phase_fracs` derives the regress-watched
``serve_p99_queue_frac`` / ``serve_p99_device_frac``,
:func:`request_chrome_events` renders per-request Perfetto tracks
(merged into ``unified_chrome_trace`` / ``gather_cluster``), and
:func:`anatomy_table` renders the table ``tools/loadgen.py`` prints
after every run.  ``/reqz`` (obs/debugserver.py) serves
:func:`reqz_payload`.
"""

from __future__ import annotations

import itertools
import os
import time
from collections import deque
from typing import NamedTuple

__all__ = [
    "REQ_EVENT_KINDS",
    "TERMINAL_KINDS",
    "QUEUE_PHASES",
    "ReqEvent",
    "ReqTrace",
    "REQTRACE",
    "fold_phases",
    "tail_anatomy",
    "phase_fracs",
    "tenant_percentiles",
    "slowest_requests",
    "request_chrome_events",
    "anatomy_table",
    "reqz_payload",
]

#: The request-lifecycle phase vocabulary — every ``REQTRACE.event``
#: kind must be one of these (ckcheck's reqevent vocabulary pass) and
#: the table in docs/OBSERVABILITY.md must list EXACTLY these
#: (tools/lint_obs.py checks both directions).
REQ_EVENT_KINDS = (
    "admitted",       # admission verdict landed (carries the gate wait)
    "queued",         # first planning cycle saw the request's group
    "coalesce-wait",  # the coalescer picked the group (batching delay)
    "warm-compile",   # compile-cache miss inside the dispatch window
    "dispatched",     # the request's batch left for the device queues
    "device",         # fused-window wall retired (barrier + flush)
    "contained",      # blast-radius containment handled its batch part
    "retry-backoff",  # a granted retry's backoff (inline or deferred)
    "diverted",       # routed off its ring owner by the health view
    "rerouted",       # re-submitted on a ring survivor after a kill
    "resolved",       # future resolved with a result
    "failed",         # future failed with the NAMED cause
)

#: Chain-terminal kinds: a request record is complete when its last
#: event is one of these (a mid-chain ``failed`` followed by a
#: ``rerouted`` hop is NOT terminal — the chain continues elsewhere).
TERMINAL_KINDS = ("resolved", "failed")

#: The phases that count as "time spent waiting to run" for the
#: regress-watched ``serve_p99_queue_frac`` (see :func:`phase_fracs`).
QUEUE_PHASES = ("admitted", "queued", "coalesce-wait")


class ReqEvent(NamedTuple):
    """One phase-transition stamp (wall-clock ``time.time()`` — the
    cross-process merge rule; see module docstring)."""

    t: float
    rid: str
    kind: str
    fields: dict


class ReqTrace:
    """The request-lifecycle recorder: a bounded ring of
    :class:`ReqEvent`, always on (the flight-recorder discipline —
    ``enabled`` is a PLAIN attribute read, the append is ONE GIL-atomic
    ``deque.append``, and a full ring evicts oldest-first instead of
    blocking or growing)."""

    def __init__(self, capacity: int = 65536):
        self.enabled = True  # plain attribute: the <100ns disabled read
        self._cap = max(16, int(capacity))
        self._ring: deque[ReqEvent] = deque(maxlen=self._cap)
        self._total = 0
        # rid minting: pid-stamped counter — unique across every fabric
        # process on the host without coordination (the `_fabric_worker`
        # wire carries rids verbatim, so collision-freedom is what keeps
        # a merged cluster chain ONE request's).  itertools.count: the
        # increment is ONE C-level next() — GIL-atomic, no lock on the
        # submit hot path (ckcheck hot root)
        self._seq = itertools.count(1)

    def mint(self) -> str:
        """A fabric-unique request id (``r<pid>-<seq>``)."""
        return f"r{os.getpid():x}-{next(self._seq):x}"

    def event(self, rid: str, kind: str, **fields) -> None:
        """Record one phase transition for ``rid``.  Hot-path safe:
        disabled is one attribute read; enabled is one tuple build +
        one deque append (ckcheck hot root — computed fields at call
        sites stay behind ``REQTRACE.enabled``)."""
        if not self.enabled:
            return
        self._ring.append(ReqEvent(time.time(), rid, kind, fields))
        self._total += 1  # GIL-racy undercount possible; reporting only

    def snapshot(self) -> list[ReqEvent]:
        """Recorded events, oldest first (reporting-only consistency —
        the flight-recorder snapshot rule)."""
        return list(self._ring)

    def clear(self) -> None:
        self._ring.clear()
        self._total = 0

    @property
    def capacity(self) -> int:
        return self._cap

    @property
    def total_recorded(self) -> int:
        return self._total


#: Process-wide recorder singleton (the FLIGHT pattern): the serving
#: tier records here; ``/reqz``, loadgen, and the cluster exchange read
#: here.
REQTRACE = ReqTrace()


# -- pure phase folding (ckmodel purity-linted) -------------------------------
def _row(ev):
    """Normalize one event (ReqEvent, 4-tuple/list off the wire, or an
    ``{"t", "rid", "kind", "fields"}`` dict) to ``(t, rid, kind,
    fields)``."""
    if isinstance(ev, dict):
        return (float(ev.get("t") or 0.0), str(ev.get("rid") or ""),
                str(ev.get("kind") or ""), dict(ev.get("fields") or {}))
    t, rid, kind, fields = ev
    return (float(t), str(rid), str(kind), dict(fields or {}))


def fold_phases(events) -> list[dict]:
    """PURE: fold an event list into one record per rid.

    Phases telescope (see module docstring): the later event of each
    consecutive pair names the phase that gap belongs to, and a chain's
    FIRST event contributes its explicit ``wait_s`` (the pre-event
    admission wait).  ``wall_s`` prefers the terminal event's measured
    ``latency_s`` (the frontend's own ``perf_counter`` wall) and falls
    back to the chain's stamp extent; ``coverage`` = phase sum /
    ``wall_s`` — the ≥0.95 contract's numerator and denominator, never
    hidden.  Records sort by completion time."""
    by: dict[str, list] = {}
    for ev in events:
        t, rid, kind, fields = _row(ev)
        if rid:
            by.setdefault(rid, []).append((t, kind, fields))
    records = []
    for rid, evs in by.items():
        evs.sort(key=lambda e: e[0])
        t0 = evs[0][0]
        lead = float(evs[0][2].get("wait_s") or 0.0)
        phases: dict[str, float] = {evs[0][1]: lead}
        prev = t0
        for t, kind, fields in evs[1:]:
            phases[kind] = phases.get(kind, 0.0) + max(0.0, t - prev)
            prev = t
        tenant = None
        outcome = None
        wall = None
        for _t, kind, fields in evs:
            if fields.get("tenant") is not None:
                tenant = str(fields["tenant"])
            if kind in TERMINAL_KINDS:
                outcome = kind
                if fields.get("latency_s") is not None:
                    wall = float(fields["latency_s"])
        if evs[-1][1] not in TERMINAL_KINDS:
            outcome = None  # chain continues (e.g. rerouted elsewhere)
            wall = None
        if wall is None:
            wall = (prev - t0) + lead
        total = sum(phases.values())
        records.append({
            "rid": rid,
            "tenant": tenant,
            "outcome": outcome,
            "t0": t0,
            "t1": prev,
            "wall_s": wall,
            "phases_s": phases,
            "coverage": (total / wall) if wall > 0 else 1.0,
            "kinds": [k for _t, k, _f in evs],
        })
    records.sort(key=lambda r: (r["t1"], r["rid"]))
    return records


def _nearest_rank(n: int, pct: float) -> int:
    """PURE: nearest-rank percentile index into a sorted length-n
    list."""
    if n <= 1:
        return 0
    k = int(round((float(pct) / 100.0) * (n - 1)))
    return min(max(k, 0), n - 1)


def tail_anatomy(records, pcts=(50, 95, 99)) -> dict:
    """PURE: decompose the latency percentiles into per-phase
    milliseconds.

    For each requested percentile the nearest-rank COMPLETED request is
    picked and its phase breakdown reported verbatim (a real request's
    anatomy — not an average that smears phases across requests), with
    its explicit ``coverage`` fraction.  A ``mean`` block aggregates
    the per-phase means over every completed request.  Returns
    ``{"count", "pcts": {"p50": {"rid", "wall_ms", "coverage",
    "phases_ms"}, ...}, "mean": {...}}``."""
    done = [r for r in records if r.get("outcome") in TERMINAL_KINDS]
    done.sort(key=lambda r: r["wall_s"])
    out: dict = {"count": len(done), "pcts": {}}
    if not done:
        return out
    for p in pcts:
        r = done[_nearest_rank(len(done), p)]
        out["pcts"][f"p{p:g}"] = {
            "rid": r["rid"],
            "wall_ms": r["wall_s"] * 1e3,
            "coverage": r["coverage"],
            "phases_ms": {k: v * 1e3
                          for k, v in sorted(r["phases_s"].items())},
        }
    mean: dict[str, float] = {}
    for r in done:
        for k, v in r["phases_s"].items():
            mean[k] = mean.get(k, 0.0) + v
    out["mean"] = {
        "wall_ms": sum(r["wall_s"] for r in done) / len(done) * 1e3,
        "phases_ms": {k: v / len(done) * 1e3
                      for k, v in sorted(mean.items())},
    }
    return out


def phase_fracs(record) -> dict:
    """PURE: one record's queue/device wall fractions — the
    regress-watched ``serve_p99_queue_frac`` /
    ``serve_p99_device_frac`` oracles (queue = the
    :data:`QUEUE_PHASES` sum; device = the ``device`` phase)."""
    rec = record or {}
    wall = float(rec.get("wall_s") or 0.0)
    ph = rec.get("phases_s") or {}
    if wall <= 0:
        return {"queue_frac": 0.0, "device_frac": 0.0}
    queue = sum(float(ph.get(k) or 0.0) for k in QUEUE_PHASES)
    return {"queue_frac": queue / wall,
            "device_frac": float(ph.get("device") or 0.0) / wall}


def tenant_percentiles(records, pcts=(50, 99)) -> dict:
    """PURE: per-tenant wall percentiles with the picked request's
    phase breakdown (the ``/reqz`` per-tenant view)."""
    by: dict[str, list] = {}
    for r in records:
        if r.get("outcome") in TERMINAL_KINDS:
            by.setdefault(str(r.get("tenant")), []).append(r)
    out = {}
    for tenant, rs in sorted(by.items()):
        rs.sort(key=lambda r: r["wall_s"])
        row = {"count": len(rs)}
        for p in pcts:
            r = rs[_nearest_rank(len(rs), p)]
            row[f"p{p:g}_ms"] = r["wall_s"] * 1e3
            row[f"p{p:g}_phases_ms"] = {
                k: v * 1e3 for k, v in sorted(r["phases_s"].items())}
        out[tenant] = row
    return out


def slowest_requests(records, n: int = 10) -> list[dict]:
    """PURE: the n slowest completed records, slowest first."""
    done = [r for r in records if r.get("outcome") in TERMINAL_KINDS]
    done.sort(key=lambda r: r["wall_s"], reverse=True)
    return done[: max(0, int(n))]


def request_chrome_events(events, t_base: float | None = None,
                          pid: int = 90,
                          process_name: str = "requests") -> list[dict]:
    """PURE: per-request Perfetto tracks — one thread per rid, one
    ``X`` slice per phase (cat ``ck-req``, so the round-trip importer
    in ``trace/device.split_unified_trace`` can tell request slices
    from host spans).  ``t_base`` defaults to the earliest stamp; the
    chain's leading explicit ``wait_s`` renders as a slice ENDING at
    the first stamp (the pre-event admission wait)."""
    rows = sorted((_row(e) for e in events), key=lambda r: (r[0], r[1]))
    rows = [r for r in rows if r[1]]
    if not rows:
        return []
    if t_base is None:
        t_base = rows[0][0] - float(rows[0][3].get("wait_s") or 0.0)
    out: list[dict] = [{
        "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
        "args": {"name": process_name},
    }]
    by: dict[str, list] = {}
    for r in rows:
        by.setdefault(r[1], []).append(r)
    for tid, rid in enumerate(sorted(by), start=1):
        evs = by[rid]
        out.append({"ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_name", "args": {"name": rid}})
        lead = float(evs[0][3].get("wait_s") or 0.0)
        if lead > 0:
            out.append({
                "ph": "X", "pid": pid, "tid": tid, "cat": "ck-req",
                "name": evs[0][2],
                "ts": (evs[0][0] - lead - t_base) * 1e6,
                "dur": lead * 1e6,
                "args": {"rid": rid},
            })
        prev = evs[0][0]
        for t, _rid, kind, fields in evs[1:]:
            out.append({
                "ph": "X", "pid": pid, "tid": tid, "cat": "ck-req",
                "name": kind,
                "ts": (prev - t_base) * 1e6,
                "dur": max(0.0, t - prev) * 1e6,
                "args": dict(fields, rid=rid),
            })
            prev = t
    return out


def anatomy_table(anatomy) -> str:
    """PURE: render one :func:`tail_anatomy` result as the fixed-width
    table ``tools/loadgen.py`` prints after every run."""
    doc = anatomy or {}
    pcts = doc.get("pcts") or {}
    if not pcts:
        return "tail anatomy: no completed requests recorded"
    kinds = sorted({k for row in pcts.values()
                    for k in (row.get("phases_ms") or {})})
    lines = ["tail anatomy (per-phase ms; coverage = phase sum / "
             "measured wall):"]
    head = f"  {'pct':>5} {'wall_ms':>9} {'cover':>6}"
    for k in kinds:
        head += f" {k:>13}"
    lines.append(head)
    for name, row in sorted(pcts.items()):
        line = (f"  {name:>5} {row.get('wall_ms', 0.0):>9.3f} "
                f"{row.get('coverage', 0.0):>6.3f}")
        ph = row.get("phases_ms") or {}
        for k in kinds:
            line += f" {ph.get(k, 0.0):>13.3f}"
        lines.append(line)
    return "\n".join(lines)


def reqz_payload(events=None, n_slow: int = 10, n_recent: int = 50,
                 pcts=(50, 95, 99)) -> dict:
    """The ``/reqz`` debug-endpoint body: recent requests, the
    slowest-N with their phase breakdowns, per-tenant phase
    percentiles, and the full tail anatomy — all folded from one
    recorder snapshot (snapshot-copy discipline)."""
    evs = REQTRACE.snapshot() if events is None else list(events)
    records = fold_phases(evs)

    def _brief(r):
        return {
            "rid": r["rid"], "tenant": r["tenant"],
            "outcome": r["outcome"],
            "wall_ms": r["wall_s"] * 1e3,
            "coverage": r["coverage"],
            "phases_ms": {k: v * 1e3
                          for k, v in sorted(r["phases_s"].items())},
            "kinds": r["kinds"],
        }

    return {
        "enabled": REQTRACE.enabled,
        "capacity": REQTRACE.capacity,
        "total_recorded": REQTRACE.total_recorded,
        "events": len(evs),
        "requests": len(records),
        "recent": [_brief(r) for r in records[-max(0, int(n_recent)):]],
        "slowest": [_brief(r)
                    for r in slowest_requests(records, n_slow)],
        "tenants": tenant_percentiles(records),
        "anatomy": tail_anatomy(records, pcts),
    }
