"""Debug HTTP endpoints: what is this process doing RIGHT NOW?

A stdlib-only (``http.server``) introspection plane served from a
daemon thread — no dependency, no framework, safe to leave on in
production the way ``/statusz``-family pages are.  Start it with
``Cores.serve_debug(port=0)`` (ephemeral port, returned on the server
object) or export ``CK_DEBUG_PORT=<port>`` before constructing the
first ``Cores`` (subsequent ``Cores`` in the same process skip the
busy port silently — one debug plane per process).

Endpoints (all GET, all JSON unless noted):

- ``/metrics`` — the live registry in Prometheus exposition format
  (``metrics/export.prometheus_text``; ``text/plain; version=0.0.4``).
- ``/statusz`` — process uptime, the lane table (device names, per-cid
  balancer shares, compute/transfer benches, driver/stream queue
  depths, stream chunk choices), fused-window state + stats, transfer
  tuner state, and the active enqueue window.
- ``/tracez`` — tracer state (enabled, total recorded, capacity,
  **dropped span count** — the ring-wrap loss that used to be silent)
  plus the most recent spans as rows; ``?chrome=1`` downloads the full
  Chrome-trace JSON for Perfetto.
- ``/healthz`` — the lane health report (``obs/health.py``): HTTP 200
  while no lane is degraded, 503 otherwise — a load-balancer-pluggable
  liveness gate.
- ``/flightz`` — the flight recorder's event ring + a registry
  snapshot: the black box, readable before the crash.
- ``/profilez`` — the last device-timeline capture's reconciled
  per-kernel report (``trace/device.py``; a NAMED absence on rigs
  whose backend exposes no device tracks), mark-plane state, and the
  persistent kernel-profile store's index.
- ``/decisionz`` — the decision-provenance plane (``obs/decisions.py``):
  ring state, per-kind decision counts, the most recent records, and
  the latest split's per-lane causality table per compute id (the live
  ``explain``; ``tools/ckreplay.py explain`` renders the same thing
  from a spilled log).
- ``/servez`` — the serving tier (``serve/frontend.py``): every live
  frontend's queue depth, signature-group table (pending + starvation
  streaks), per-tenant accounting, admission configuration, and the
  windowed (last-N) latency snapshot next to the cumulative tenant
  stats.
- ``/reqz`` — request-lifecycle tracing (``obs/reqtrace.py``): recent
  requests, the slowest-N with per-phase breakdowns, per-tenant phase
  percentiles, and the p50/p95/p99 tail anatomy with its coverage
  fraction (``?slow=N`` / ``?n=N`` size the views).

Lock discipline (the hot-path contract): every endpoint reads
SNAPSHOTS — ``REGISTRY.snapshot()`` copies under the registry lock,
``TRACER.snapshot()``/``FLIGHT.snapshot()`` are one-slice ring copies,
the health report copies under the monitor lock, and the ``Cores``
scheduler lock is held only long enough to copy the small enqueue-window
sets.  No endpoint ever blocks a worker thread for longer than one of
those copies, and no endpoint mutates runtime state.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..metrics.export import prometheus_text
from ..metrics.registry import REGISTRY
from ..trace.spans import TRACER
from ..utils.jsonsafe import json_safe
from .flight import FLIGHT

__all__ = ["DebugServer", "serve_debug", "DEBUG_PORT_ENV"]

DEBUG_PORT_ENV = "CK_DEBUG_PORT"

#: /tracez row cap — the full ring downloads via ?chrome=1.
TRACEZ_ROWS = 256


def _json_bytes(obj) -> bytes:
    # json_safe: a float('inf') ANYWHERE in a payload (a gauge a caller
    # set, a weird tag) must degrade to null, never serialize as the
    # RFC-8259-invalid bare `Infinity` every strict scraper rejects —
    # the generalized PR 6 /healthz fix (ckcheck invariant/json-unsafe)
    return json.dumps(json_safe(obj), allow_nan=False).encode()


def _copy_dict(d: dict) -> dict:
    """Racy-read dict copy: worker bench dicts gain first-ever keys on
    pool threads with no lock a reader may take (the phase lock can be
    held for a whole phase — a scraper must not queue behind it).  A
    resize mid-copy raises RuntimeError; retry a few times and degrade
    to empty rather than answering 500 (same race class the registry
    iterator locks against — these dicts have no such lock by design)."""
    for _ in range(8):
        try:
            return dict(d)
        except RuntimeError:
            continue
    return {}


class DebugServer:
    """The introspection daemon.  ``cores`` is duck-typed (anything with
    ``workers``/``global_ranges``/``fused_stats``/``health`` enriches
    ``/statusz`` and ``/healthz``) and may be None — the metrics/trace/
    flight endpoints are process-global either way."""

    def __init__(self, cores=None, port: int = 0, host: str = "127.0.0.1"):
        self.cores = cores
        self._t0 = time.time()
        server = self  # captured by the handler class below

        class Handler(BaseHTTPRequestHandler):
            # silence per-request stderr lines — a scraper at 1 Hz must
            # not spam the owning process's logs
            def log_message(self, fmt, *args):  # noqa: D102
                pass

            def do_GET(self):  # noqa: N802 - http.server API
                try:
                    server._route(self)
                except BrokenPipeError:
                    pass  # client went away mid-reply; nothing to save
                except Exception as e:  # noqa: BLE001 - reply, don't die
                    try:
                        body = _json_bytes(
                            {"error": f"{type(e).__name__}: {e}"})
                        self.send_response(500)
                        self.send_header(
                            "Content-Type", "application/json")
                        self.send_header(
                            "Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    except Exception:  # noqa: BLE001
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ck-debug-http",
            daemon=True,
        )
        self._thread.start()

    # -- routing -------------------------------------------------------------
    def _route(self, h: BaseHTTPRequestHandler) -> None:
        url = urlparse(h.path)
        q = parse_qs(url.query)
        route = {
            "/": self._index,
            "/metrics": self._metrics,
            "/statusz": self._statusz,
            "/tracez": self._tracez,
            "/healthz": self._healthz,
            "/flightz": self._flightz,
            "/profilez": self._profilez,
            "/decisionz": self._decisionz,
            "/servez": self._servez,
            "/reqz": self._reqz,
        }.get(url.path)
        if route is None:
            self._reply(h, 404, _json_bytes(
                {"error": f"no such endpoint: {url.path}"}))
            return
        route(h, q)

    @staticmethod
    def _reply(h, code: int, body: bytes,
               ctype: str = "application/json") -> None:
        h.send_response(code)
        h.send_header("Content-Type", ctype)
        h.send_header("Content-Length", str(len(body)))
        h.end_headers()
        h.wfile.write(body)

    # -- endpoints -----------------------------------------------------------
    def _index(self, h, q) -> None:
        self._reply(h, 200, _json_bytes({
            "endpoints": ["/metrics", "/statusz", "/tracez", "/healthz",
                          "/flightz", "/profilez", "/decisionz", "/servez",
                          "/reqz"],
            "uptime_s": round(time.time() - self._t0, 3),
        }))

    def _metrics(self, h, q) -> None:
        self._reply(
            h, 200, prometheus_text().encode(),
            ctype="text/plain; version=0.0.4; charset=utf-8",
        )

    def _statusz(self, h, q) -> None:
        doc: dict = {
            "uptime_s": round(time.time() - self._t0, 3),
            "time": time.time(),
        }
        cores = self.cores
        if cores is not None:
            with cores._lock:
                enq = {
                    "enqueue_mode": cores.enqueue_mode,
                    "active_cids": sorted(cores._enqueue_cids),
                    "cid_order": list(cores._enqueue_cid_order),
                    "iters": dict(cores._enqueue_iters),
                    "window_age_s": (
                        round(time.perf_counter() - cores._enqueue_t0, 6)
                        if cores._enqueue_t0 is not None else None
                    ),
                    "fused_window_open": cores._fused_sig is not None,
                    "fused_pending": cores._fused_pending,
                }
                shares = {
                    cid: list(r) for cid, r in cores.global_ranges.items()
                }
                fused = {
                    "windows": cores.fused_stats["windows"],
                    "fused_iters": cores.fused_stats["fused_iters"],
                    "deferred_iters": cores.fused_stats["deferred_iters"],
                    "disengaged": dict(cores.fused_stats["disengaged"]),
                }
            lanes = []
            for w in cores.workers:
                lanes.append({
                    "lane": w.index,
                    "device": str(w.device),
                    "benchmarks_ms": {
                        str(c): round(v, 4)
                        for c, v in _copy_dict(w.benchmarks).items()
                    },
                    "transfer_benchmarks_ms": {
                        str(c): round(v, 4)
                        for c, v in _copy_dict(w.transfer_benchmarks).items()
                    },
                    "driver_queue_depth": w._m_driver_depth.value,
                    "stream_queue_depth": w._m_stream_depth.value,
                    "stream_chunks": cores.last_stream_chunks.get(w.index),
                })
            doc.update({
                "devices": cores.device_names(),
                "lanes": lanes,
                "shares": {str(c): r for c, r in shares.items()},
                "enqueue_window": enq,
                "fused": fused,
                "stream_tuner": {
                    "retunes": cores.transfer_tuner.retunes,
                    "lane_overhead_ms": {
                        str(w.index): round(
                            cores.transfer_tuner.lane_overhead_ms(w.index), 4)
                        for w in cores.workers
                    },
                },
            })
        self._reply(h, 200, _json_bytes(doc))

    def _tracez(self, h, q) -> None:
        spans = TRACER.snapshot()
        if q.get("chrome"):
            from ..trace.export import to_chrome_trace

            body = _json_bytes(to_chrome_trace(spans))
            self._reply(h, 200, body)
            return
        rows = [
            {"kind": s.kind, "t0": s.t0, "dur_ms": round(s.dur_ms, 4),
             "cid": s.cid, "lane": s.lane, "tag": s.tag}
            for s in spans[-TRACEZ_ROWS:]
        ]
        self._reply(h, 200, _json_bytes({
            "enabled": TRACER.enabled,
            "total_recorded": TRACER.total_recorded,
            "capacity": TRACER.capacity,
            "dropped_spans": TRACER.dropped_spans,
            "spans": rows,
            "shown": len(rows),
        }))

    def _healthz(self, h, q) -> None:
        cores = self.cores
        if cores is not None and getattr(cores, "health", None) is not None:
            report = cores.health.report()
        else:
            from .health import registry_health_summary

            report = registry_health_summary()["lanes"]
        # verdict, gate, and drain list all derive from the ONE report
        # snapshot — separate monitor calls could disagree if a window
        # closed in between, making the 200/503 contradict the payload
        # exactly at flip time
        drain = [
            lane for lane, rec in report.items()
            if rec["verdict"] == "degraded"
        ]
        healthy = not drain
        self._reply(h, 200 if healthy else 503, _json_bytes({
            "healthy": healthy,
            "lanes": {str(k): v for k, v in report.items()},
            "suggest_drain": drain,
        }))

    def _flightz(self, h, q) -> None:
        self._reply(h, 200, _json_bytes({
            "total_recorded": FLIGHT.total_recorded,
            "capacity": FLIGHT.capacity,
            "events": [e.to_row() for e in FLIGHT.snapshot()],
            "metrics": REGISTRY.snapshot(),
        }))

    def _profilez(self, h, q) -> None:
        # profilez_payload reads the last-report slot under its own
        # lock and lists store FILENAMES only (no row bodies) — the
        # same snapshot-copy discipline as every other endpoint
        from ..trace.device import profilez_payload

        self._reply(h, 200, _json_bytes(profilez_payload()))

    def _decisionz(self, h, q) -> None:
        # decisionz_payload reads ONE ring snapshot and formats the
        # latest splits' causality tables from the records' own stored
        # outputs — no controller state is touched, nothing re-derives
        from .replay import decisionz_payload

        recent = 64
        if q.get("n"):
            try:
                recent = max(1, min(4096, int(q["n"][0])))
            except ValueError:
                pass
        self._reply(h, 200, _json_bytes(decisionz_payload(recent=recent)))

    def _servez(self, h, q) -> None:
        # servez_payload copies each frontend's small state under its
        # own lock (stats()) — the same snapshot discipline as every
        # other endpoint; no submit is blocked for longer than the copy
        from ..serve.frontend import servez_payload

        self._reply(h, 200, _json_bytes(servez_payload()))

    def _reqz(self, h, q) -> None:
        # reqz_payload folds ONE recorder snapshot (the flight-ring
        # copy discipline) — no serving state is touched, nothing
        # blocks a submit
        from .reqtrace import reqz_payload

        n_slow, n_recent = 10, 50
        if q.get("slow"):
            try:
                n_slow = max(1, min(1024, int(q["slow"][0])))
            except ValueError:
                pass
        if q.get("n"):
            try:
                n_recent = max(1, min(4096, int(q["n"][0])))
            except ValueError:
                pass
        self._reply(h, 200, _json_bytes(
            reqz_payload(n_slow=n_slow, n_recent=n_recent)))

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:  # noqa: BLE001 - dispose must not raise
            pass

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def serve_debug(cores=None, port: int = 0,
                host: str = "127.0.0.1") -> DebugServer:
    """Start the introspection daemon (ephemeral port with ``port=0``;
    read it back from ``server.port``)."""
    return DebugServer(cores=cores, port=port, host=host)
