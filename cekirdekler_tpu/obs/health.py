"""Lane health scoring: rolling per-lane baselines and a degradation
detector with hysteresis.

ROADMAP item 4's eviction loop ("a lane whose ``ck_fence_seconds``
degrades N× gets drained") needs the OBSERVATION half first: something
that watches each lane's fence walls, transfer walls, and stream-queue
stalls, learns what "normal" looks like per lane (lanes are allowed to
be unequal — that is the whole reference premise; only a lane departing
from ITS OWN baseline is degradation), and produces machine-readable
verdicts.  This module is that half.  It is **advisory only**:
:meth:`HealthMonitor.suggest_drain` names lanes, it never drains one —
eviction is ROADMAP item 4's business.

Detector math (pinned by ``tests/test_obs.py``):

- Samples stream in per (lane, signal) via :meth:`HealthMonitor.observe`
  (seconds).  Every ``window`` samples close one **window**; the window's
  MEDIAN is its value (a single GC pause or link hiccup inside a window
  must not flag it).
- The **baseline** is the rolling median of up to ``baseline_windows``
  previously closed, un-flagged window medians.  Flagged windows (ratio
  ≥ threshold) are excluded from the baseline on purpose: a persisting
  degradation must keep reading as degradation, not get absorbed into a
  "new normal" that silently re-greens the lane.
- ``ratio = current window median / baseline``.  A window with
  ``ratio ≥ threshold`` is a strike; ``confirm`` (default 3)
  consecutive strikes flip the (lane, signal) to **degraded** (a
  shorter strike streak reads **suspect** — enough windows to confirm
  have not elapsed).  So an injected N× degradation flips the lane
  within ``confirm`` windows of its onset (the acceptance bound: ≤ 3),
  while a 1-2 window contention blip only warns.
- **Hysteresis**: a degraded (lane, signal) recovers only when a closed
  window's ratio falls to ``release`` (default ``threshold/2``) — a
  lane oscillating around the threshold cannot flap ok/degraded each
  window.
- A lane's verdict is the WORST of its signals' states; the numeric
  score (0 ok / 1 suspect / 2 degraded) is exported as the
  ``ck_lane_health{lane}`` gauge on every window close.

Integration (core/cores.py): ``Cores`` owns one monitor; the barrier
feeds per-lane fence walls, ``_note_transfer``/``_finish_deferred`` feed
transfer walls, and the streamed path feeds stream-driver backpressure
stalls.  ``Cores.health_report()`` returns :meth:`HealthMonitor.report`;
``trace/aggregate.gather_cluster`` ships the report so the DCN tier sees
every process's lane verdicts on one table
(:func:`cluster_health_table`).
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from statistics import median

from ..metrics.registry import REGISTRY
from .decisions import DECISIONS

__all__ = [
    "HealthMonitor",
    "VERDICTS",
    "evaluate_window",
    "verdict_score",
    "score_verdict",
    "registry_health_summary",
    "cluster_health_table",
]

#: Verdict names in severity order — index IS the exported gauge value.
VERDICTS = ("ok", "suspect", "degraded")


def verdict_score(verdict: str) -> int:
    return VERDICTS.index(verdict)


def score_verdict(score: float) -> str:
    i = max(0, min(len(VERDICTS) - 1, int(round(score))))
    return VERDICTS[i]


def evaluate_window(
    med: float,
    baseline: float | None,
    streak: int,
    degraded: bool,
    threshold: float,
    confirm: int,
    release: float,
) -> dict:
    """The detector's PURE per-window state transition (see the module
    docstring for the math): one closed window's median against the
    rolling baseline → ``{"flagged", "ratio", "streak", "degraded"}``.

    Factored out of :meth:`HealthMonitor._close_window` so the decision
    is replay-verifiable: a ``health-verdict`` record carries exactly
    these arguments, and ``tools/ckreplay.py verify`` re-executes this
    function and asserts the identical transition.  ``ratio`` is None
    while the baseline is still learning AND in the zero-baseline
    strike case (never ``float('inf')`` — the RFC-8259 rule)."""
    flagged = False
    ratio: float | None = None
    if baseline is not None and baseline > 0.0:
        ratio = med / baseline
        if degraded:
            # hysteresis: only a clear return to baseline releases
            if ratio <= release:
                degraded = False
                streak = 0
            else:
                flagged = True
        elif ratio >= threshold:
            flagged = True
            streak += 1
            if streak >= confirm:
                degraded = True
        else:
            streak = 0
    elif baseline is not None and baseline == 0.0:
        # baseline of zero: any nonzero median is "infinitely" worse —
        # a material sample is a strike, zeros are normal
        ratio = None if med > 0.0 else 1.0
        if med > 0.0:
            flagged = True
            streak += 1
            if streak >= confirm:
                degraded = True
        else:
            streak = 0
            degraded = False
    # baseline None: still learning this signal's normal — no change
    return {"flagged": flagged, "ratio": ratio, "streak": streak,
            "degraded": degraded}


@dataclass
class _SignalState:
    """Rolling state of one (lane, signal)."""

    window: list = field(default_factory=list)
    history: deque = field(default_factory=deque)  # un-flagged medians
    last_median: float | None = None
    last_ratio: float | None = None
    windows_closed: int = 0
    streak: int = 0          # consecutive threshold strikes
    degraded: bool = False   # sticky until ratio <= release


class HealthMonitor:
    """Per-lane degradation detector (see module docstring).

    Thread-safe: ``observe`` may be called from worker/pool threads;
    verdict reads snapshot under the same lock (the debug server's
    lock-consistency contract — readers never block the hot path for
    longer than one small-state copy)."""

    def __init__(
        self,
        threshold: float = 3.0,
        window: int = 8,
        baseline_windows: int = 16,
        confirm: int = 3,
        release: float | None = None,
        min_history: int = 4,
    ):
        # defaults tuned on the 2-core CPU rig: confirm=3 still flips an
        # injected degradation within the 3-window acceptance bound, but
        # a 2-window contention blip (a scraper process landing on the
        # box) no longer does; min_history=4 keeps the baseline from
        # being judged off just two warm windows
        if threshold <= 1.0:
            raise ValueError(f"threshold must exceed 1.0: {threshold}")
        self.threshold = float(threshold)
        self.window = max(2, int(window))
        self.baseline_windows = max(2, int(baseline_windows))
        self.confirm = max(1, int(confirm))
        self.release = (
            float(release) if release is not None else self.threshold / 2.0
        )
        if not 1.0 <= self.release <= self.threshold:
            raise ValueError(
                f"release {self.release} must lie in [1.0, {self.threshold}]"
            )
        self.min_history = max(1, int(min_history))
        self._mu = threading.Lock()
        self._state: dict[tuple[int, str], _SignalState] = {}
        self._gauges: dict[int, object] = {}
        # last advisory recorded as a decision — suggest_drain dedups
        # on it (the health-verdict flip rule: a 1 Hz healthz/healthy()
        # poll during a sustained degradation must not fill the
        # decision ring with identical advisories)
        self._last_advisory: list[int] | None = None

    # -- inputs --------------------------------------------------------------
    def observe(self, lane: int, signal: str, seconds: float) -> None:
        """One sample of ``signal`` (``fence`` / ``transfer`` /
        ``stream_stall`` by convention) for ``lane``, in seconds.
        Negative/zero samples are recorded as 0 (a zero-cost window is a
        legitimate 'this lane did nothing expensive' observation)."""
        v = max(float(seconds), 0.0)
        with self._mu:
            st = self._state.setdefault((int(lane), signal), _SignalState())
            st.window.append(v)
            if len(st.window) >= self.window:
                self._close_window(int(lane), signal, st)

    def _close_window(self, lane: int, signal: str,
                      st: _SignalState) -> None:
        """Caller holds the lock.  Evaluate the closed window against
        the rolling baseline (:func:`evaluate_window` — the pure,
        replay-verifiable transition) and update the strike/hysteresis
        state.  A verdict FLIP records a ``health-verdict`` decision
        with the transition's complete inputs.

        (``last_ratio`` stays None for the zero-baseline strike — NOT
        ``float('inf')``: json.dumps serializes inf as the bare token
        `Infinity`, which is RFC-8259-invalid and would break every
        /healthz consumer and the DCN health payload.)"""
        med = median(st.window)
        st.window = []
        st.windows_closed += 1
        st.last_median = med
        baseline = (
            median(st.history) if len(st.history) >= self.min_history
            else None
        )
        before = self._signal_state_name(st)
        rec = None
        if DECISIONS.enabled:
            rec = {
                "lane": lane, "signal": signal,
                "median_s": med, "baseline_s": baseline,
                "streak": st.streak, "degraded": st.degraded,
                "threshold": self.threshold, "confirm": self.confirm,
                "release": self.release,
            }
        res = evaluate_window(
            med, baseline, streak=st.streak, degraded=st.degraded,
            threshold=self.threshold, confirm=self.confirm,
            release=self.release,
        )
        st.last_ratio = res["ratio"]
        st.streak = res["streak"]
        st.degraded = res["degraded"]
        if not res["flagged"]:
            st.history.append(med)
            while len(st.history) > self.baseline_windows:
                st.history.popleft()
        after = self._signal_state_name(st)
        if rec is not None and after != before:
            # the FLIP is the decision of record; steady windows are
            # recoverable from the metrics gauges and would swamp the
            # ring at scrape cadence
            DECISIONS.record("health-verdict", rec,
                             dict(res, state=after, state_before=before))
        self._export_gauge_locked(lane)

    def _export_gauge_locked(self, lane: int) -> None:
        pair = self._gauges.get(lane)
        if pair is None:
            pair = (
                REGISTRY.gauge(
                    "ck_lane_health",
                    "lane health verdict (0 ok / 1 suspect / 2 degraded)",
                    lane=lane,
                ),
                REGISTRY.gauge(
                    "ck_lane_health_peak",
                    "worst lane-health verdict seen this process "
                    "(monotone high-water)",
                    lane=lane,
                ),
            )
            self._gauges[lane] = pair
        g, peak = pair
        score = float(verdict_score(self._lane_verdict_locked(lane)[0]))
        g.set(score)
        # the high-water mark never decreases: later monitors (a fresh
        # Cores per bench section) must not erase an earlier section's
        # degradation from the process-wide artifact view
        if score > peak.value:
            peak.set(score)

    # -- verdicts ------------------------------------------------------------
    def _signal_state_name(self, st: _SignalState) -> str:
        if st.degraded:
            return "degraded"
        if st.streak > 0:
            return "suspect"
        return "ok"

    def _lane_verdict_locked(self, lane: int) -> tuple[str, dict]:
        worst = "ok"
        evidence: dict[str, dict] = {}
        for (ln, signal), st in self._state.items():
            if ln != lane:
                continue
            name = self._signal_state_name(st)
            if verdict_score(name) > verdict_score(worst):
                worst = name
            evidence[signal] = {
                "state": name,
                "windows": st.windows_closed,
                "baseline_ms": (
                    round(median(st.history) * 1000.0, 4)
                    if len(st.history) >= self.min_history else None
                ),
                "current_ms": (
                    round(st.last_median * 1000.0, 4)
                    if st.last_median is not None else None
                ),
                "ratio": (
                    round(st.last_ratio, 3)
                    if st.last_ratio is not None else None
                ),
                "streak": st.streak,
            }
        return worst, evidence

    def lanes(self) -> list[int]:
        with self._mu:
            return sorted({ln for (ln, _sig) in self._state})

    def verdict(self, lane: int) -> str:
        with self._mu:
            return self._lane_verdict_locked(int(lane))[0]

    def report(self) -> dict:
        """``{lane: {"verdict", "score", "evidence": {signal: {...}}}}``
        — the machine-readable health table (``/healthz``,
        ``Cores.health_report``, the DCN merge)."""
        with self._mu:
            out: dict = {}
            for lane in sorted({ln for (ln, _s) in self._state}):
                verdict, evidence = self._lane_verdict_locked(lane)
                out[lane] = {
                    "verdict": verdict,
                    "score": verdict_score(verdict),
                    "evidence": evidence,
                }
            return out

    def suggest_drain(self) -> list[int]:
        """Lanes currently DEGRADED — the advisory eviction candidate
        list.  Observation only: nothing in this module (or this PR)
        acts on it; ROADMAP item 4's elastic tier is the consumer.

        A CHANGED advisory records a ``drain-advisory`` decision
        (inputs: every lane's verdict + per-signal ratios) so the
        eviction work ROADMAP item 4 builds starts with provenance
        already wired — "why was this lane named" is answerable from
        the log alone.  Change-only, the health-verdict flip rule: a
        polling consumer (``healthy()`` at scrape cadence) during a
        sustained degradation must not evict the balancer/tuner
        provenance from the ring with identical advisories; the
        all-clear (a previously-advised list going empty) records too
        — recovery is a decision of record."""
        report = self.report()
        drain = [
            lane for lane, rec in report.items()
            if rec["verdict"] == "degraded"
        ]
        # compare-and-set under the monitor lock (report() released it
        # above — no nesting): the debug server's healthz thread and an
        # application poller race this path, and an unlocked RMW could
        # double-record a flip or overwrite the baseline the next real
        # change must compare against
        with self._mu:
            changed = drain != self._last_advisory and (
                drain or self._last_advisory)
            self._last_advisory = drain
        if changed and DECISIONS.enabled:
            DECISIONS.record("drain-advisory", {
                "lanes": {
                    str(lane): {
                        "verdict": rec["verdict"],
                        "ratios": {
                            sig: ev.get("ratio")
                            for sig, ev in rec["evidence"].items()
                        },
                    }
                    for lane, rec in report.items()
                },
            }, {"drain": list(drain)})
        return drain

    def healthy(self) -> bool:
        """True while no lane is degraded (the ``/healthz`` 200/503
        gate — ``suspect`` still answers 200: one strike is a warning,
        not an outage)."""
        return not self.suggest_drain()


# -- registry / cluster views ------------------------------------------------

def registry_health_summary(snapshot: dict | None = None) -> dict:
    """Per-lane verdicts recovered from the ``ck_lane_health`` (current)
    and ``ck_lane_health_peak`` (process-lifetime high-water) gauges in
    a registry snapshot (live registry when None) — the process-wide
    view that survives individual ``Cores`` disposal.  ``bench.py``
    embeds this as the artifact ``health`` block: ``worst``/``healthy``
    describe the run's END state, ``worst_seen`` whether ANY lane
    degraded at any point during the whole run (the peak gauge is
    monotone, so a later section's fresh monitor cannot erase it)."""
    if snapshot is None:
        snapshot = REGISTRY.snapshot()
    lanes: dict = {}
    for series, value in (snapshot.get("gauges") or {}).items():
        if not series.startswith("ck_lane_health"):
            continue
        is_peak = series.startswith("ck_lane_health_peak")
        lane = "?"
        if 'lane="' in series:
            lane = series.split('lane="', 1)[1].split('"', 1)[0]
        rec = lanes.setdefault(lane, {"score": 0.0, "verdict": "ok"})
        if is_peak:
            rec["peak"] = value
            rec["peak_verdict"] = score_verdict(value)
        else:
            rec["score"] = value
            rec["verdict"] = score_verdict(value)
    worst = max((v["score"] for v in lanes.values()), default=0.0)
    worst_seen = max(
        (v.get("peak", v["score"]) for v in lanes.values()), default=0.0)
    return {"lanes": lanes, "worst": score_verdict(worst),
            "worst_seen": score_verdict(worst_seen),
            "healthy": worst < 2}


def cluster_health_table(snapshot) -> dict:
    """Merge a :class:`~cekirdekler_tpu.trace.aggregate.ClusterSnapshot`'s
    per-process health reports into one job-wide table::

        {"processes": [{"process": p, "lanes": {...}} ...],
         "degraded": [{"process": p, "lane": l, "evidence": {...}}],
         "worst": "ok|suspect|degraded"}

    Processes that shipped no health report (older peers, health off)
    appear with ``lanes: {}`` — absence is visible, never an implicit
    "ok"."""
    per_proc = snapshot.get("health") or []
    processes = []
    degraded = []
    worst = 0
    for p, rep in enumerate(per_proc):
        rep = rep or {}
        processes.append({"process": p, "lanes": rep})
        for lane, rec in rep.items():
            score = int(rec.get("score", verdict_score(rec.get("verdict", "ok"))))
            worst = max(worst, score)
            if rec.get("verdict") == "degraded":
                degraded.append({
                    "process": p, "lane": lane,
                    "evidence": rec.get("evidence"),
                })
    return {
        "processes": processes,
        "degraded": degraded,
        "worst": score_verdict(worst),
    }
