"""``cekirdekler_tpu.obs`` — the live introspection plane.

Three pillars over the r7 tracer and r9 metrics registry (see
``docs/OBSERVABILITY.md`` "Live introspection"):

- :mod:`.debugserver` — stdlib-HTTP debug endpoints (``/metrics``,
  ``/statusz``, ``/tracez``, ``/healthz``, ``/flightz``) served from a
  daemon thread; start via ``Cores.serve_debug(port=0)`` or
  ``CK_DEBUG_PORT``.
- :mod:`.flight` — the always-on flight recorder: a bounded ring of
  DECISION events (balancer moves, fused engage/disengage, stream-tuner
  choices, driver failures) plus throttled metric samples, dumped as a
  self-contained postmortem JSON (``CK_POSTMORTEM_DIR``) whenever a
  crash surfaces at a wired boundary.
- :mod:`.health` — rolling per-lane baselines over fence/transfer/
  stream-stall walls with an N×-threshold + hysteresis degradation
  detector; advisory verdicts only (``suggest_drain`` names lanes, the
  elastic tier — ROADMAP item 4 — is the consumer that will act).
- :mod:`.reqtrace` — request-lifecycle tracing: every serving-tier
  request's phase-transition events (admitted → queued → coalesce-wait
  → dispatched → device → resolved, plus containment/retry/fabric
  hops) in an always-on bounded ring keyed by a fabric-unique ``rid``;
  the pure ``tail_anatomy`` fold decomposes p50/p95/p99 into per-phase
  milliseconds (also served live on ``/reqz``).
- :mod:`.decisions` — decision PROVENANCE: the event-sourced log of
  every controller decision with inputs sufficient to reproduce it;
  :mod:`.replay` + ``tools/ckreplay.py`` replay-verify it bit-
  identically, run counterfactual what-ifs, and render the ``explain``
  causality tables (also served live on ``/decisionz``).

No jax imports at module level — the plane costs no backend
initialization (same contract as ``trace``/``metrics``).
"""

from .decisions import (
    DECISION_KINDS,
    DECISION_LOG_ENV,
    DECISIONS,
    REPLAYABLE_KINDS,
    DecisionLog,
    DecisionRecord,
    load_decision_log,
)
from .debugserver import DEBUG_PORT_ENV, DebugServer, serve_debug
from .flight import (
    FLIGHT,
    POSTMORTEM_DIR_ENV,
    FlightEvent,
    FlightRecorder,
    dump_postmortem,
    load_postmortem,
    postmortem_spans,
    record_crash,
)
from .health import (
    VERDICTS,
    HealthMonitor,
    cluster_health_table,
    evaluate_window,
    registry_health_summary,
)
from .reqtrace import (
    REQ_EVENT_KINDS,
    REQTRACE,
    ReqEvent,
    ReqTrace,
    fold_phases,
    request_chrome_events,
    reqz_payload,
    tail_anatomy,
)

__all__ = [
    "DEBUG_PORT_ENV",
    "DECISIONS",
    "DECISION_KINDS",
    "DECISION_LOG_ENV",
    "DebugServer",
    "DecisionLog",
    "DecisionRecord",
    "FLIGHT",
    "FlightEvent",
    "FlightRecorder",
    "HealthMonitor",
    "POSTMORTEM_DIR_ENV",
    "REPLAYABLE_KINDS",
    "REQTRACE",
    "REQ_EVENT_KINDS",
    "ReqEvent",
    "ReqTrace",
    "VERDICTS",
    "cluster_health_table",
    "dump_postmortem",
    "evaluate_window",
    "fold_phases",
    "load_decision_log",
    "load_postmortem",
    "postmortem_spans",
    "record_crash",
    "registry_health_summary",
    "request_chrome_events",
    "reqz_payload",
    "serve_debug",
    "tail_anatomy",
]
