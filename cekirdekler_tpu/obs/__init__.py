"""``cekirdekler_tpu.obs`` — the live introspection plane.

Three pillars over the r7 tracer and r9 metrics registry (see
``docs/OBSERVABILITY.md`` "Live introspection"):

- :mod:`.debugserver` — stdlib-HTTP debug endpoints (``/metrics``,
  ``/statusz``, ``/tracez``, ``/healthz``, ``/flightz``) served from a
  daemon thread; start via ``Cores.serve_debug(port=0)`` or
  ``CK_DEBUG_PORT``.
- :mod:`.flight` — the always-on flight recorder: a bounded ring of
  DECISION events (balancer moves, fused engage/disengage, stream-tuner
  choices, driver failures) plus throttled metric samples, dumped as a
  self-contained postmortem JSON (``CK_POSTMORTEM_DIR``) whenever a
  crash surfaces at a wired boundary.
- :mod:`.health` — rolling per-lane baselines over fence/transfer/
  stream-stall walls with an N×-threshold + hysteresis degradation
  detector; advisory verdicts only (``suggest_drain`` names lanes, the
  elastic tier — ROADMAP item 4 — is the consumer that will act).

No jax imports at module level — the plane costs no backend
initialization (same contract as ``trace``/``metrics``).
"""

from .debugserver import DEBUG_PORT_ENV, DebugServer, serve_debug
from .flight import (
    FLIGHT,
    POSTMORTEM_DIR_ENV,
    FlightEvent,
    FlightRecorder,
    dump_postmortem,
    load_postmortem,
    postmortem_spans,
    record_crash,
)
from .health import (
    VERDICTS,
    HealthMonitor,
    cluster_health_table,
    registry_health_summary,
)

__all__ = [
    "DEBUG_PORT_ENV",
    "DebugServer",
    "FLIGHT",
    "FlightEvent",
    "FlightRecorder",
    "HealthMonitor",
    "POSTMORTEM_DIR_ENV",
    "VERDICTS",
    "cluster_health_table",
    "dump_postmortem",
    "load_postmortem",
    "postmortem_spans",
    "record_crash",
    "registry_health_summary",
    "serve_debug",
]
