"""Decision replay: verify, what-if, and explain over a recorded
decision log (``obs/decisions.py``).

Three consumers of the same event-sourced record, all offline-capable
(a jsonl spill or a postmortem's decision ring is enough — no live
rig):

- :func:`verify_records` — **replay-verify**: re-execute the PURE
  decision functions (``core.balance.load_balance``,
  ``TransferTuner.choose``/``observe``, ``obs.health.evaluate_window``)
  from each record's inputs and assert **bit-identical** outputs.  A
  recorded log is thereby a golden test of the controllers: hidden
  nondeterminism (a clock or dict-order dependency that crept into the
  balancer) and silent behavior drift (someone retunes ``DAMP_GROW``)
  both surface as a divergence naming the first divergent ``seq``.
  Exact float equality is the contract — JSON round-trips Python floats
  losslessly (``repr`` shortest-round-trip), and the replayed math runs
  the same operations on the same bits.

- :func:`whatif` — **counterfactual runs**: re-run the CHAINED
  load-balance sequence with modified knobs (``damping=…``,
  ``jump_start=off``, ``transfer_floor=off``, ``smoothing=off``),
  carrying ``BalanceState``/history forward.  Because a counterfactual
  split changes the benches the next iteration would have measured, the
  chain runs on the log's implied **per-item rates** (``bench_i /
  range_i`` per recorded step — the balancer's own cost-density model):
  the factual simulation reproduces the recorded trajectory exactly
  while the log lasts, and both runs extend on the final step's rates
  (steady-state assumption) until the split settles or ``horizon``.
  Reported: iterations-to-converge, the final-split L1 distance, and
  chunk-choice deltas when a tuner knob was overridden.

- :func:`explain_balance` — the **causality table** of one split:
  per lane, the raw bench, the transfer floor (bound or slack, with
  margin), the damped move, the quantization residue, and which input
  bound the outcome.  Pure formatting of the record's own outputs (the
  emission site stores shares/effective/cont precisely so nothing here
  re-derives — re-derivation is replay-verify's job, and keeping the
  two separate means explain can never drift from what actually ran).
  ``/decisionz`` serves the same payload live
  (:func:`decisionz_payload`).

Replays run "quiesced": the global DECISIONS/FLIGHT recorders are
disabled around re-execution so replaying a log never re-records it
(and an in-process bench verify cannot pollute the artifact's rings).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

from .decisions import DECISIONS, REPLAYABLE_KINDS, DecisionRecord

__all__ = [
    "verify_records",
    "replay_record",
    "whatif",
    "simulate_balance",
    "explain_balance",
    "explain_latest",
    "explain_rid",
    "convergence_summary",
    "bench_decisions_summary",
    "decisionz_payload",
    "verify_counterexample",
    "save_counterexample",
    "WHATIF_KNOBS",
]

#: The what-if knob vocabulary (``ckreplay whatif --set k=v,...``).
#: bool knobs accept on/off; the rest parse as floats.
WHATIF_KNOBS = {
    "damping": "initial/fixed damping (float; adaptive mode re-seeds "
               "per-chip damp at this value)",
    "jump_start": "one-shot undamped warm jump to the rate-implied "
                  "split (on/off)",
    "transfer_floor": "floor each lane's effective time at its "
                      "measured link wall (on/off)",
    "smoothing": "sliding-window share smoothing (on/off)",
    "overhead_ms": "transfer tuner per-chunk overhead (float; replays "
                   "every transfer-choose with this lane overhead)",
    "rate_prior": "prior-seeded first split (on/off; off restarts the "
                  "chain from the equal split, quantifying what the "
                  "device-kind priors saved)",
    "block_grid": "block tuner candidate tile sizes, x-separated (e.g. "
                  "128x256x512; replays every block-retune with the "
                  "legal grid rebuilt from these candidates)",
}

#: Consecutive no-change iterations that close a what-if simulation.
SETTLE = 3


def _rows(records) -> list[dict]:
    """Normalize DecisionRecord / raw-dict input to row dicts, seq
    order."""
    out = []
    for r in records:
        if isinstance(r, DecisionRecord):
            out.append(r.to_row())
        elif isinstance(r, dict) and "kind" in r:
            out.append(r)
    out.sort(key=lambda r: r.get("seq", 0))
    return out


def _retuple(x):
    """JSON round-trips tuples as lists; tuner kernel keys must come
    back hashable and self-consistent (the same canonical form is used
    for state insertion AND the replayed call, so an in-memory tuple
    and a disk-loaded list replay identically)."""
    if isinstance(x, (list, tuple)):
        return tuple(_retuple(v) for v in x)
    return x


_quiesce_mu = threading.Lock()
_quiesce_depth = 0
_quiesce_saved: tuple | None = None


@contextmanager
def _quiesced():
    """Disable the global recorders around a replay: re-executing
    recorded decisions must not re-record them (or emit flight events
    into a live ring mid-bench).

    Depth-counted under a lock so OVERLAPPING replays (two threads, or
    whatif nesting simulate_balance) restore the flags only at the
    outermost exit — an early restore would let the still-running
    inner replay re-record into the live ring.  The quiesce is still
    process-GLOBAL by design (the enabled flags are the hot-path
    attribute reads and must stay lock-free): decisions other live
    threads make DURING a replay window are not recorded, so run
    verify at sync points — bench runs it in ``finalize_result``,
    after every section's workload has completed."""
    global _quiesce_depth, _quiesce_saved
    from .flight import FLIGHT

    with _quiesce_mu:
        _quiesce_depth += 1
        if _quiesce_depth == 1:
            _quiesce_saved = (DECISIONS.enabled, FLIGHT.enabled)
            DECISIONS.enabled = False
            FLIGHT.enabled = False
    try:
        yield
    finally:
        with _quiesce_mu:
            _quiesce_depth -= 1
            if _quiesce_depth == 0 and _quiesce_saved is not None:
                DECISIONS.enabled, FLIGHT.enabled = _quiesce_saved
                _quiesce_saved = None


# ---------------------------------------------------------------------------
# replay-verify
# ---------------------------------------------------------------------------

def _mk_balance_parts(inp):
    """(history, carry, state) reconstructed from a load-balance
    record's entry snapshot — fresh objects, bit-equal state."""
    from ..core import balance as B

    hist = None
    hin = inp.get("history")
    if hin is not None:
        hist = B.BalanceHistory(
            depth=int(hin["depth"]), weighted=bool(hin["weighted"]))
        hist.rows = [[float(v) for v in row] for row in hin["rows"]]
    carry = list(inp["carry"]) if inp.get("carry") is not None else None
    st = None
    sin = inp.get("state")
    if sin is not None:
        st = B.BalanceState(
            cont=[float(x) for x in sin["cont"]],
            prev_delta=[float(x) for x in sin["prev_delta"]],
            damp=[float(x) for x in sin["damp"]],
            jumped=bool(sin["jumped"]), warm=bool(sin["warm"]),
        )
    return hist, carry, st


def _replay_load_balance(inp: dict, out: dict) -> dict:
    from ..core import balance as B

    hist, carry, st = _mk_balance_parts(inp)
    got = B.load_balance(
        [float(b) for b in inp["benchmarks"]],
        [int(r) for r in inp["ranges"]],
        int(inp["total"]), int(inp["step"]), hist,
        damping=float(inp["damping"]), carry=carry, state=st,
        transfer_ms=(None if inp.get("transfer_ms") is None
                     else [float(t) for t in inp["transfer_ms"]]),
        jump_start=bool(inp.get("jump_start", False)),
        cid=inp.get("cid"),
        rate_prior=(None if inp.get("rate_prior") is None
                    else [float(p) for p in inp["rate_prior"]]),
    )
    mism: dict = {}
    exp = [int(x) for x in out.get("ranges", ())]
    if got != exp:
        mism["ranges"] = {"expected": exp, "got": got}
    exp_state = out.get("state_after")
    if st is not None and exp_state is not None:
        got_state = {
            "cont": st.cont, "prev_delta": st.prev_delta, "damp": st.damp,
            "jumped": st.jumped, "warm": st.warm,
        }
        for k, v in got_state.items():
            ev = exp_state.get(k)
            ev = list(ev) if isinstance(ev, list) else ev
            gv = list(v) if isinstance(v, list) else v
            if gv != ev:
                mism[f"state_after.{k}"] = {"expected": ev, "got": gv}
    return mism


def _replay_prior_split(inp: dict, out: dict) -> dict:
    from ..core import balance as B

    got = B.prior_split(
        int(inp["total"]), int(inp["step"]),
        [float(p) for p in inp["priors"]],
        cid=inp.get("cid"),
    )
    exp = [int(x) for x in out.get("ranges", ())]
    if got != exp:
        return {"ranges": {"expected": exp, "got": got}}
    return {}


def _mk_tuner(inp):
    """A fresh TransferTuner carrying exactly the recorded pre-state
    for the record's (lane, key) point."""
    from ..core import stream as S

    t = S.TransferTuner(
        overhead_ms=float(inp.get("default_overhead_ms",
                                  S.PER_CHUNK_OVERHEAD_MS)),
        candidates=tuple(int(c) for c in inp.get(
            "candidates", S.CHUNK_CANDIDATES)),
        ema=float(inp.get("ema", 0.5)),
    )
    lane = int(inp["lane"])
    kk = _retuple(inp["kernel_key"])
    key = (lane, kk, int(inp["bucket"]))
    o = inp.get("obs")
    if o is not None:
        t._obs[key] = S._Obs(
            float(o["u_ms"]), float(o["c_ms"]), float(o["d_ms"]),
            count=int(o.get("count", 1)), stale=int(o.get("stale", 0)))
    s = inp.get("seed")
    if s is not None:
        t._seed[lane] = S._LinkSeed(
            float(s["h2d_ms_per_mib"]), float(s["d2h_ms_per_mib"]))
    t._overhead[lane] = float(inp["overhead_ms"])
    return t, lane, kk, key


def _obs_dict(o) -> dict | None:
    if o is None:
        return None
    return {"u_ms": o.u_ms, "c_ms": o.c_ms, "d_ms": o.d_ms,
            "count": o.count, "stale": o.stale}


def _replay_transfer_choose(inp: dict, out: dict) -> dict:
    t, lane, kk, _key = _mk_tuner(inp)
    got = t.choose(lane, kk, int(inp["nbytes"]), int(inp["max_chunks"]),
                   has_compute=bool(inp.get("has_compute", True)))
    exp = int(out.get("chunks", -1))
    if got != exp:
        return {"chunks": {"expected": exp, "got": got}}
    return {}


def _replay_transfer_observe(inp: dict, out: dict) -> dict:
    t, lane, kk, key = _mk_tuner(inp)
    t.observe(
        lane, kk, int(inp["nbytes"]),
        float(inp["u_ms"]), float(inp["c_ms"]), float(inp["d_ms"]),
        chunks=int(inp.get("chunks", 1)),
        wall_ms=(None if inp.get("wall_ms") is None
                 else float(inp["wall_ms"])),
        fenced=bool(inp.get("fenced", False)),
    )
    if inp.get("obs") is None and int(inp.get("chunks", 1)) > 1:
        got = {"stored": False}
    else:
        got = {
            "stored": True,
            "obs": _obs_dict(t._obs.get(key)),
            "overhead_ms": t._overhead.get(lane, t.overhead_ms),
        }
    mism: dict = {}
    for k, gv in got.items():
        ev = out.get(k)
        if gv != ev:
            mism[k] = {"expected": ev, "got": gv}
    return mism


def _replay_health_verdict(inp: dict, out: dict) -> dict:
    from .health import evaluate_window

    got = evaluate_window(
        float(inp["median_s"]),
        None if inp.get("baseline_s") is None else float(inp["baseline_s"]),
        streak=int(inp["streak"]), degraded=bool(inp["degraded"]),
        threshold=float(inp["threshold"]), confirm=int(inp["confirm"]),
        release=float(inp["release"]),
    )
    got["state"] = ("degraded" if got["degraded"]
                    else "suspect" if got["streak"] > 0 else "ok")
    mism: dict = {}
    for k in ("flagged", "ratio", "streak", "degraded", "state"):
        if got[k] != out.get(k):
            mism[k] = {"expected": out.get(k), "got": got[k]}
    return mism


def _replay_admission(inp: dict, out: dict) -> dict:
    from ..serve.admission import admit_decision

    got = admit_decision(
        tenant_inflight=int(inp["tenant_inflight"]),
        quota=int(inp["quota"]),
        queue_depth=int(inp["queue_depth"]),
        max_queue_depth=int(inp["max_queue_depth"]),
        healthy=bool(inp["healthy"]),
        est_batch_s=float(inp["est_batch_s"]),
        # kernel-verifier inputs arrived with the ckprove gate, the
        # breaker/brownout inputs with the resilience layer; older
        # logs lack them — replay with the pre-gate defaults
        kernel_unsafe=bool(inp.get("kernel_unsafe", False)),
        kernel_finding=inp.get("kernel_finding"),
        breaker_open=bool(inp.get("breaker_open", False)),
        breaker_retry_after_s=inp.get("breaker_retry_after_s"),
        brownout=bool(inp.get("brownout", False)),
        shed_quota=inp.get("shed_quota"),
        priority=int(inp.get("priority", 1)),
    )
    mism: dict = {}
    for k in ("admit", "reason", "retry_after_s"):
        if got.get(k) != out.get(k):
            mism[k] = {"expected": out.get(k), "got": got.get(k)}
    return mism


def _replay_coalesce(inp: dict, out: dict) -> dict:
    from ..serve.coalescer import plan_coalesce

    got = plan_coalesce(
        list(inp.get("groups") or ()), int(inp.get("round", 0)),
        int(inp.get("max_picks") or 0),
    )
    mism: dict = {}
    for k in ("order", "picked", "promoted"):
        gv, ev = list(got.get(k) or ()), list(out.get(k) or ())
        if gv != ev:
            mism[k] = {"expected": ev, "got": gv}
    return mism


def _replay_breaker(inp: dict, out: dict) -> dict:
    """breaker: one circuit-breaker transition or admit
    (serve/resilience.py) — both pure, dispatched on the recorded
    ``op``."""
    from ..serve.resilience import breaker_admit, breaker_transition

    if inp.get("op") == "admit":
        got = breaker_admit(
            inp.get("state") or {}, float(inp["now"]),
            float(inp["open_s"]))
        keys = ("state", "action", "allow", "probe", "retry_after_s")
    else:
        got = breaker_transition(
            inp.get("state") or {}, str(inp["event"]),
            float(inp["now"]), int(inp["threshold"]),
            float(inp["open_s"]))
        keys = ("state", "action")
    mism: dict = {}
    for k in keys:
        if got.get(k) != out.get(k):
            mism[k] = {"expected": out.get(k), "got": got.get(k)}
    return mism


def _replay_shed(inp: dict, out: dict) -> dict:
    from ..serve.resilience import brownout_transition

    got = brownout_transition(
        inp.get("state") or {}, int(inp["queue_depth"]),
        int(inp["watermark"]), int(inp["clear_mark"]),
        int(inp["open_breakers"]), int(inp["drained_lanes"]),
        engage_streak=int(inp.get("engage_streak", 2)))
    mism: dict = {}
    for k in ("active", "streak", "pressure", "changed"):
        if got.get(k) != out.get(k):
            mism[k] = {"expected": out.get(k), "got": got[k]}
    return mism


def _replay_retry(inp: dict, out: dict) -> dict:
    from ..serve.resilience import retry_decision

    got = retry_decision(
        int(inp["attempt"]), int(inp["max_attempts"]),
        float(inp["tokens"]),
        (None if inp.get("deadline_left_s") is None
         else float(inp["deadline_left_s"])),
        float(inp["base_s"]), float(inp["cap_s"]),
        float(inp["jitter_u"]))
    mism: dict = {}
    for k in ("retry", "delay_s", "reason"):
        if got.get(k) != out.get(k):
            mism[k] = {"expected": out.get(k), "got": got.get(k)}
    return mism


def _replay_containment(inp: dict, out: dict) -> dict:
    from ..serve.resilience import containment_plan

    got = containment_plan(int(inp["k"]), leaf=int(inp.get("leaf", 1)))
    mism: dict = {}
    for k in ("mode", "parts"):
        gv = got.get(k)
        ev = out.get(k)
        gv = list(gv) if isinstance(gv, (list, tuple)) else gv
        ev = list(ev) if isinstance(ev, (list, tuple)) else ev
        if gv != ev:
            mism[k] = {"expected": ev, "got": gv}
    return mism


def _replay_drain(inp: dict, out: dict) -> dict:
    from .drain import drain_transition

    got = drain_transition(
        inp.get("verdicts") or {}, inp.get("states") or {},
        inp.get("hold") or {}, inp.get("clear_streak") or {},
        int(inp.get("hold_barriers", 2)), int(inp.get("confirm_clear", 2)),
        probe_grace=int(inp.get("probe_grace", 2)),
    )
    mism: dict = {}
    for k in ("drained", "readmitted", "probed", "states", "hold",
              "clear_streak"):
        ev = out.get(k)
        ev = list(ev) if isinstance(ev, list) else ev
        gv = got[k]
        if gv != ev:
            mism[k] = {"expected": ev, "got": gv}
    return mism


def _replay_member(inp: dict, out: dict) -> dict:
    """member-leave / member-join: the recorded re-split over the
    post-change step table must re-execute bit-identically (when the
    record carried a total — membership transitions with no known
    workload record only the roster, nothing to re-derive)."""
    from ..cluster.elastic import member_resplit

    mism: dict = {}
    steps = inp.get("steps_after") or []
    total = inp.get("total")
    if total is not None and steps:
        got = member_resplit(steps, int(total))
        for k in ("ranges", "lcm"):
            ev = out.get(k)
            ev = list(ev) if isinstance(ev, list) else ev
            gv = got[k]
            gv = list(gv) if isinstance(gv, list) else gv
            if gv != ev:
                mism[k] = {"expected": ev, "got": gv}
    rec_epoch = out.get("epoch_after")
    got_epoch = int(inp.get("epoch_before", 0)) + 1
    if rec_epoch is not None and rec_epoch != got_epoch:
        # same label convention as ranges/lcm above: "expected" is the
        # RECORDED output, "got" the re-derived value
        mism["epoch_after"] = {"expected": rec_epoch, "got": got_epoch}
    return mism


def _replay_block_retune(inp: dict, out: dict) -> dict:
    """Re-run the pure block transition from the recorded snapshot —
    the tuner's stateful wrapper records exactly the value-copied
    inputs ``block_transition`` consumed, so the re-derivation is
    bit-exact by construction (walls are sorted inside the pure fn;
    insertion order cannot diverge the replay)."""
    from ..core.blocktuner import HYSTERESIS_FRAC, block_transition

    walls = [(_retuple(p), float(w)) for p, w in (inp.get("walls") or [])]
    grid = tuple(_retuple(p) for p in (inp.get("grid") or []))
    choice, why = block_transition(
        _retuple(inp.get("current")), walls, grid,
        hysteresis=float(inp.get("hysteresis", HYSTERESIS_FRAC)),
        seed=_retuple(inp.get("seed")),
        fallback=_retuple(inp.get("fallback")),
    )
    got = {
        "block_q": None if choice is None else choice[0],
        "block_k": None if choice is None else choice[1],
        "why": why,
    }
    mism: dict = {}
    for k, gv in got.items():
        if gv != out.get(k):
            mism[k] = {"expected": out.get(k), "got": gv}
    return mism


def _replay_route(inp: dict, out: dict) -> dict:
    """route: one shard-placement verdict (serve/fabric.py) — the
    pure consistent-hash + diversion walk re-executed from the
    recorded roster, health view, and epoch."""
    from ..serve.fabric import route_decision

    got = route_decision(
        str(inp.get("tenant", "")), str(inp.get("key", "")),
        list(inp.get("members") or ()),
        tuple(inp.get("unhealthy") or ()),
        int(inp.get("epoch", 0)))
    mism: dict = {}
    for k in ("shard", "owner", "diverted", "hops", "reason", "epoch"):
        if got.get(k) != out.get(k):
            mism[k] = {"expected": out.get(k), "got": got.get(k)}
    return mism


_REPLAYERS = {
    "load-balance": _replay_load_balance,
    "transfer-choose": _replay_transfer_choose,
    "transfer-observe": _replay_transfer_observe,
    "health-verdict": _replay_health_verdict,
    "admission": _replay_admission,
    "coalesce": _replay_coalesce,
    "breaker": _replay_breaker,
    "shed": _replay_shed,
    "retry": _replay_retry,
    "containment": _replay_containment,
    "drain-apply": _replay_drain,
    "readmit": _replay_drain,
    "member-leave": _replay_member,
    "member-join": _replay_member,
    "block-retune": _replay_block_retune,
    "route": _replay_route,
    "prior-split": _replay_prior_split,
}
assert set(_REPLAYERS) == set(REPLAYABLE_KINDS)


def replay_record(row) -> dict:
    """Re-execute one record.  Returns ``{"seq", "kind", "ok",
    "mismatch"}`` — ``mismatch`` maps field → expected/got on
    divergence; non-replayable kinds come back ``ok: None``
    (context records, skipped by contract)."""
    rows = _rows([row])
    if not rows:
        return {"seq": None, "kind": None, "ok": None, "mismatch": None}
    r = rows[0]
    fn = _REPLAYERS.get(r["kind"])
    if fn is None:
        return {"seq": r.get("seq"), "kind": r["kind"], "ok": None,
                "mismatch": None}
    with _quiesced():
        mism = fn(r.get("inputs") or {}, r.get("outputs") or {})
    return {"seq": r.get("seq"), "kind": r["kind"], "ok": not mism,
            "mismatch": mism or None}


def verify_records(records, max_divergences: int = 8) -> dict:
    """Replay-verify a whole log (the ``ckreplay verify`` engine and
    bench.py's in-process epilogue pass).

    Returns ``{"ok", "records", "replayed", "skipped", "per_kind",
    "first_divergence", "divergences"}``.  ``ok`` is True when every
    replayable record re-executed bit-identically; ``first_divergence``
    names the earliest divergent seq — the contract the acceptance
    criterion pins ("an injected knob change must fail naming the first
    divergent seq")."""
    rows = _rows(records)
    per_kind: dict = {}
    divergences: list = []
    replayed = skipped = divergent = 0
    with _quiesced():
        for r in rows:
            kind = r["kind"]
            per_kind[kind] = per_kind.get(kind, 0) + 1
            fn = _REPLAYERS.get(kind)
            if fn is None:
                skipped += 1
                continue
            replayed += 1
            try:
                mism = fn(r.get("inputs") or {}, r.get("outputs") or {})
            except Exception as e:  # noqa: BLE001 - a replay crash IS drift
                mism = {"replay-error": {"expected": "clean re-execution",
                                         "got": f"{type(e).__name__}: {e}"}}
            if mism:
                divergent += 1
                # cap the DETAIL, not the scan: counts cover the whole
                # log either way (a report saying records:500 but
                # replayed:8 would misread as 492 never attempted)
                if len(divergences) < max_divergences:
                    divergences.append({
                        "seq": r.get("seq"), "kind": kind,
                        "mismatch": mism})
    return {
        "ok": not divergent,
        "records": len(rows),
        "replayed": replayed,
        "skipped": skipped,
        "divergent": divergent,
        "per_kind": per_kind,
        "first_divergence": divergences[0] if divergences else None,
        "divergences": divergences,
        "divergences_truncated": divergent > len(divergences),
    }


# ---------------------------------------------------------------------------
# the counterexample→replay bridge (tools/ckmodel)
# ---------------------------------------------------------------------------

def _counterexample_trace(violation) -> list[dict]:
    """Trace rows from a ckmodel violation (object, ``to_row()`` dict,
    or a bare row list)."""
    if isinstance(violation, (list, tuple)):
        return list(violation)
    trace = getattr(violation, "trace", None)
    if trace is None and isinstance(violation, dict):
        trace = violation.get("trace")
    return list(trace or ())


def verify_counterexample(violation) -> dict:
    """Replay a model-checker counterexample TRACE through the live
    code path — the bridge the bounded model checker
    (``cekirdekler_tpu/analysis/model.py``) emits its violations for.

    Traces are sequences of decision records in the standard row
    schema, so this is :func:`verify_records` with the violation
    unwrapped.  Two uses, both pinned by tests:

    - a counterexample from the REAL controllers (e.g. a true liveness
      violation found on HEAD) replays ``ok: True`` — the trace is a
      faithful execution, and committing it as a fixture pins the
      fixed behavior as a regression test;
    - a counterexample from a deliberately-broken fixture machine
      diverges naming the first seq where the broken outputs part
      from the real functions — the same drill ``ckreplay verify``
      runs on a tampered log."""
    return verify_records(_counterexample_trace(violation))


def save_counterexample(path: str, violation) -> str:
    """Spill one counterexample as a ``ck-decision-log-v1`` jsonl (the
    decision log's own format, tmp+rename): ``ckreplay verify <path>``
    re-executes it and ``ckreplay explain <path>`` renders the
    causality table of a balance trace — no ckmodel-specific reader
    anywhere downstream."""
    from .decisions import DecisionRecord, _write_jsonl

    rows = [DecisionRecord.from_row(r)
            for r in _counterexample_trace(violation)]
    return _write_jsonl(path, rows, dropped=0, total=len(rows))


# ---------------------------------------------------------------------------
# what-if: chained counterfactual runs
# ---------------------------------------------------------------------------

def _balance_rows(rows: list[dict], cid=None) -> list[dict]:
    recs = [r for r in rows if r["kind"] == "load-balance"]
    if cid is None and recs:
        cid = recs[0]["inputs"].get("cid")
    return [r for r in recs if r["inputs"].get("cid") == cid], cid


def simulate_balance(recs: list[dict], overrides: dict | None = None,
                     horizon: int = 200) -> dict:
    """Run the chained balancer sequence under ``overrides`` (empty =
    the factual run) on the log's implied per-item rates; see the
    module docstring for why rates, not raw benches, drive the chain.
    Pure and deterministic — the simulation itself records nothing."""
    from ..core import balance as B

    overrides = overrides or {}
    first = recs[0]["inputs"]
    n = len(first["ranges"])
    step = int(first["step"])
    total = int(first["total"])

    def rates_of(inp, values):
        if values is None:
            return None
        return [float(values[i]) / max(int(inp["ranges"][i]), step)
                for i in range(n)]

    rate_seq = [rates_of(r["inputs"], r["inputs"]["benchmarks"])
                for r in recs]
    trate_seq = [rates_of(r["inputs"], r["inputs"].get("transfer_ms"))
                 for r in recs]

    damping = float(overrides.get("damping", first["damping"]))
    jump = bool(overrides.get("jump_start", first.get("jump_start", False)))
    floor_on = bool(overrides.get("transfer_floor", True))
    smooth_on = bool(overrides.get(
        "smoothing", first.get("history") is not None))
    hist = None
    if smooth_on:
        hin = first.get("history") or {
            "depth": B.HISTORY_DEPTH, "weighted": True, "rows": []}
        hist = B.BalanceHistory(
            depth=int(hin["depth"]), weighted=bool(hin["weighted"]))
        hist.rows = [[float(v) for v in row] for row in hin["rows"]]
    state = carry = None
    sin = first.get("state")
    if sin is not None:
        state = B.BalanceState(
            cont=[float(x) for x in sin["cont"]],
            prev_delta=[float(x) for x in sin["prev_delta"]],
            damp=([damping] * n if "damping" in overrides
                  else [float(x) for x in sin["damp"]]),
            jumped=bool(sin["jumped"]), warm=bool(sin["warm"]),
        )
    elif first.get("carry") is not None:
        carry = list(first["carry"])

    # the prior's entire effect is the chain's STARTING ranges (the
    # recorded first split is the prior-seeded one when the log carries
    # a rate_prior input) — so the off-counterfactual restarts the
    # chain from the equal split with fresh continuous state, exactly
    # the pre-ISSUE-20 first window
    prior_on = bool(overrides.get("rate_prior", True))
    if prior_on:
        ranges = [int(r) for r in first["ranges"]]
    else:
        ranges = B.equal_split(total, n, step)
        if state is not None:
            state.reset(ranges, damping)
        elif carry is not None:
            carry = None
    trajectory = [list(ranges)]
    last_change = 0
    it = 0
    # settle patience: a damped system behind a depth-N share smoother
    # can hold still for up to ~N iterations while the window absorbs a
    # rate-regime shift (the steady-tail extension IS such a shift when
    # the last recorded step's rates differ from the early ones) — a
    # bare SETTLE would declare "converged" mid-absorption and
    # understate iterations-to-converge for exactly the counterfactuals
    # this simulator exists for
    settle = SETTLE + (hist.depth if hist is not None else 0)
    with _quiesced():
        for it in range(1, max(int(horizon), len(recs)) + 1):
            k = min(it - 1, len(recs) - 1)
            bench = [rate_seq[k][i] * max(ranges[i], step)
                     for i in range(n)]
            tr = None
            if floor_on and trate_seq[k] is not None:
                tr = [trate_seq[k][i] * max(ranges[i], step)
                      for i in range(n)]
            new = B.load_balance(
                bench, list(ranges), total, step, hist,
                damping=damping, carry=carry, state=state,
                transfer_ms=tr, jump_start=jump, cid=first.get("cid"),
            )
            if new != ranges:
                last_change = it
            ranges = new
            trajectory.append(list(ranges))
            if it >= len(recs) and it - last_change >= settle:
                break
    return {
        "iterations_to_converge": last_change,
        "converged": it - last_change >= settle,
        "simulated_iterations": it,
        "final_ranges": list(ranges),
        "trajectory": trajectory,
    }


def whatif(records, overrides: dict, cid=None, horizon: int = 200) -> dict:
    """The counterfactual report (``ckreplay whatif --set k=v,...``):
    factual vs overridden chained runs for one compute id, plus
    chunk-choice deltas when ``overhead_ms`` was overridden."""
    rows = _rows(records)
    recs, cid = _balance_rows(rows, cid)
    out: dict = {"cid": cid, "overrides": dict(overrides),
                 "recorded_steps": len(recs)}
    unknown = set(overrides) - set(WHATIF_KNOBS)
    if unknown:
        raise ValueError(
            f"unknown what-if knob(s) {sorted(unknown)}; "
            f"knobs: {sorted(WHATIF_KNOBS)}")
    if recs:
        balance_overrides = {
            k: v for k, v in overrides.items()
            if k not in ("overhead_ms", "block_grid")}
        factual = simulate_balance(recs, {}, horizon)
        counter = simulate_balance(recs, balance_overrides, horizon)
        l1 = None
        if len(factual["final_ranges"]) == len(counter["final_ranges"]):
            l1 = sum(abs(a - b) for a, b in zip(
                factual["final_ranges"], counter["final_ranges"]))
        out.update({
            "factual": factual,
            "counterfactual": counter,
            "final_split_l1": l1,
        })
    if "overhead_ms" in overrides:
        choices = []
        ov = float(overrides["overhead_ms"])
        with _quiesced():
            for r in rows:
                if r["kind"] != "transfer-choose":
                    continue
                inp = r["inputs"]
                t, lane, kk, _key = _mk_tuner(inp)
                t._overhead[lane] = ov
                got = t.choose(
                    lane, kk, int(inp["nbytes"]), int(inp["max_chunks"]),
                    has_compute=bool(inp.get("has_compute", True)))
                choices.append({
                    "seq": r.get("seq"), "lane": lane,
                    "factual": r["outputs"].get("chunks"),
                    "counterfactual": got,
                })
        out["chunk_choices"] = choices
        out["chunk_choices_changed"] = sum(
            1 for c in choices if c["factual"] != c["counterfactual"])
    if "block_grid" in overrides:
        from ..core.blocktuner import (
            HYSTERESIS_FRAC, block_transition, legal_block_grid)

        raw = overrides["block_grid"]
        if isinstance(raw, str):
            cands = tuple(int(s) for s in raw.split("x") if s.strip())
        elif isinstance(raw, (int, float)):
            cands = (int(raw),)
        else:
            cands = tuple(int(c) for c in raw)
        choices = []
        with _quiesced():
            for r in rows:
                if r["kind"] != "block-retune":
                    continue
                inp = r["inputs"]
                grid = legal_block_grid(
                    int(inp["tq"]), int(inp["tk"]), candidates=cands)
                walls = [(_retuple(p), float(w))
                         for p, w in (inp.get("walls") or [])]
                choice, why = block_transition(
                    _retuple(inp.get("current")), walls, grid,
                    hysteresis=float(
                        inp.get("hysteresis", HYSTERESIS_FRAC)),
                    seed=_retuple(inp.get("seed")),
                    fallback=_retuple(inp.get("fallback")),
                )
                fact = (r["outputs"].get("block_q"),
                        r["outputs"].get("block_k"))
                cf = (None, None) if choice is None else choice
                choices.append({
                    "seq": r.get("seq"),
                    "kernel_sig": inp.get("kernel_sig"),
                    "factual": list(fact),
                    "counterfactual": list(cf),
                    "why": why,
                })
        out["block_choices"] = choices
        out["block_choices_changed"] = sum(
            1 for c in choices if c["factual"] != c["counterfactual"])
    return out


# ---------------------------------------------------------------------------
# explain: the causality table
# ---------------------------------------------------------------------------

def explain_balance(row) -> dict:
    """Per-lane causality table of one recorded split — pure formatting
    of the record's stored outputs (nothing is re-derived; see module
    docstring)."""
    rows = _rows([row])
    if not rows or rows[0]["kind"] != "load-balance":
        raise ValueError("explain_balance wants a load-balance record")
    r = rows[0]
    inp, out = r["inputs"], r["outputs"]
    n = len(inp["ranges"])
    action = out.get("action", "?")
    sin = inp.get("state")
    if sin is not None and len(sin.get("cont") or ()) == n:
        base = [float(x) for x in sin["cont"]]
    elif inp.get("carry"):
        base = [float(x) for x in inp["carry"]]
    else:
        base = [float(x) for x in inp["ranges"]]
    transfer = inp.get("transfer_ms")
    shares = out.get("shares") or [None] * n
    eff = out.get("effective_ms") or [None] * n
    fb = out.get("floor_bound") or [False] * n
    cont = out.get("cont") or [None] * n
    damp = (out.get("state_after") or {}).get("damp") or [None] * n
    lanes = []
    for i in range(n):
        bench = float(inp["benchmarks"][i])
        tms = None if transfer is None else float(transfer[i])
        if action == "freeze":
            binding = "quantization floor (split held)"
        elif action == "jump":
            binding = "rate-implied target (undamped jump)"
        elif fb[i]:
            binding = "transfer floor (link-bound)"
        else:
            binding = "compute bench (damped)"
        lanes.append({
            "lane": i,
            "bench_ms": bench,
            "transfer_ms": tms,
            # + margin = the floor BINDS by this much; − = slack under
            # the compute bench
            "floor_margin_ms": None if tms is None else tms - bench,
            "floor_bound": bool(fb[i]),
            "effective_ms": eff[i],
            "share": shares[i],
            "target_items": (None if shares[i] is None
                             else inp["total"] * shares[i]),
            "base_items": base[i],
            "damp": damp[i],
            "damped_move_items": (None if cont[i] is None
                                  else cont[i] - base[i]),
            "cont_items": cont[i],
            "range_items": int(out["ranges"][i]),
            "quantization_residue_items": (
                None if cont[i] is None else cont[i] - out["ranges"][i]),
            "binding": binding,
        })
    doc = {
        "seq": r.get("seq"), "cid": inp.get("cid"), "action": action,
        "total": inp["total"], "step": inp["step"],
        "jump_start": inp.get("jump_start"),
        "jump_armed": out.get("jump_armed"),
        "lanes": lanes,
    }
    if out.get("freeze") is not None:
        doc["freeze"] = out["freeze"]
    return doc


def explain_latest(records, cid=None) -> dict | None:
    """The latest split's causality table (``ckreplay explain`` /
    ``/decisionz``), optionally filtered to one compute id."""
    rows = _rows(records)
    recs, _cid = _balance_rows(rows, cid)
    if not recs:
        return None
    return explain_balance(recs[-1])


def _mentions_rid(inp: dict, rid: str) -> bool:
    """Does a decision record's input snapshot name this request?  The
    rid rides three shapes: a scalar ``rid`` (admission, retry, route),
    a flat ``rids`` list (containment), and per-group ``rids`` inside a
    coalesce record's ``groups`` rows."""
    if inp.get("rid") == rid:
        return True
    if rid in (inp.get("rids") or ()):
        return True
    for g in inp.get("groups") or ():
        if isinstance(g, dict) and rid in (g.get("rids") or ()):
            return True
    return False


def explain_rid(records, rid: str) -> dict:
    """One request's decision history (``ckreplay explain --rid <id>``):
    every recorded controller decision whose INPUTS named this rid —
    the admission verdict, the coalesce wave(s) that grouped it, any
    containment/retry it rode, and the fabric route/re-route hops — in
    seq order.  Pure filtering of the records' own inputs/outputs
    (nothing re-derived; re-derivation is replay-verify's job).  The
    rid is a decision INPUT, so this is the causal complement of the
    reqtrace timeline: ``fold_phases`` says WHERE the milliseconds
    went, this says WHICH verdicts routed them there.  Decisions
    recorded while the log was disabled (or by a pre-rid build) carry
    no rid and simply do not appear."""
    rid = str(rid)
    steps: list = []
    kinds: dict = {}
    for r in _rows(records):
        inp = r.get("inputs") or {}
        if not _mentions_rid(inp, rid):
            continue
        kinds[r["kind"]] = kinds.get(r["kind"], 0) + 1
        steps.append({
            "seq": r.get("seq"), "t": r.get("t"), "kind": r["kind"],
            "inputs": inp, "outputs": r.get("outputs") or {},
        })
    return {"rid": rid, "decisions": len(steps), "kinds": kinds,
            "steps": steps}


# ---------------------------------------------------------------------------
# summaries (bench artifact + /decisionz)
# ---------------------------------------------------------------------------

def convergence_summary(records) -> dict:
    """Per-cid convergence view of the recorded rebalance sequences:
    how many iterations until the split last moved, and whether it
    ended settled (froze, or stopped changing)."""
    rows = _rows(records)
    per_cid: dict = {}
    for r in rows:
        if r["kind"] != "load-balance":
            continue
        per_cid.setdefault(r["inputs"].get("cid"), []).append(r)
    out: dict = {}
    for cid, recs in per_cid.items():
        changes = 0
        last_change = 0
        prev = None
        for i, r in enumerate(recs, start=1):
            ranges = list(r["outputs"].get("ranges", ()))
            if prev is not None and ranges != prev:
                changes += 1
                last_change = i
            prev = ranges
        last = recs[-1]["outputs"]
        out[str(cid)] = {
            "rebalances": len(recs),
            "moves": changes,
            "iterations_to_converge": last_change,
            "settled": (last.get("action") == "freeze"
                        or last_change < len(recs)),
            "jumped": any(r["outputs"].get("action") == "jump"
                          for r in recs),
            "final_ranges": list(last.get("ranges", ())),
        }
    return out


def bench_decisions_summary(records=None) -> dict:
    """The bench artifact's ``decisions`` block: per-kind counts, the
    per-cid convergence view, and the in-process replay-verify verdict
    (``replay_ok`` — ``tools/regress.py`` hard-fails an artifact that
    carries ``false``: behavior drift in the balancer becomes a
    sentinel failure, not a silent perf mystery)."""
    rows = _rows(records if records is not None else DECISIONS.snapshot())
    counts: dict = {}
    for r in rows:
        counts[r["kind"]] = counts.get(r["kind"], 0) + 1
    verdict = verify_records(rows)
    return {
        "counts": counts,
        "total_recorded": DECISIONS.total_recorded,
        "rebalances": counts.get("load-balance", 0),
        "convergence": convergence_summary(rows),
        "replay_ok": verdict["ok"],
        "replay": {
            "replayed": verdict["replayed"],
            "skipped": verdict["skipped"],
            "first_divergence": verdict["first_divergence"],
        },
    }


def decisionz_payload(recent: int = 64) -> dict:
    """The ``/decisionz`` debug-endpoint body: ring state, per-kind
    counts, the most recent records, and the latest split's causality
    table per compute id (the live ``explain`` plane)."""
    rows = [r.to_row() for r in DECISIONS.snapshot()]
    counts: dict = {}
    latest_lb: dict = {}
    for r in rows:
        counts[r["kind"]] = counts.get(r["kind"], 0) + 1
        if r["kind"] == "load-balance":
            latest_lb[r["inputs"].get("cid")] = r
    explain = {}
    for cid, r in latest_lb.items():
        try:
            explain[str(cid)] = explain_balance(r)
        except Exception as e:  # noqa: BLE001 - one bad record, not a 500
            explain[str(cid)] = {"error": f"{type(e).__name__}: {e}"}
    return {
        "enabled": DECISIONS.enabled,
        "capacity": DECISIONS.capacity,
        "total_recorded": DECISIONS.total_recorded,
        "spill_path": DECISIONS.spill_path(),
        "spill_dropped": DECISIONS.spill_dropped,
        "counts": counts,
        "recent": rows[-max(1, int(recent)):],
        "shown": min(len(rows), max(1, int(recent))),
        "explain": explain,
    }
