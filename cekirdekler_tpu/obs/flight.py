"""Always-on flight recorder: the runtime's black box.

The tracer (``trace/spans.py``) is scoped and off by default; the
metrics registry (``metrics/registry.py``) is always on but keeps only
CURRENT values.  Neither can answer "what was the runtime *deciding*
in the seconds before this crash?" — the balancer's last jumps, the
fused window's engage/disengage sequence, the stream tuner's chunk
flips, the driver-queue failure that preceded the fence error.  This
module records exactly those **decision events** into a bounded ring
that is ALWAYS on (same discipline as the registry: the whole point is
evidence nobody planned to collect), plus throttled periodic metric
samples, and knows how to dump itself as a self-contained postmortem
JSON when a crash surfaces.

Design constraints, same order as the tracer's:

1. **Recording is cheap and lock-free-ish.**  ``event()`` is one
   ``deque.append`` (GIL-atomic on a ``maxlen`` deque — the ring
   evicts oldest-first with no lock) plus one clock read; disabled is
   one attribute read + falsy check, pinned by
   ``tests/test_obs.py::test_disabled_flight_event_overhead`` to the
   PR 4 budget (< 100 ns marginal over the call floor).  No decision
   event rides the fused DEFERRAL path (the enqueue hot path) — all
   instrument sites are window-granularity or colder.
2. **Wall-clock timestamps.**  Events carry ``time.time()`` epoch
   seconds, not ``perf_counter``: postmortems are read OFF-process,
   where a monotonic epoch is meaningless.  The dump also records the
   perf_counter↔epoch exchange rate so the span ring (perf_counter
   seconds) can be placed on the same axis.
3. **Dumps are opt-in by environment.**  ``dump_postmortem`` writes
   only when given a path or when :data:`POSTMORTEM_DIR_ENV`
   (``CK_POSTMORTEM_DIR``) is set — a test rig that injects failures
   on purpose must not litter the filesystem.  When armed, EVERY crash
   surfacing through the wired paths (``Cores.compute``/``barrier``
   error collection, the worker driver-queue drain, ``ClPipeline.push``)
   leaves a black box on disk; the dump itself can never mask the
   original exception (``record_crash`` swallows its own failures).

Event kinds recorded by the built-in instrumentation (callers may add
more): ``rebalance`` (range table moved), ``balance-freeze`` /
``balance-jump`` (balancer decisions, core/balance.py),
``fused-engage`` / ``fused-disengage`` / ``fused-window`` (the fused
dispatch path's lifecycle, with reasons), ``stream-choice`` (the
transfer autotuner's chunk count changed for a lane),
``stream-retune`` (observations dropped after a re-partition),
``barrier`` (sync point, with per-lane fence ms), ``driver-error``
(a dispatch-driver closure failed), ``metrics-sample`` (periodic
registry snapshot), ``crash`` (an exception surfaced at a wired
boundary), ``profiler-start`` / ``profiler-stop`` (a device-timeline
capture opened/closed — ``trace/device.DeviceCapture``; a postmortem
shows whether the crash happened under capture).
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback as _tb
from collections import deque
from typing import Any, NamedTuple

__all__ = [
    "FlightEvent",
    "FlightRecorder",
    "FLIGHT",
    "EVENT_KINDS",
    "POSTMORTEM_DIR_ENV",
    "dump_postmortem",
    "load_postmortem",
    "postmortem_spans",
    "record_crash",
]

POSTMORTEM_DIR_ENV = "CK_POSTMORTEM_DIR"

#: The declared event-kind vocabulary: every kind the built-in
#: instrumentation emits.  ``tools/ckcheck`` (pass 4) fails CI on an
#: emitted kind missing here, and ``tools/lint_obs.py`` cross-checks
#: this tuple against the flight-recorder kind table in
#: docs/OBSERVABILITY.md — so a new decision event is always declared
#: AND documented.  Callers outside the package may still record ad-hoc
#: kinds (the ring does not validate); this tuple is the contract for
#: in-tree emitters only.
EVENT_KINDS = (
    "rebalance", "balance-freeze", "balance-jump",
    "fused-engage", "fused-disengage", "fused-window",
    "stream-choice", "stream-retune", "block-retune",
    "barrier", "driver-error", "metrics-sample", "crash",
    "kernel-verify",
    "debug-server", "debug-port-skipped",
    "profiler-start", "profiler-stop",
    "fault-injected",
    "serve-contain", "breaker-flip", "brownout", "serve-crash",
    "drain-apply", "readmit", "drain-probe",
    "member-leave", "member-join",
    "checkpoint-restore", "checkpoint-fallback", "checkpoint-sweep",
    "fabric-divert", "fabric-reroute", "fabric-warm",
    "cache-warmup",
)

#: Postmortem JSON schema tag.  v2 (this revision) embeds the decision
#: ring (``obs/decisions.py``) next to the event/span rings so a crash
#: black box answers "what was the balancer DECIDING, from which
#: inputs" without a live rig; v1 files (no ``decisions`` key) still
#: load — ``load_postmortem`` backfills an empty list, and every other
#: key is unchanged (additive bump, round-trip pinned by test).
SCHEMA = "ck-postmortem-v2"


class FlightEvent(NamedTuple):
    """One recorded decision.  ``t`` is ``time.time()`` epoch seconds."""

    t: float
    kind: str
    fields: dict

    def to_row(self) -> dict:
        return {"t": self.t, "kind": self.kind, **self.fields}


class FlightRecorder:
    """Bounded always-on ring of decision events (one process-global
    instance: :data:`FLIGHT`).

    ``enabled`` is a plain attribute (the tracer convention: the
    disabled fast path must be an attribute read, not a property call).
    The ring is a ``maxlen`` deque — append evicts oldest-first
    atomically under the GIL, so concurrent recorders never contend on
    a lock and a reader's ``list(ring)`` sees a consistent-enough view
    (reporting, not synchronization — the tracer's snapshot contract).
    """

    def __init__(self, capacity: int = 4096,
                 sample_interval_s: float = 5.0):
        self.enabled = True
        self._cap = max(16, int(capacity))
        self._ring: deque[FlightEvent] = deque(maxlen=self._cap)
        self._total = 0
        self.sample_interval_s = float(sample_interval_s)
        self._last_sample_t = 0.0

    # -- recording (cold/warm paths only — never the fused deferral) ---------
    def event(self, kind: str, **fields: Any) -> None:
        if not self.enabled:
            return
        self._ring.append(FlightEvent(time.time(), kind, fields))
        self._total += 1  # GIL-racy undercount possible; reporting only

    def maybe_sample_metrics(self, now: float | None = None) -> bool:
        """Record a throttled ``metrics-sample`` event carrying the
        registry's counter/gauge values (histograms ride as count/sum —
        the buckets would dwarf the ring).  Call from sync points; at
        most one sample per :attr:`sample_interval_s`."""
        if not self.enabled:
            return False
        t = time.time() if now is None else now
        if t - self._last_sample_t < self.sample_interval_s:
            return False
        self._last_sample_t = t
        from ..metrics.registry import REGISTRY

        snap = REGISTRY.snapshot()
        compact = dict(snap["counters"])
        compact.update(snap["gauges"])
        for series, v in snap["histograms"].items():
            compact[series + "_count"] = v["count"]
            compact[series + "_sum"] = v["sum"]
        self.event("metrics-sample", values=compact)
        return True

    # -- inspection ----------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self._cap

    @property
    def total_recorded(self) -> int:
        """Events recorded since the last clear — exceeds ``capacity``
        when the ring wrapped (oldest events were evicted)."""
        return self._total

    def snapshot(self) -> list[FlightEvent]:
        """Recorded events, oldest first."""
        return list(self._ring)

    def clear(self) -> None:
        self._ring.clear()
        self._total = 0
        self._last_sample_t = 0.0


#: The process-global recorder every built-in instrument site uses.
FLIGHT = FlightRecorder()


# -- postmortem dumps --------------------------------------------------------

def _versions() -> dict:
    out = {"python": sys.version.split()[0], "platform": sys.platform}
    try:  # jax may be absent/broken at crash time — versions best-effort
        import jax

        out["jax"] = getattr(jax, "__version__", "?")
    except Exception:  # noqa: BLE001 - dump must survive anything
        out["jax"] = None
    try:
        from .. import __version__ as _v

        out["cekirdekler_tpu"] = _v
    except Exception:  # noqa: BLE001
        out["cekirdekler_tpu"] = None
    return out


def _exc_block(exc: BaseException | None) -> dict | None:
    if exc is None:
        return None
    return {
        "type": type(exc).__name__,
        "message": str(exc)[:2000],
        "traceback": "".join(
            _tb.format_exception(type(exc), exc, exc.__traceback__)
        )[-8000:],
    }


def dump_postmortem(
    path: str | None = None,
    exc: BaseException | None = None,
    lanes: dict | None = None,
    extra: dict | None = None,
    flight: FlightRecorder | None = None,
) -> str | None:
    """Write the black box: flight events, the tracer's span ring, a
    metrics snapshot, lane configuration, and versions, as one
    self-contained JSON.

    ``path`` may be a file or a directory; ``None`` falls back to the
    :data:`POSTMORTEM_DIR_ENV` directory and returns None (no dump)
    when that is unset — the arming contract.  Returns the written
    path.  The write is tmp+rename so a crash-during-dump never leaves
    a half-parseable black box."""
    if path is None:
        path = os.environ.get(POSTMORTEM_DIR_ENV)
        if not path:
            return None
        # the env var names a DIRECTORY by contract — create it so an
        # operator who armed it without mkdir still gets per-crash
        # files instead of successive crashes overwriting one path (or
        # a missing parent silently dumping nothing)
        os.makedirs(path, exist_ok=True)
    fr = flight if flight is not None else FLIGHT
    from ..metrics.registry import REGISTRY
    from ..trace.spans import TRACER
    from .decisions import DECISIONS

    spans = TRACER.snapshot()
    decisions = DECISIONS.snapshot()
    doc = {
        "schema": SCHEMA,
        "wrote_at": time.time(),
        "wrote_at_iso": time.strftime(
            "%Y-%m-%dT%H:%M:%S%z", time.localtime()
        ),
        # perf_counter↔epoch exchange rate at dump time: span t0/t1 are
        # perf_counter seconds; epoch ≈ t + (wrote_at − perf_at_dump)
        "perf_counter_at_dump": time.perf_counter(),
        "exc": _exc_block(exc),
        "events": [e.to_row() for e in fr.snapshot()],
        "events_total_recorded": fr.total_recorded,
        "events_capacity": fr.capacity,
        "spans": [
            {"kind": s.kind, "t0": s.t0, "t1": s.t1, "cid": s.cid,
             "lane": s.lane, "tag": s.tag}
            for s in spans
        ],
        "tracer": {
            "enabled": TRACER.enabled,
            "total_recorded": TRACER.total_recorded,
            "capacity": TRACER.capacity,
            "dropped_spans": TRACER.dropped_spans,
        },
        # v2: the decision ring — the event-sourced "what was every
        # controller deciding, from which inputs" record, replayable
        # offline via `python -m tools.ckreplay verify <dump>`
        "decisions": [r.to_row() for r in decisions],
        "decisions_total_recorded": DECISIONS.total_recorded,
        "decisions_capacity": DECISIONS.capacity,
        "metrics": REGISTRY.snapshot(),
        "lanes": lanes,
        "versions": _versions(),
    }
    if extra:
        doc.update(extra)
    if os.path.isdir(path):
        name = f"ck_postmortem_{os.getpid()}_{int(time.time() * 1000)}.json"
        path = os.path.join(path, name)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        # json_safe: callers may put arbitrary values in their own
        # flight events ("callers may add more") — one np.int64 or a
        # float('inf') must not suppress (or render unparseable) the
        # whole black box at exactly the moment it matters.  default=str
        # stays as the last-resort belt under allow_nan=False's braces.
        from ..utils.jsonsafe import json_safe

        json.dump(json_safe(doc), f, default=str, allow_nan=False)
    os.replace(tmp, path)
    return path


def load_postmortem(path: str) -> dict:
    """Read a dump back; ``"spans"`` come back as
    :class:`~cekirdekler_tpu.trace.spans.Span` records so the dump
    round-trips through the Chrome-trace exporter::

        pm = load_postmortem(p)
        trace.save_chrome_trace(pm["spans"], "crash.json")
    """
    from ..trace.spans import Span

    with open(path) as f:
        doc = json.load(f)
    doc["spans"] = [
        Span(r["kind"], r["t0"], r["t1"], r.get("cid"), r.get("lane"),
             r.get("tag"))
        for r in doc.get("spans", ())
    ]
    # v1 back-compat: files written before the decision ring existed
    # load with an explicitly-empty decision list (absence is visible
    # as [], never a KeyError in a consumer)
    doc["decisions"] = list(doc.get("decisions") or [])
    return doc


def postmortem_spans(path: str):
    """Just the span list of a dump (Perfetto-export convenience)."""
    return load_postmortem(path)["spans"]


def record_crash(
    where: str,
    exc: BaseException,
    lanes: dict | None = None,
    flight: FlightRecorder | None = None,
) -> str | None:
    """The one crash hook every wired boundary calls: a ``crash``
    flight event + a best-effort postmortem dump.  NEVER raises — the
    original exception always outranks the black box.  One exception,
    ONE dump: a failure propagating through nested wired boundaries
    (a multi-chip pipeline stage's ``Cores.compute`` re-raising into
    ``ClPipeline.push``) records a ``crash`` event per boundary — the
    propagation path is evidence — but the black box is written only
    at the innermost one (the exception object carries the marker)."""
    fr = flight if flight is not None else FLIGHT
    try:
        fr.event("crash", where=where, exc_type=type(exc).__name__,
                 exc=str(exc)[:500])
    except Exception:  # noqa: BLE001 - the hook must be harmless
        pass
    try:
        if getattr(exc, "_ck_postmortem_path", None) is not None:
            return None  # already dumped at an inner boundary
        path = dump_postmortem(exc=exc, lanes=lanes, flight=fr)
        if path is not None:
            try:
                exc._ck_postmortem_path = path
            except Exception:  # noqa: BLE001 - slots-only exceptions
                pass
        return path
    except Exception:  # noqa: BLE001
        return None
