"""Decision provenance: the event-sourced controller decision log.

The flight recorder (``obs/flight.py``) records *that* control
decisions happened — a ``rebalance`` event says the range table moved.
Nothing records **inputs sufficient to reproduce** the decision, so a
bad split on a production rig is undebuggable offline: you can see the
balancer chose ``[7936, 256]`` but not the benches, damping state,
transfer floors and history rows it chose it FROM.  This module is that
record.  Every controller decision in the runtime — ``load_balance``
(core/balance.py), ``TransferTuner.choose``/``observe``
(core/stream.py), fused-window engage/disengage (core/cores.py), lane
health verdict flips and drain advisories (obs/health.py), and the
bench's scheduler fairness rotation (bench.py) — appends one typed
:class:`DecisionRecord` carrying the decision's **complete inputs and
outputs**, a process-monotone ``seq``, and both clock stamps
(``perf_counter`` for ordering against the span ring, epoch for
off-process reads).

Three consumers ride on top (``obs/replay.py`` + ``tools/ckreplay.py``):

- **replay-verify** re-executes the pure decision functions from the
  recorded inputs and asserts bit-identical outputs — a recorded log is
  a golden test of the controllers, catching hidden nondeterminism and
  silent behavior drift when someone edits the balancer;
- **what-if** re-runs the *chained* decision sequence with modified
  knobs (``damping=…``, ``jump_start=off``, ``transfer_floor=off``),
  carrying balancer/tuner state forward, and reports the counterfactual
  convergence trajectory;
- **explain** renders the per-lane causality table of a split —
  raw bench, transfer floor (bound or slack), damped move, quantization
  residue, and which input bound the outcome — on the CLI and the
  ``/decisionz`` debug endpoint.

Design constraints, the flight recorder's exactly:

1. **Recording is cheap and lock-free.**  ``record()`` is two clock
   reads + one ``deque.append`` (GIL-atomic on a ``maxlen`` deque);
   disabled is one attribute read + falsy check, pinned by
   ``tests/test_decisions.py`` to the PR 4 budget (< 100 ns marginal).
   A FULL ring never blocks an append — ``maxlen`` eviction is the
   overflow policy, there is no lock to contend on.  No decision site
   rides the fused DEFERRAL path: every instrumented decision is
   window-granularity or colder (rebalances, tuner choices per streamed
   phase, health window closes).
2. **Records are self-contained.**  Each record's ``inputs`` snapshot
   everything the decision function read (including mutable carried
   state — ``BalanceState``, tuner observations — *before* the call
   mutated it), so any record can be replayed in isolation and a chain
   can be re-run from any starting seq.
3. **Spill is opt-in by environment.**  With :data:`DECISION_LOG_ENV`
   (``CK_DECISION_LOG``) naming a path, every record also lands in a
   bounded spill buffer and :meth:`DecisionLog.maybe_spill` (called
   from ``Cores.barrier``/``dispose`` — cold sync points) persists it:
   the file is CREATED whole via tmp+rename, then extended by
   incremental appends of only the rows written since the last spill
   (one ``write`` per spill — a sync point must not pay a rewrite of
   the whole history, and :func:`load_decision_log` skips a torn tail
   line by contract), so the on-disk log is a complete superset of the
   buffer — rows the :data:`SPILL_MAX` bound later evicts from memory
   are already on disk.  ``save_jsonl``/``spill`` with an explicit
   path stay full atomic tmp+rename dumps.  A path naming a DIRECTORY
   (or ending in a path separator) resolves to a per-process
   ``ck_decisions_<pid>.jsonl`` inside it — multi-process rigs (DCN
   jobs, bench's benchrig subprocess) must not last-writer-win one
   file.  Unarmed (unset OR empty), nothing touches disk.

The kind vocabulary is :data:`DECISION_KINDS`; ``tools/ckcheck``'s
invariant pass fails CI on an emitted kind missing here, and
``tools/lint_obs.py`` cross-checks the tuple against the decision table
in docs/OBSERVABILITY.md — a new decision kind is always declared AND
documented.
"""

from __future__ import annotations

import itertools
import json
import os
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, NamedTuple

__all__ = [
    "DecisionRecord",
    "DecisionLog",
    "DECISIONS",
    "DECISION_KINDS",
    "REPLAYABLE_KINDS",
    "CONTEXT_KINDS",
    "DECISION_LOG_ENV",
    "load_decision_log",
]

DECISION_LOG_ENV = "CK_DECISION_LOG"

#: The declared decision-kind vocabulary (the ``EVENT_KINDS`` contract,
#: applied to decisions): every kind the built-in controllers emit.
DECISION_KINDS = (
    "load-balance",        # core/balance.load_balance — one balancer iteration
    "transfer-choose",     # core/stream.TransferTuner.choose — chunk count
    "transfer-observe",    # core/stream.TransferTuner.observe — model update
    "fused-engage",        # core/cores — a fused window opened
    "fused-disengage",     # core/cores — window refusal/break, named reason
    "health-verdict",      # obs/health — a (lane, signal) verdict flipped
    "drain-advisory",      # obs/health.suggest_drain — lanes named for eviction
    "scheduler-rotation",  # bench.SectionScheduler — fairness promotion
    "admission",           # serve/admission — one request admitted/rejected
    "coalesce",            # serve/coalescer — one dispatch cycle's batch plan
    "breaker",             # serve/resilience — a circuit breaker transitioned
    "shed",                # serve/resilience — brownout engaged/released
    "retry",               # serve/resilience — one budget-gated retry verdict
    "containment",         # serve/resilience — a failed batch's bisection plan
    "drain-apply",         # obs/drain — lanes quarantined (advice became action)
    "readmit",             # obs/drain — quarantined lanes re-admitted
    "member-leave",        # cluster/elastic — a member departed, re-split
    "member-join",         # cluster/elastic — a member arrived, re-split
    "checkpoint-restore",  # cluster/elastic — a run resumed from a window ckpt
    "block-retune",        # core/blocktuner — tile/block choice engaged/moved
    "route",               # serve/fabric — one shard-placement verdict
    "cache-warmup",        # core/cores.warmup — one AOT plan warmed (key set)
    "prior-split",         # core/balance.prior_split — prior-seeded first split
)

#: The subset replay-verify re-executes: decisions that are pure
#: functions of their recorded inputs.  The rest (fused engage/
#: disengage depend on live device residency; advisories and rotations
#: are derived views) are context records — provenance, not oracles.
REPLAYABLE_KINDS = (
    "load-balance", "transfer-choose", "transfer-observe", "health-verdict",
    "admission", "coalesce",
    "breaker", "shed", "retry", "containment",
    "drain-apply", "readmit", "member-leave", "member-join",
    "block-retune", "route", "prior-split",
)

#: The complement, DECLARED: every decision kind is placed in exactly
#: one bucket on purpose.  A kind in neither tuple would silently skip
#: ``ckreplay verify`` (an "unregistered kind" looks identical to a
#: deliberately context-only one) — ``tools/lint_obs.py`` fails CI
#: unless REPLAYABLE_KINDS ∪ CONTEXT_KINDS == DECISION_KINDS exactly,
#: and cross-checks the replayer registry in ``obs/replay.py`` against
#: REPLAYABLE_KINDS both ways.
CONTEXT_KINDS = (
    "fused-engage",        # depends on live device residency
    "fused-disengage",     # depends on live device residency
    "drain-advisory",      # derived view of the monitor's verdicts
    "scheduler-rotation",  # derived from on-disk artifact history
    "checkpoint-restore",  # reads the filesystem: provenance, not oracle
    "cache-warmup",        # reads the cache manifest: provenance, not oracle
)

#: Spill-buffer bound: the armed jsonl accumulation is capped so a
#: weeks-long process cannot grow host memory without bound; overflow
#: evicts oldest-first and is counted (``spill_dropped``).
SPILL_MAX = 200_000

#: jsonl spill format tag (first line of every spilled file).
SCHEMA = "ck-decision-log-v1"


class DecisionRecord(NamedTuple):
    """One recorded controller decision.

    ``seq`` is process-monotone across ALL kinds (``itertools.count`` —
    atomic under the GIL), so interleaved controllers order totally;
    ``t`` is ``perf_counter`` seconds (the span ring's clock), ``epoch``
    is ``time.time()`` (off-process readable)."""

    seq: int
    t: float
    epoch: float
    kind: str
    inputs: dict
    outputs: dict

    def to_row(self) -> dict:
        return {
            "seq": self.seq, "t": self.t, "epoch": self.epoch,
            "kind": self.kind, "inputs": self.inputs,
            "outputs": self.outputs,
        }

    @classmethod
    def from_row(cls, row: dict) -> "DecisionRecord":
        return cls(
            int(row["seq"]), float(row.get("t", 0.0)),
            float(row.get("epoch", 0.0)), str(row["kind"]),
            row.get("inputs") or {}, row.get("outputs") or {},
        )


class DecisionLog:
    """Bounded always-on ring of controller decisions (one
    process-global instance: :data:`DECISIONS`).

    ``enabled`` is a plain attribute (the tracer/flight convention: the
    disabled fast path must be an attribute read, not a property call).
    The ring is a ``maxlen`` deque — append evicts oldest-first
    atomically under the GIL; a full ring NEVER blocks an append, and
    readers take one-slice snapshots (reporting, not synchronization)."""

    def __init__(self, capacity: int = 4096, spill_interval_s: float = 5.0):
        self.enabled = True
        self._cap = max(16, int(capacity))
        self._ring: deque[DecisionRecord] = deque(maxlen=self._cap)
        # itertools.count.__next__ is GIL-atomic: concurrent recorders
        # get unique, strictly-increasing seqs with no lock
        self._seq = itertools.count(1)
        self._total = 0
        self._spill: deque[DecisionRecord] = deque(maxlen=SPILL_MAX)
        self._spill_seen = 0  # spill_dropped = seen - len(spill)
        self.spill_interval_s = float(spill_interval_s)
        self._last_spill_t = 0.0
        # incremental-append bookkeeping: the path the armed file was
        # created at and the highest seq already persisted there —
        # periodic spills append only newer rows
        self._spill_file: str | None = None
        self._spill_watermark = 0

    # -- recording (window-granularity sites only — never the deferral) ------
    def record(self, kind: str, inputs: dict | None = None,
               outputs: dict | None = None) -> int:
        """Append one decision; returns its ``seq`` (-1 when disabled).
        Callers build the (potentially large) inputs dict behind an
        ``if DECISIONS.enabled:`` guard — disabled must cost nothing."""
        if not self.enabled:
            return -1
        seq = next(self._seq)
        rec = DecisionRecord(
            seq, time.perf_counter(), time.time(), kind,
            inputs if inputs is not None else {},
            outputs if outputs is not None else {},
        )
        self._ring.append(rec)
        self._total += 1  # GIL-racy undercount possible; reporting only
        # ONE truthiness rule with spill_path()/maybe_spill(): a
        # set-but-empty CK_DECISION_LOG is "off" everywhere — arming
        # the buffer on mere presence would retain up to SPILL_MAX
        # full snapshots that no spill site would ever write
        if os.environ.get(DECISION_LOG_ENV):
            self._spill.append(rec)
            self._spill_seen += 1
        return seq

    # -- inspection ----------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self._cap

    @property
    def total_recorded(self) -> int:
        """Decisions recorded since the last clear — exceeds
        ``capacity`` when the ring wrapped (oldest were evicted)."""
        return self._total

    @property
    def spill_dropped(self) -> int:
        """Armed-spill rows evicted by the :data:`SPILL_MAX` bound."""
        return max(0, self._spill_seen - len(self._spill))

    def snapshot(self) -> list[DecisionRecord]:
        """Recorded decisions, oldest first (one-slice ring copy)."""
        return list(self._ring)

    @contextmanager
    def capture(self):
        """Route records into a scratch ring and yield it: the pure-
        function seam the bounded model checker
        (``cekirdekler_tpu/analysis/model.py``) needs — exploring a
        controller's state space re-executes its REAL emission sites
        thousands of times, and those records must neither evict the
        live ring's history nor land in an armed spill.  The live
        ring, spill buffer, watermark and ``total_recorded`` are saved
        and restored; ``seq`` keeps advancing globally (captured rows
        are renumbered by their consumer).  Process-global like
        :func:`~.replay._quiesced` — run captures at sync points
        (bench runs the model check in ``finalize_result``, after
        every section's workload has completed)."""
        saved = (self._ring, self._spill, self._spill_seen, self._total,
                 self.enabled)
        scratch: deque[DecisionRecord] = deque(maxlen=self._cap)
        self._ring = scratch
        self._spill = deque(maxlen=SPILL_MAX)
        self._spill_seen = 0
        self.enabled = True
        try:
            yield scratch
        finally:
            (self._ring, self._spill, self._spill_seen, self._total,
             self.enabled) = saved

    def clear(self) -> None:
        self._ring.clear()
        self._total = 0
        self._spill.clear()
        self._spill_seen = 0
        self._last_spill_t = 0.0
        self._spill_file = None
        self._spill_watermark = 0

    # -- jsonl spill ---------------------------------------------------------
    def spill_path(self) -> str | None:
        """The armed jsonl path (:data:`DECISION_LOG_ENV`; unset OR
        empty = unarmed).  A DIRECTORY (existing, or a value ending in
        a path separator) resolves to ``ck_decisions_<pid>.jsonl``
        inside it — the postmortem pattern: N processes sharing one
        armed environment (a DCN job, bench's benchrig subprocess)
        must each keep their own log, not last-writer-win one file."""
        path = os.environ.get(DECISION_LOG_ENV)
        if not path:
            return None
        if path.endswith(os.sep) or os.path.isdir(path):
            os.makedirs(path, exist_ok=True)
            return os.path.join(path, f"ck_decisions_{os.getpid()}.jsonl")
        return path

    def save_jsonl(self, path: str) -> str:
        """Write the retained decisions (the armed spill buffer when it
        holds more than the ring, else the ring) as one jsonl file via
        tmp+rename: a crash mid-write never leaves a half-replaced log.
        Line 1 is a schema header; each further line is one record."""
        rows = list(self._spill) if len(self._spill) > len(self._ring) \
            else list(self._ring)
        return _write_jsonl(path, rows, dropped=self.spill_dropped,
                            total=self._total)

    def spill(self, path: str | None = None) -> str | None:
        """Persist the spill buffer to the armed file.  The FIRST spill
        to a path (or any explicit ``path`` argument) is a full atomic
        tmp+rename dump; later armed spills APPEND only the rows newer
        than the persisted watermark — one bounded write per sync
        point instead of rewriting the whole history (the loader skips
        a torn tail line by contract), and rows :data:`SPILL_MAX` later
        evicts from memory stay on disk.  Returns the written path, or
        None when unarmed."""
        explicit = path is not None
        path = path or self.spill_path()
        if not path:
            return None
        self._last_spill_t = time.time()
        rows = list(self._spill)
        if explicit or path != self._spill_file \
                or not os.path.exists(path):
            out = _write_jsonl(path, rows, dropped=self.spill_dropped,
                               total=self._total)
        else:
            fresh = [r for r in rows if r.seq > self._spill_watermark]
            if fresh:
                from ..utils.jsonsafe import json_safe

                with open(path, "a") as f:
                    f.write("".join(
                        json.dumps(json_safe(r.to_row()),
                                   allow_nan=False) + "\n"
                        for r in fresh))
            out = path
        if not explicit:
            self._spill_file = path
            if rows:
                self._spill_watermark = max(
                    self._spill_watermark, rows[-1].seq)
        return out

    def maybe_spill(self, now: float | None = None,
                    force: bool = False) -> str | None:
        """Throttled spill for cold sync points (``Cores.barrier``): at
        most one write per :attr:`spill_interval_s` unless ``force``
        (dispose — the last chance to persist the tail)."""
        if not self.spill_path():
            return None
        t = time.time() if now is None else now
        if not force and t - self._last_spill_t < self.spill_interval_s:
            return None
        return self.spill()


def _write_jsonl(path: str, rows: list[DecisionRecord], dropped: int,
                 total: int) -> str:
    from ..utils.jsonsafe import json_safe

    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        header = {
            "schema": SCHEMA, "wrote_at": time.time(),
            "perf_counter_at_dump": time.perf_counter(),
            "rows": len(rows), "total_recorded": total,
            "spill_dropped": dropped,
        }
        f.write(json.dumps(json_safe(header), allow_nan=False) + "\n")
        for r in rows:
            f.write(json.dumps(json_safe(r.to_row()), allow_nan=False) + "\n")
    os.replace(tmp, path)
    return path


#: The process-global log every built-in controller records into.
DECISIONS = DecisionLog()


def load_decision_log(path: str) -> list[DecisionRecord]:
    """Read a jsonl spill (or postmortem-extracted rows) back as
    :class:`DecisionRecord` entries, seq-ordered.  The schema header
    line and torn trailing lines are skipped (the ProfileStore reader
    contract — a log written by a dying process must still replay)."""
    out: list[DecisionRecord] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line
            if not isinstance(row, dict) or "kind" not in row \
                    or "seq" not in row:
                continue  # the schema header (or foreign junk)
            out.append(DecisionRecord.from_row(row))
    out.sort(key=lambda r: r.seq)
    return out
