"""DrainController: advisory drains become actions.

PR 6 built the sensor (``HealthMonitor.suggest_drain()`` — explicitly
advisory) and PR 9 records every advisory in the DecisionLog; nothing
ever ACTED on one.  This module is the actuator: ``Cores`` owns one
controller, consults it at every barrier (the cold sync point — drains
happen at window boundaries, never mid-window), and masks the range
table through :func:`apply_quarantine` so a quarantined lane's share is
redistributed onto the surviving lanes via the normal re-split
machinery (the next compute sees a changed range table and takes the
existing sync-point-rebalance path: deferred records flushed, coverage
reset, host made current — nothing new to get wrong).

The per-lane state machine (:func:`drain_transition`, PURE and
replay-verified):

- **active** — verdict ``degraded`` → **drain**: the lane is
  quarantined (share → 0) for ``hold_barriers`` barriers.  The drain
  is a ``drain-apply`` decision record carrying every lane's verdict.
- **quarantined** — share 0.  A lane that runs nothing produces no
  health samples, so its verdict can never clear on its own; after
  ``hold_barriers`` barriers it enters **probation**.
- **probation** — the lane gets exactly ONE step-sized probe share
  (the smallest schedulable unit): its fence/transfer signals flow
  again.  Verdict ``degraded`` → back to quarantined (hold resets —
  no flapping); verdict ``ok`` for ``confirm_clear`` consecutive
  evaluations → **readmit** (a ``readmit`` decision record), and the
  balancer redistributes organically from the probe share.
- The controller never drains the LAST active lane: a fully-degraded
  rig limps, it does not halt (availability floor).

Hysteresis lives in two places on purpose: the HealthMonitor's
release threshold gates the VERDICT, and ``confirm_clear`` gates the
re-admission — a lane oscillating around the release boundary cannot
flap drained/active each barrier (pinned by tests/test_drain.py).
"""

from __future__ import annotations

import threading

from ..metrics.registry import REGISTRY
from .decisions import DECISIONS
from .flight import FLIGHT

__all__ = [
    "DrainController",
    "drain_transition",
    "apply_quarantine",
    "LANE_ACTIVE",
    "LANE_QUARANTINED",
    "LANE_PROBATION",
    "MODEL_INVARIANTS",
]

LANE_ACTIVE = "active"
LANE_QUARANTINED = "quarantined"
LANE_PROBATION = "probation"

#: Machine-checked temporal invariants of the drain state machine
#: (``(id, kind, statement)`` — kind is ``safety`` or ``liveness``).
#: Declared NEXT to the machine they bind (the ``MODEL_INVARIANTS``
#: contract): ``cekirdekler_tpu/analysis/model.py`` explores the
#: product state space of :func:`drain_transition` ×
#: :func:`apply_quarantine` under small bounds and proves each of
#: these over every reachable state — the properties PR 12's review
#: found violated by hand (probation↔quarantine flapping) are CI
#: failures now, not review folklore.  ``tools/ckmodel`` asserts the
#: checker implements exactly this list.
MODEL_INVARIANTS = (
    ("availability-floor", "safety",
     "the last active lane is never drained — every reachable state "
     "keeps at least one lane active"),
    ("share-conservation", "safety",
     "apply_quarantine preserves the range-table total exactly under "
     "every reachable drain/probation mask"),
    ("quarantine-masked", "safety",
     "a quarantined lane's masked share is 0; a probation lane's is "
     "exactly one step (the probe)"),
    ("action-visibility", "safety",
     "every lane whose state changed this barrier appears in "
     "drained/readmitted/probed — no silent transition (flapping is "
     "visible on every evidence stream)"),
    ("eventual-readmission", "liveness",
     "under sustained ok verdicts (fairness: the lane genuinely "
     "recovered) every non-active lane is readmitted within "
     "hold_barriers + confirm_clear + 1 barriers"),
    ("no-silent-flap", "liveness",
     "no all-ok barrier ever drains a lane: a quarantine↔probation "
     "cycle requires fresh degraded evidence at every relapse"),
)


def drain_transition(
    verdicts: dict,
    states: dict,
    hold: dict,
    clear_streak: dict,
    hold_barriers: int,
    confirm_clear: int,
    probe_grace: int = 2,
) -> dict:
    """The PURE per-barrier drain state transition (see the module
    docstring for the machine).  ``verdicts`` maps lane →
    ok/suspect/degraded (absent lane = no evidence = treated ``ok``);
    ``states``/``hold``/``clear_streak`` are the controller's carried
    state.  Returns the complete post-state plus the ``drained`` /
    ``readmitted`` / ``probed`` action lists — the decision records
    store exactly these arguments and outputs, so ``ckreplay verify``
    re-executes this function bit-identically.

    Keys arrive stringified when a record round-trips through JSON;
    everything here compares by normalized string key so a live
    transition and a disk-loaded replay run the same arithmetic."""
    verdicts = {str(k): v for k, v in verdicts.items()}
    states = {str(k): v for k, v in states.items()}
    hold = {str(k): int(v) for k, v in hold.items()}
    clear_streak = {str(k): int(v) for k, v in clear_streak.items()}
    lanes = sorted(states, key=lambda s: (len(s), s))
    drained: list[str] = []
    readmitted: list[str] = []
    probed: list[str] = []
    new_states = dict(states)
    new_hold = dict(hold)
    new_streak = dict(clear_streak)
    for lane in lanes:
        st = states.get(lane, LANE_ACTIVE)
        verdict = verdicts.get(lane, "ok")
        if st == LANE_ACTIVE:
            if verdict == "degraded":
                # availability floor: never drain the last active lane
                actives = [
                    ln for ln in lanes
                    if new_states.get(ln, LANE_ACTIVE) == LANE_ACTIVE
                ]
                if len(actives) <= 1:
                    continue
                new_states[lane] = LANE_QUARANTINED
                new_hold[lane] = int(hold_barriers)
                new_streak[lane] = 0
                drained.append(lane)
        elif st == LANE_QUARANTINED:
            h = new_hold.get(lane, 0) - 1
            new_hold[lane] = h
            if h <= 0:
                new_states[lane] = LANE_PROBATION
                new_streak[lane] = 0
                # `hold` doubles as the probation GRACE countdown: a
                # quarantined lane produced no health samples, so its
                # verdict is necessarily STALE-degraded when probation
                # begins — it takes the monitor a full window of probe
                # samples to re-judge, and relapsing on the stale
                # verdict would cycle probation↔quarantine forever
                # (reproduced by the chaos suite)
                new_hold[lane] = int(probe_grace)
                probed.append(lane)
        elif st == LANE_PROBATION:
            if verdict == "degraded":
                g = new_hold.get(lane, 0)
                if g > 0:
                    # stale-verdict grace: tolerate `probe_grace`
                    # degraded reads while fresh probe evidence closes
                    # a window (an `ok` — a genuinely released window —
                    # ends the grace early via the readmit path)
                    new_hold[lane] = g - 1
                    continue
                # a RE-quarantine is a drain action like any other: it
                # must land in `drained` so the decision record, the
                # flight event, and ck_drain_total all move — flapping
                # (probation↔quarantine oscillation) is visible on
                # every evidence stream, never silent
                new_states[lane] = LANE_QUARANTINED
                new_hold[lane] = int(hold_barriers)
                new_streak[lane] = 0
                drained.append(lane)
            elif verdict == "ok":
                s = new_streak.get(lane, 0) + 1
                new_streak[lane] = s
                if s >= int(confirm_clear):
                    new_states[lane] = LANE_ACTIVE
                    new_streak[lane] = 0
                    readmitted.append(lane)
            else:  # suspect: hold position, streak resets
                new_streak[lane] = 0
    return {
        "drained": drained,
        "readmitted": readmitted,
        "probed": probed,
        "states": new_states,
        "hold": new_hold,
        "clear_streak": new_streak,
    }


def apply_quarantine(
    ranges: list[int], step: int, drained: set, probation: set,
) -> list[int]:
    """Mask a range table with the drain state: quarantined lanes drop
    to 0, probation lanes to exactly one ``step`` (the probe), and the
    displaced share moves onto active lanes in step quanta, round-robin
    in lane order — deterministic, total-preserving, and IDEMPOTENT
    (``Cores._ranges_for`` applies it to cached tables every call).
    When no lane is active the table is returned unchanged (the
    availability floor — the transition never produces that state, but
    the masker must not divide by zero if handed it)."""
    n = len(ranges)
    active = [i for i in range(n)
              if i not in drained and i not in probation]
    if not active or (not drained and not probation):
        return list(ranges)
    out = list(ranges)
    freed = 0
    for i in range(n):
        if i in drained and out[i] > 0:
            freed += out[i]
            out[i] = 0
        elif i in probation and out[i] != step:
            # a probation lane holds exactly ONE probe step; the
            # difference lands in (or borrows from) the displaced pool
            freed += out[i] - step
            out[i] = step
    k = 0
    while freed >= step:
        out[active[k % len(active)]] += step
        freed -= step
        k += 1
    while freed <= -step:
        # borrow for the probe share from the largest active lane
        donor = max(active, key=lambda i: out[i])
        if out[donor] < step:
            break  # nothing left to borrow — leave the residue
        out[donor] -= step
        freed += step
    if freed > 0:
        out[active[0]] += freed  # sub-step residue to the first active
    return out


class DrainController:
    """The barrier-time drain actuator one :class:`~.core.cores.Cores`
    owns (see module docstring).  Thread-safe: ``evaluate`` runs at
    barriers; the share-mask readers (``drained_lanes`` /
    ``probe_lanes``) take one small-state snapshot."""

    def __init__(self, monitor, lanes: int, hold_barriers: int = 2,
                 confirm_clear: int = 2, probe_grace: int | None = None,
                 enabled: bool = True):
        self.monitor = monitor
        self.lanes = int(lanes)
        self.hold_barriers = max(1, int(hold_barriers))
        self.confirm_clear = max(1, int(confirm_clear))
        # default the stale-verdict grace to TWO monitor windows: one
        # for the detector to close a window of probe samples at all
        # (the verdict is necessarily stale-degraded until then), and
        # one because the FIRST probe window is polluted by the probe
        # transition itself — the range change resets upload coverage,
        # so that window carries a re-upload spike that re-flags the
        # lane against its steady baseline (the relapse loop the chaos
        # suite reproduced)
        self.probe_grace = max(1, int(
            probe_grace if probe_grace is not None
            else 2 * getattr(monitor, "window", 2)))
        self.enabled = bool(enabled)
        self._mu = threading.Lock()
        self._states: dict[str, str] = {
            str(i): LANE_ACTIVE for i in range(self.lanes)}
        self._hold: dict[str, int] = {}
        self._streak: dict[str, int] = {}
        self._drain_count = 0
        self._readmit_count = 0
        # cached gauge handles (evaluate is cold, but the per-lane set
        # is static — the PR 4 handle discipline)
        self._g_state = {
            i: REGISTRY.gauge(
                "ck_drain_state",
                "lane drain state (0 active / 1 probation / 2 quarantined)",
                lane=i)
            for i in range(self.lanes)
        }
        self._m_drains = REGISTRY.counter(
            "ck_drain_total", "lanes quarantined by the DrainController")
        self._m_readmits = REGISTRY.counter(
            "ck_drain_readmit_total",
            "lanes re-admitted after drain hysteresis cleared")

    # -- the barrier hook -----------------------------------------------------
    def evaluate(self) -> dict | None:
        """One barrier-time evaluation: read the monitor's verdicts,
        run the pure transition, apply it, and record ``drain-apply`` /
        ``readmit`` decisions for any action taken.  Returns the
        transition result (None when disabled)."""
        if not self.enabled:
            return None
        report = self.monitor.report()
        verdicts = {str(ln): rec["verdict"] for ln, rec in report.items()}
        with self._mu:
            inputs = None
            if DECISIONS.enabled:
                inputs = {
                    "verdicts": dict(verdicts),
                    "states": dict(self._states),
                    "hold": dict(self._hold),
                    "clear_streak": dict(self._streak),
                    "hold_barriers": self.hold_barriers,
                    "confirm_clear": self.confirm_clear,
                    "probe_grace": self.probe_grace,
                }
            res = drain_transition(
                verdicts, self._states, self._hold, self._streak,
                self.hold_barriers, self.confirm_clear,
                probe_grace=self.probe_grace)
            changed = res["states"] != self._states
            self._states = res["states"]
            self._hold = res["hold"]
            self._streak = res["clear_streak"]
            self._drain_count += len(res["drained"])
            self._readmit_count += len(res["readmitted"])
        if res["drained"]:
            self._m_drains.inc(len(res["drained"]))
            FLIGHT.event("drain-apply", lanes=list(res["drained"]))
            if inputs is not None:
                DECISIONS.record("drain-apply", inputs, res)
        if res["readmitted"]:
            self._m_readmits.inc(len(res["readmitted"]))
            FLIGHT.event("readmit", lanes=list(res["readmitted"]))
            if inputs is not None:
                DECISIONS.record("readmit", inputs, res)
        if res["probed"]:
            # the quarantine→probation tick is a state change too —
            # event-sourcing must see it (flight-level; the next
            # drain-apply/readmit record carries the full state)
            FLIGHT.event("drain-probe", lanes=list(res["probed"]))
        if changed:
            score = {LANE_ACTIVE: 0, LANE_PROBATION: 1,
                     LANE_QUARANTINED: 2}
            for i in range(self.lanes):
                g = self._g_state.get(i)
                if g is not None:
                    g.set(float(score.get(
                        res["states"].get(str(i), LANE_ACTIVE), 0)))
        res["changed"] = changed
        return res

    # -- share-mask readers (Cores._ranges_for) ------------------------------
    def drained_lanes(self) -> set[int]:
        with self._mu:
            return {int(ln) for ln, st in self._states.items()
                    if st == LANE_QUARANTINED}

    def probe_lanes(self) -> set[int]:
        with self._mu:
            return {int(ln) for ln, st in self._states.items()
                    if st == LANE_PROBATION}

    def lane_state(self, lane: int) -> str:
        with self._mu:
            return self._states.get(str(int(lane)), LANE_ACTIVE)

    def report(self) -> dict:
        with self._mu:
            return {
                "enabled": self.enabled,
                "states": dict(self._states),
                "hold": dict(self._hold),
                "clear_streak": dict(self._streak),
                "drains": self._drain_count,
                "readmits": self._readmit_count,
                "hold_barriers": self.hold_barriers,
                "confirm_clear": self.confirm_clear,
                "probe_grace": self.probe_grace,
            }

    def healthy_with_drains(self) -> bool:
        """True while every DEGRADED lane is already quarantined or on
        probation — the serving tier's admission gate: a drained lane
        means reduced capacity, not an outage, so requests re-dispatch
        onto the surviving lanes instead of being rejected (the raw
        ``HealthMonitor.healthy()`` would 503 the whole tier for the
        duration of every drain)."""
        report = self.monitor.report()
        with self._mu:
            for ln, rec in report.items():
                if rec["verdict"] != "degraded":
                    continue
                if self._states.get(str(ln), LANE_ACTIVE) == LANE_ACTIVE:
                    return False
        return True
