"""Model families built on the parallel tier.

The flagship is the decoder-only :class:`Transformer` (transformer.py) —
it exercises dp/fsdp/tp/sp shardings, ring/Ulysses attention, remat, and
the full train step the driver dry-runs multi-chip.
"""

from .transformer import Transformer, TransformerConfig, cross_entropy_loss

__all__ = ["Transformer", "TransformerConfig", "cross_entropy_loss"]
