"""Mixture-of-experts FFN with expert parallelism (ep axis).

Experts' weights shard over ``ep`` — each chip holds E/ep experts' params
(the memory win expert parallelism exists for) and computes its experts'
outputs for every token; a top-1 router gates, and a ``psum`` over ep
combines.  Tokens are replicated across ep (they remain sharded over the
data/sequence axes, which stay in GSPMD auto mode: ``axis_names={'ep'}``).

This is the dense ("compute-all, mask") formulation: simple, exactly
differentiable, and correct for any router outcome; the all-to-all
capacity-dispatch variant is the flop-optimal successor and slots in
behind the same function signature.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["moe_ffn", "moe_ffn_sharded"]


def moe_ffn(x, router, w1, w2, axis: str | None = None):
    """Top-1 routed expert FFN.

    x [B,T,d]; router [d,E]; w1 (local) [E_local,d,f]; w2 [E_local,f,d].
    With ``axis`` bound (inside shard_map) E_local = E/ep and results
    psum-combine; with ``axis=None`` w1/w2 hold all experts.
    """
    dt = x.dtype
    logits = (x.astype(jnp.float32) @ router.astype(jnp.float32))  # [B,T,E]
    gate_all = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(logits, axis=-1)                              # [B,T]
    g = jnp.take_along_axis(gate_all, idx[..., None], axis=-1)[..., 0]

    e0 = lax.axis_index(axis) * w1.shape[0] if axis is not None else 0
    h = jnp.einsum("btd,edf->ebtf", x, w1.astype(dt))
    h = jax.nn.gelu(h)
    o = jnp.einsum("ebtf,efd->ebtd", h, w2.astype(dt))
    local_e = jnp.arange(w1.shape[0]) + e0
    sel = (idx[None, :, :] == local_e[:, None, None]).astype(jnp.float32)
    y = jnp.sum(o.astype(jnp.float32) * (sel * g[None])[..., None], axis=0)
    if axis is not None:
        y = lax.psum(y, axis)
    return y.astype(dt)


def moe_ffn_sharded(mesh: Mesh, x, router, w1, w2, axis: str = "ep"):
    """shard_map wrapper: w1/w2 are global [E,d,f]/[E,f,d] sharded on dim 0
    over ``axis``; x and router replicated over it (their other shardings
    stay auto)."""
    fn = jax.shard_map(
        lambda xx, r, a, b: moe_ffn(xx, r, a, b, axis=axis),
        mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis)),
        out_specs=P(),
        axis_names={axis},
    )
    return fn(x, router, w1, w2)
