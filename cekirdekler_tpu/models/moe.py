"""Mixture-of-experts FFN with expert parallelism (ep axis).

Experts' weights shard over ``ep`` — each chip holds E/ep experts' params
(the memory win expert parallelism exists for).  Two formulations behind
one signature:

- **Dense compute-all** (:func:`moe_ffn`): every chip computes its
  experts' outputs for EVERY token, a top-1 router gates, and a ``psum``
  over ep combines.  Simple, exactly differentiable, correct for any
  router outcome — and E_local× the FLOPs actually needed.
- **Capacity dispatch** (:func:`moe_ffn_capacity`): the Switch-style
  flop-optimal form.  Each chip GATHERS only the tokens routed to its
  local experts into an [E_local, C, d] dispatch buffer (C = capacity),
  runs the expert FFN on those, and SCATTERS the gated results back —
  per-chip FFN FLOPs drop from T·E_local to C·E_local ≈ T·cap/E · E_local
  (the expert-parallel flop win, realized).  Tokens beyond an expert's
  capacity are dropped (the standard Switch trade); ``capacity_factor``
  sizes the slack, and a factor ≥ E reproduces the dense result exactly
  (nothing can overflow).

Tokens are replicated across ep (they remain sharded over the data/
sequence axes, which stay in GSPMD auto mode: ``axis_names={'ep'}``), so
dispatch/combine are local gathers/scatters plus one psum — the
"all-to-all" of token routing rides the same combine collective.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.collectives import axis_size
from ..parallel.mesh import shard_map

__all__ = ["moe_ffn", "moe_ffn_capacity", "moe_ffn_sharded"]


def _route(x, router):
    """Top-1 routing: (gate weight, expert index) per token."""
    logits = x.astype(jnp.float32) @ router.astype(jnp.float32)  # [B,T,E]
    gate_all = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(logits, axis=-1)                            # [B,T]
    g = jnp.take_along_axis(gate_all, idx[..., None], axis=-1)[..., 0]
    return g, idx


def moe_ffn(x, router, w1, w2, axis: str | None = None):
    """Top-1 routed expert FFN, dense compute-all formulation.

    x [B,T,d]; router [d,E]; w1 (local) [E_local,d,f]; w2 [E_local,f,d].
    With ``axis`` bound (inside shard_map) E_local = E/ep and results
    psum-combine; with ``axis=None`` w1/w2 hold all experts.
    """
    dt = x.dtype
    g, idx = _route(x, router)

    e0 = lax.axis_index(axis) * w1.shape[0] if axis is not None else 0
    h = jnp.einsum("btd,edf->ebtf", x, w1.astype(dt))
    h = jax.nn.gelu(h)
    o = jnp.einsum("ebtf,efd->ebtd", h, w2.astype(dt))
    local_e = jnp.arange(w1.shape[0]) + e0
    sel = (idx[None, :, :] == local_e[:, None, None]).astype(jnp.float32)
    y = jnp.sum(o.astype(jnp.float32) * (sel * g[None])[..., None], axis=0)
    if axis is not None:
        y = lax.psum(y, axis)
    return y.astype(dt)


def moe_ffn_capacity(x, router, w1, w2, axis: str | None = None,
                     capacity_factor: float = 2.0):
    """Top-1 routed expert FFN, capacity-dispatch formulation.

    Same signature/contract as :func:`moe_ffn` plus ``capacity_factor``:
    per-expert capacity C = ceil(N/E · capacity_factor) (N = B·T tokens,
    E = global expert count).  Tokens overflowing an expert's capacity
    contribute zero (dropped — Switch Transformer semantics); a factor
    ≥ E makes dropping impossible and the result matches :func:`moe_ffn`
    exactly.  Differentiable: gradients flow through the gate weights and
    the expert computation via the gather/scatter (argmax routing itself
    is non-differentiable in both formulations).
    """
    dt = x.dtype
    B, T, d = x.shape
    El = w1.shape[0]
    nshards = axis_size(axis) if axis is not None else 1
    E = El * nshards
    N = B * T
    C = int(max(1, -(-N * capacity_factor // E)))
    e0 = lax.axis_index(axis) * El if axis is not None else 0

    g, idx = _route(x, router)
    xf = x.reshape(N, d)
    gf = g.reshape(N)
    idxf = idx.reshape(N)

    # position of each token within its expert's queue (0-based), computed
    # over the GLOBAL expert id so every chip agrees on slot assignment
    oh = jax.nn.one_hot(idxf, E, dtype=jnp.int32)                # [N,E]
    pos = jnp.cumsum(oh, axis=0) * oh - oh                       # 0-based at hit
    pos_t = pos.sum(axis=1)                                      # [N]
    keep = pos_t < C

    # local slot id for tokens routed to THIS chip's experts; everything
    # else (other chips' tokens, overflow) is redirected out of bounds and
    # dropped by the scatter
    local_e = idxf - e0
    mine = (local_e >= 0) & (local_e < El) & keep
    slot = jnp.where(mine, local_e * C + pos_t, El * C)          # [N]

    # dispatch[e*C + c] = token id occupying that slot (N = empty slot)
    dispatch = jnp.full((El * C + 1,), N, jnp.int32)
    dispatch = dispatch.at[slot].set(
        jnp.arange(N, dtype=jnp.int32), mode="drop"
    )[: El * C]

    # gather tokens (empty slots read a zero row via the padded x)
    xpad = jnp.concatenate([xf, jnp.zeros((1, d), dt)], axis=0)
    xe = xpad[dispatch].reshape(El, C, d)                        # [El,C,d]

    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xe, w1.astype(dt)))
    o = jnp.einsum("ecf,efd->ecd", h, w2.astype(dt))             # [El,C,d]

    # combine: scatter gated outputs back to token order
    gpad = jnp.concatenate([gf, jnp.zeros((1,), jnp.float32)])
    oflat = o.reshape(El * C, d).astype(jnp.float32) * gpad[dispatch][:, None]
    y = jnp.zeros((N + 1, d), jnp.float32).at[dispatch].add(
        oflat, mode="drop"
    )[:N]
    if axis is not None:
        y = lax.psum(y, axis)
    return y.reshape(B, T, d).astype(dt)


def moe_ffn_sharded(mesh: Mesh, x, router, w1, w2, axis: str = "ep",
                    capacity_factor: float = 0.0):
    """shard_map wrapper: w1/w2 are global [E,d,f]/[E,f,d] sharded on dim 0
    over ``axis``; x and router replicated over it (their other shardings
    stay auto).  ``capacity_factor > 0`` selects the capacity-dispatch
    formulation; 0 keeps dense compute-all."""
    if capacity_factor > 0:
        def body(xx, r, a, b):
            return moe_ffn_capacity(
                xx, r, a, b, axis=axis, capacity_factor=capacity_factor
            )
    else:
        def body(xx, r, a, b):
            return moe_ffn(xx, r, a, b, axis=axis)
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis)),
        out_specs=P(),
        axis_names={axis},
    )
    return fn(x, router, w1, w2)
