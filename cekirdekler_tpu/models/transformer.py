"""Flagship model family: a decoder-only transformer, TPU-first.

The reference framework predates ML models (its "models" are demo kernels,
SURVEY.md §2.1 #17/#20); this family exists because a complete TPU compute
framework must demonstrate the parallel tier end-to-end — dp/fsdp/tp/sp
shardings, ring/Ulysses long-context attention (parallel/attention.py),
remat, and a full jittable train step over a mesh.

Design choices (TPU-first, SURVEY.md §7 design stance):
- Params are plain pytrees (dicts) with a parallel pytree of
  ``PartitionSpec`` — GSPMD places every matmul; no manual collectives in
  the dense path.
- Compute in bfloat16 (MXU-native), params + optimizer state in float32.
- ``jax.checkpoint`` on each block when ``remat=True`` — recompute
  activations in backward, trading FLOPs for HBM.
- Static shapes; layers scanned-free (unrolled python loop — layer count
  is static) so XLA sees one big fusable graph.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.attention import attention_reference, ring_attention, ulysses_attention
from ..parallel.mesh import constrain, shard_map

__all__ = ["TransformerConfig", "Transformer", "cross_entropy_loss"]


@dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    d_ff: int = 2048
    max_seq: int = 2048
    dtype: Any = jnp.bfloat16          # activation/compute dtype (MXU-native)
    param_dtype: Any = jnp.float32
    attention: str = "dense"            # "dense" | "flash" | "ring" | "ulysses"
    # flash kernel precision; None = follow dtype (sub-f32 activations ->
    # "default" bf16 streaming, f32 -> "highest" true-f32 passes)
    attention_precision: str | None = None
    remat: bool = False
    sp_axis: str = "sp"
    # mixture of experts: n_experts > 0 turns every ``moe_every``-th block's
    # FFN into a top-1 routed expert layer (experts shard over ep).
    # moe_capacity_factor > 0 selects Switch-style capacity dispatch
    # (per-chip FFN flops ~ cap/E of compute-all; over-capacity tokens
    # drop); 0 keeps the dense compute-all formulation (exact)
    n_experts: int = 0
    moe_every: int = 2
    moe_capacity_factor: float = 0.0
    # pipeline parallelism: pp_stages > 1 stacks the blocks and runs them
    # GPipe-style over the pp axis with n_microbatches per step
    pp_stages: int = 1
    n_microbatches: int = 1

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def is_moe_block(self, i: int) -> bool:
        if self.n_experts <= 0:
            return False
        if self.pp_stages > 1:
            return True  # pp needs homogeneous (stackable) blocks
        return (i + 1) % self.moe_every == 0


def _init(rng, shape, scale, dtype):
    return (jax.random.normal(rng, shape) * scale).astype(dtype)


class Transformer:
    """Decoder-only transformer with mesh-aware sharding specs."""

    def __init__(self, config: TransformerConfig):
        self.config = config

    # -- parameters ----------------------------------------------------------
    def init(self, rng) -> dict:
        c = self.config
        keys = jax.random.split(rng, 2 + c.n_layers)
        params: dict = {
            "embed": _init(keys[0], (c.vocab, c.d_model), 0.02, c.param_dtype),
            "final_norm": jnp.ones((c.d_model,), c.param_dtype),
            "blocks": [],
        }
        for i in range(c.n_layers):
            ks = jax.random.split(keys[2 + i], 5)
            d, h, f = c.d_model, c.n_heads * c.head_dim, c.d_ff
            block = {
                "ln1": jnp.ones((d,), c.param_dtype),
                "wqkv": _init(ks[0], (d, 3 * h), d**-0.5, c.param_dtype),
                "wo": _init(ks[1], (h, d), h**-0.5, c.param_dtype),
                "ln2": jnp.ones((d,), c.param_dtype),
            }
            if c.is_moe_block(i):
                block["router"] = _init(ks[4], (d, c.n_experts), 0.02, c.param_dtype)
                block["w1"] = _init(ks[2], (c.n_experts, d, f), d**-0.5, c.param_dtype)
                block["w2"] = _init(ks[3], (c.n_experts, f, d), f**-0.5, c.param_dtype)
            else:
                block["w1"] = _init(ks[2], (d, f), d**-0.5, c.param_dtype)
                block["w2"] = _init(ks[3], (f, d), f**-0.5, c.param_dtype)
            params["blocks"].append(block)
        if c.pp_stages > 1:
            from ..parallel.pipeline_parallel import stack_layers

            if c.n_layers % c.pp_stages != 0:
                raise ValueError(
                    f"n_layers ({c.n_layers}) must divide into pp_stages ({c.pp_stages})"
                )
            params["blocks"] = stack_layers(params["blocks"])
        return params

    def param_specs(self) -> dict:
        """PartitionSpec pytree matching :meth:`init` — tp shards the head
        and ff dimensions, fsdp shards the other matmul dimension."""
        c = self.config

        def block_spec(i: int) -> dict:
            spec = {
                "ln1": P(),
                "wqkv": P("fsdp", "tp"),
                "wo": P("tp", "fsdp"),
                "ln2": P(),
            }
            if c.is_moe_block(i):
                spec["router"] = P()
                spec["w1"] = P("ep", "fsdp", "tp")
                spec["w2"] = P("ep", "tp", "fsdp")
            else:
                spec["w1"] = P("fsdp", "tp")
                spec["w2"] = P("tp", "fsdp")
            return spec

        blocks = [block_spec(i) for i in range(c.n_layers)]
        if c.pp_stages > 1:
            # stacked layer dim shards over pp (each stage holds its layers)
            blocks = jax.tree_util.tree_map(
                lambda s: P("pp", *s), blocks[0],
                is_leaf=lambda x: isinstance(x, P),
            )
        return {
            "embed": P("tp", "fsdp"),
            "final_norm": P(),
            "blocks": blocks,
        }

    def shard_params(self, params: dict, mesh: Mesh) -> dict:
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params,
            self.param_specs(),
        )

    # -- forward -------------------------------------------------------------
    def _embed_lookup(self, embed, tokens, mesh: Mesh | None):
        """Token → embedding row.  Under a mesh the table is sharded
        ``P("tp", "fsdp")`` (vocab over tp), so a plain gather forces GSPMD
        to rematerialize the full table every step; the one-hot matmul form
        is a contraction over the sharded vocab dim instead — XLA keeps the
        shards in place and inserts one psum over tp (MXU-friendly)."""
        c = self.config
        if mesh is None or mesh.shape.get("tp", 1) <= 1:
            # vocab dim unsharded: the gather is local and cheap — the
            # one-hot contraction would cost O(B·T·vocab·D) for nothing
            return embed.astype(c.dtype)[tokens]
        onehot = jax.nn.one_hot(tokens, c.vocab, dtype=c.dtype)
        onehot = constrain(onehot, mesh, ("dp", "fsdp"), c.sp_axis, "tp")
        return onehot @ embed.astype(c.dtype)

    def _attention(self, q, k, v, mesh: Mesh | None):
        c = self.config
        if c.attention in ("ring", "ulysses") and mesh is not None:
            # sequence-parallel paths run under shard_map: batch over the
            # data axes, sequence over sp, heads over tp; the ring/all-to-all
            # collectives ride the sp axis only
            inner = ring_attention if c.attention == "ring" else ulysses_attention
            spec = P(("dp", "fsdp"), c.sp_axis, "tp", None)
            fn = shard_map(
                partial(inner, axis=c.sp_axis, causal=True),
                mesh=mesh,
                in_specs=(spec,) * 3,
                out_specs=spec,
            )
            return fn(q, k, v)
        if c.attention == "flash":
            # Pallas hot op (ops/flash_attention.py): tiled stable-softmax,
            # O(block²) attention memory, fwd+bwd kernels, differentiable.
            from ..ops.flash_attention import default_blocks, flash_attention

            # precision follows the activation dtype (overridable via
            # config): sub-f32 activations (the bf16 config default) take
            # the r6 "default" path — bf16 streamed through every fwd+bwd
            # contraction with f32 accumulators, single-pass MXU; f32
            # activations keep "highest" (true-f32 passes, the r5 ~5e-5
            # dense agreement the f32 tests pin)
            prec = c.attention_precision or (
                "default" if jnp.dtype(c.dtype).itemsize < 4 else "highest"
            )
            # measured 512/512 sweet spot, degraded by gcd; None = only
            # sub-128 (sub-MXU) tiles divide T -> dense is faster (the
            # documented default-args convention, ADVICE r4 / VERDICT #7)
            blocks = default_blocks(q.shape[1])
            bq, bk = blocks if blocks is not None else (None, None)
            if bq is not None and mesh is None:
                return flash_attention(q, k, v, True, bq, bk, None, prec)
            if bq is not None and mesh is not None and (
                q.shape[0] % (mesh.shape.get("dp", 1)
                              * mesh.shape.get("fsdp", 1)) == 0
                and c.n_heads % mesh.shape.get("tp", 1) == 0
                and mesh.shape.get(c.sp_axis, 1) <= 1
            ):
                # batch-sharded mesh (dp/fsdp; heads optionally over tp):
                # causal self-attention is independent per (batch, head),
                # so each shard runs the SAME Pallas kernel on its local
                # slice under shard_map — pallas_call cannot be
                # auto-partitioned by GSPMD, but it doesn't need to be
                # when no sharded axis crosses the attention reduction.
                # Sequence-sharded meshes use ring/ulysses instead.
                # interpret follows the MESH's devices, not the process
                # default backend — on a host whose default is a tunneled
                # TPU, a CPU-rig mesh must still get the interpreter.
                interp = mesh.devices.flat[0].platform != "tpu"
                spec = P(("dp", "fsdp"), None, "tp", None)
                # the Pallas INTERPRETER can't satisfy the replication/
                # vma checker — relax it off-TPU only, same workaround
                # as the ring/ulysses sharded wrappers
                kw = {"check_vma": False} if interp else {}
                fn = shard_map(
                    lambda qq, kk, vv: flash_attention(
                        qq, kk, vv, True, bq, bk, interp, prec),
                    mesh=mesh,
                    in_specs=(spec,) * 3,
                    out_specs=spec,
                    **kw,
                )
                return fn(q, k, v)
            # degenerate tiling, uneven batch/head sharding, or a
            # sequence-sharded mesh: the GSPMD dense path handles all of
            # them (it tolerates uneven sharding via padding) — still
            # honoring the derived precision trade (a bf16 model's dense
            # fallback must not silently pay multi-pass-f32 einsums)
            return attention_reference(
                q, k, v, causal=True,
                precision=(jax.lax.Precision.DEFAULT
                           if prec == "default" else None),
            )
        return attention_reference(q, k, v, causal=True)

    def _block(self, params: dict, x, mesh: Mesh | None):
        """Pre-norm block: x + Attn(LN(x)); x + FFN(LN(x)) (dense or MoE)."""
        c = self.config
        B, T, _ = x.shape
        h = _rms_norm(x, params["ln1"])
        qkv = h @ params["wqkv"].astype(c.dtype)
        if mesh is not None:
            qkv = constrain(qkv, mesh, ("dp", "fsdp"), c.sp_axis, "tp")
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = (B, T, c.n_heads, c.head_dim)
        o = self._attention(q.reshape(shp), k.reshape(shp), v.reshape(shp), mesh)
        o = o.reshape(B, T, -1) @ params["wo"].astype(c.dtype)
        if mesh is not None:
            o = constrain(o, mesh, ("dp", "fsdp"), c.sp_axis, None)
        x = x + o
        h = _rms_norm(x, params["ln2"])
        if "router" in params:
            from .moe import moe_ffn, moe_ffn_capacity, moe_ffn_sharded

            cf = c.moe_capacity_factor
            if mesh is not None:
                h = moe_ffn_sharded(mesh, h, params["router"], params["w1"],
                                    params["w2"], capacity_factor=cf)
            elif cf > 0:
                h = moe_ffn_capacity(h, params["router"], params["w1"],
                                     params["w2"], capacity_factor=cf)
            else:
                # under pp (or single device) GSPMD auto-shards the expert
                # dim from the param shardings
                h = moe_ffn(h, params["router"], params["w1"], params["w2"])
            return x + h
        h = jax.nn.gelu(h @ params["w1"].astype(c.dtype))
        if mesh is not None:
            h = constrain(h, mesh, ("dp", "fsdp"), c.sp_axis, "tp")
        h = h @ params["w2"].astype(c.dtype)
        return x + h

    def apply(self, params: dict, tokens, mesh: Mesh | None = None):
        """tokens [B, T] int32 → logits [B, T, vocab] (f32)."""
        c = self.config
        x = self._embed_lookup(params["embed"], tokens, mesh)
        if mesh is not None:
            x = constrain(x, mesh, ("dp", "fsdp"), c.sp_axis, None)
        if c.pp_stages > 1:
            # blocks is a stacked pytree (init, pp_stages>1 branch) — run it
            # through the GPipe microbatch pipeline over the pp axis
            x = self._apply_pipelined(params["blocks"], x, mesh)
        else:
            def block(bp, x):
                return self._block(bp, x, mesh)

            if c.remat:
                block = jax.checkpoint(block)
            for bp in params["blocks"]:
                x = block(bp, x)
        x = _rms_norm(x, params["final_norm"])
        logits = x.astype(jnp.float32) @ params["embed"].astype(jnp.float32).T
        if mesh is not None:
            logits = constrain(logits, mesh, ("dp", "fsdp"), c.sp_axis, "tp")
        return logits

    def _apply_pipelined(self, stacked_blocks, x, mesh: Mesh | None):
        """GPipe over the pp axis: each stage holds n_layers/pp stacked
        layers; activations rotate around the ring per microbatch step
        (parallel/pipeline_parallel.py).  Inside the stage the other mesh
        axes stay in GSPMD auto mode, so blocks run with mesh=None."""
        from ..parallel.pipeline_parallel import gpipe

        c = self.config

        def stage_fn(local_blocks, x_mb):
            n_local = jax.tree_util.tree_leaves(local_blocks)[0].shape[0]

            def one(bp_i, x_mb):
                return self._block(bp_i, x_mb, None)

            if c.remat:
                one = jax.checkpoint(one)
            for i in range(n_local):
                bp_i = jax.tree_util.tree_map(lambda a: a[i], local_blocks)
                x_mb = one(bp_i, x_mb)
            return x_mb

        if mesh is None:
            # no mesh: run the stack sequentially (pp degenerates)
            n = jax.tree_util.tree_leaves(stacked_blocks)[0].shape[0]
            for i in range(n):
                bp_i = jax.tree_util.tree_map(lambda a: a[i], stacked_blocks)
                x = self._block(bp_i, x, None)
            return x
        return gpipe(stage_fn, stacked_blocks, x, c.n_microbatches, mesh)

    # -- training ------------------------------------------------------------
    def loss_fn(self, params: dict, batch: dict, mesh: Mesh | None = None):
        """Next-token cross entropy; batch = {"tokens": [B, T+1]}."""
        tokens = batch["tokens"]
        logits = self.apply(params, tokens[:, :-1], mesh)
        return cross_entropy_loss(logits, tokens[:, 1:])

    def make_train_step(self, optimizer, mesh: Mesh | None = None) -> Callable:
        """Build the full jittable train step: loss, grads, optax update.

        Returns ``step(params, opt_state, batch) -> (params, opt_state,
        loss)``; caller jits (optionally with shardings over ``mesh``).
        """

        def step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(
                lambda p, b: self.loss_fn(p, b, mesh)
            )(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
            return params, opt_state, loss

        return step


def _rms_norm(x, gain):
    dt = x.dtype
    xf = x.astype(jnp.float32)
    y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-6)
    return (y * gain.astype(jnp.float32)).astype(dt)


def cross_entropy_loss(logits, labels):
    """Mean next-token cross entropy (f32)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -ll.mean()
