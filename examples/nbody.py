"""N-body demo — the reference's flagship numeric workload, TPU-style.

Reference: ``Tester.nBody`` (Tester.cs:7682-7799) — n particles, direct
O(n²) gravity, 150 load-balanced iterations, velocity updates checked
against a host loop within ±0.01f; also the micro-benchmark behind the
device-ranking DSL (ClObjectApi.cs:1222-1244).  Here the same program as
a standalone demo: the C-subset kernel (workloads.NBODY_SRC) runs through
``NumberCruncher`` + ``ClArray.compute()`` with the iterative balancer
splitting bodies across every selected chip, leapfrog integration on the
host arrays between steps, a velocity-magnitude readout, and the ±0.01
host check on step one.

On TPU the kernel's inner ``x[j]`` loop takes the Pallas uniform-gather
path (SMEM operand; kernel/pallas_backend.py) — ~25× the vectorized XLA
lowering of the same source, and faster than the hand-written jnp
formulation (ops/nbody.py).

Run it anywhere:

    python examples/nbody.py                       # real TPU chip (if any)
    JAX_PLATFORMS=cpu python examples/nbody.py     # host CPU
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import cekirdekler_tpu as ct  # noqa: E402
from cekirdekler_tpu import ClArray  # noqa: E402
from cekirdekler_tpu.core.cruncher import NumberCruncher  # noqa: E402
from cekirdekler_tpu.workloads import NBODY_SRC, nbody_host_step  # noqa: E402

N = 4096
DT = 1e-3
STEPS = 25
LOCAL = 256


def main() -> int:
    devs = ct.all_devices()
    tpus = devs.tpus()
    if len(tpus):
        devs = tpus
    print(f"devices: {[str(d) for d in devs]}")

    rng = np.random.default_rng(0)
    pos = (rng.random((3, N), dtype=np.float32) - 0.5) * 2.0
    x = ClArray(pos[0].copy(), name="x", read_only=True)
    y = ClArray(pos[1].copy(), name="y", read_only=True)
    z = ClArray(pos[2].copy(), name="z", read_only=True)
    vel = [ClArray(N, np.float32, name=f"v{c}", partial_read=True)
           for c in "xyz"]

    cr = NumberCruncher(devs, NBODY_SRC)
    group = x.next_param(y, z, *vel)  # built once, reused per step
    try:
        t0 = None  # starts AFTER step 0 (JIT compile + host check excluded)
        for step in range(STEPS):
            if step == 1:
                t0 = time.perf_counter()
            # one balanced velocity update across all chips
            group.compute(cr, 42, "nBody", N, LOCAL, values=(N, DT))
            if step == 0:
                # the reference's ±0.01f host check, on the first step
                exp = nbody_host_step(
                    pos[0], pos[1], pos[2],
                    np.zeros(N, np.float32), np.zeros(N, np.float32),
                    np.zeros(N, np.float32), DT,
                )
                err = max(
                    np.abs(vel[i].host() - exp[i]).max() for i in range(3)
                )
                status = "OK" if err < 0.01 else "FAIL"
                print(f"step 1 host check: maxerr={err:.2e}  [{status}]")
                if status == "FAIL":
                    return 1
            # leapfrog drift on the host arrays (they re-upload next step)
            for arr, v in zip((x, y, z), vel):
                arr.host()[:] += v.host() * DT
        dt = time.perf_counter() - t0
        timed_steps = STEPS - 1
        ranges = cr.ranges_of(42)
        gpairs = N * N * timed_steps / dt / 1e9
        vmag = np.sqrt(sum(v.host().astype(np.float64) ** 2 for v in vel))
        print(f"{timed_steps} timed steps x {N} bodies in {dt:.2f}s "
              f"({gpairs:.2f} Gpairs/s incl. host drift + transfers)")
        print(f"balancer ranges: {ranges} (sum {sum(ranges)})")
        print(f"mean |v| = {vmag.mean():.4f}, max |v| = {vmag.max():.4f}")
        print("nbody demo: OK")
        return 0
    finally:
        cr.dispose()


if __name__ == "__main__":
    sys.exit(main())
