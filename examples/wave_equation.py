"""Wave-equation demo — the reference's Unity mesh demo, TPU-style.

Reference: Kamera.cs:190-268 — a sphere mesh deformed every frame by a
``waveEquation`` kernel through ClNumberCruncher + ClArray.  Here the same
idea as a standalone program: a 2-D membrane simulated by a C-subset
kernel, stepped N times through a :class:`DevicePipeline` whose INTERNAL
arrays keep the field state device-resident across generations, with live
readback of every frame (the OUTPUT array), an ASCII render, and a numpy
reference check.

Run it anywhere:

    python examples/wave_equation.py              # real TPU chip (if any)
    JAX_PLATFORMS=cpu python examples/wave_equation.py   # host CPU

The kernel uses shifted neighbor loads (``u[i-1]``, ``u[i+W]``) — outside
the elementwise Pallas subset, so it exercises the vectorized XLA lowering
(kernel/codegen.py padded-view slice loads) on every backend.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import cekirdekler_tpu as ct  # noqa: E402
from cekirdekler_tpu import ClArray
from cekirdekler_tpu.pipeline.device_pipeline import DevicePipeline, PipelineStage

W, H = 96, 48        # membrane grid (flattened row-major)
C2 = 0.22            # (c·dt/dx)^2 — stability requires < 0.5 in 2-D
STEPS = 120
LOCAL = 64

# One work item per cell.  u0 = field at t-1, u1 = field at t; the step
# kernel writes t+1 into `frame` (the OUTPUT the host reads every push),
# then `rotate` shifts the time window (u0 <- u1 <- frame) so state stays
# device-resident across generations (ArrayRole.INTERNAL).
WAVE_SRC = """
__kernel void waveStep(__global float* u0, __global float* u1,
                       __global float* frame,
                       int width, int height, float c2) {
    int i = get_global_id(0);
    int x = i % width;
    int y = i / width;
    if (x == 0 || x == width - 1 || y == 0 || y == height - 1) {
        frame[i] = 0.0f;    /* clamped boundary */
    } else {
        float lap = u1[i - 1] + u1[i + 1] + u1[i - width] + u1[i + width]
                    - 4.0f * u1[i];
        frame[i] = 2.0f * u1[i] - u0[i] + c2 * lap;
    }
}
__kernel void rotate(__global float* u0, __global float* u1,
                     __global float* frame,
                     int width, int height, float c2) {
    int i = get_global_id(0);
    u0[i] = u1[i];
    u1[i] = frame[i];
}
"""


def host_reference(u0: np.ndarray, u1: np.ndarray, steps: int) -> np.ndarray:
    """Numpy reference for the same scheme (self-check, the Tester.nBody
    pattern: Tester.cs:7682-7799)."""
    a = u0.reshape(H, W).astype(np.float64).copy()
    b = u1.reshape(H, W).astype(np.float64).copy()
    for _ in range(steps):
        lap = np.zeros_like(b)
        lap[1:-1, 1:-1] = (
            b[1:-1, :-2] + b[1:-1, 2:] + b[:-2, 1:-1] + b[2:, 1:-1]
            - 4.0 * b[1:-1, 1:-1]
        )
        c = 2.0 * b - a + C2 * lap
        c[0, :] = c[-1, :] = 0.0
        c[:, 0] = c[:, -1] = 0.0
        a, b = b, c
    return b.reshape(-1).astype(np.float32)


def ascii_frame(field: np.ndarray) -> str:
    """Coarse ASCII render of the membrane (the demo's 'mesh view')."""
    shades = " .:-=+*#%@"
    img = field.reshape(H, W)[::4, ::2]
    lo, hi = img.min(), img.max()
    span = (hi - lo) or 1.0
    rows = []
    for row in img:
        idx = ((row - lo) / span * (len(shades) - 1)).astype(int)
        rows.append("".join(shades[k] for k in idx))
    return "\n".join(rows)


def main() -> None:
    devs = ct.all_devices()
    tpus = devs.tpus()
    dev = (tpus if len(tpus) else devs.cpus())[0]
    print(f"wave_equation: {W}x{H} membrane, {STEPS} steps on {dev.name}")

    # initial condition: a gaussian pluck off-center
    yy, xx = np.mgrid[0:H, 0:W]
    bump = np.exp(-(((xx - W // 3) ** 2) / 18.0 + ((yy - H // 2) ** 2) / 18.0))
    u1_init = (0.6 * bump).reshape(-1).astype(np.float32)
    u0_init = u1_init.copy()  # zero initial velocity

    u0 = ClArray(u0_init.copy(), name="u0")
    u1 = ClArray(u1_init.copy(), name="u1")
    frame = ClArray(W * H, np.float32, name="frame")

    stage = PipelineStage(
        WAVE_SRC, "waveStep rotate", global_range=W * H, local_range=LOCAL,
        values=(W, H, C2),
    )
    stage.add_hidden(u0)
    stage.add_hidden(u1)
    stage.add_output(frame)

    pipe = DevicePipeline.make([stage], dev)
    out = np.zeros(W * H, np.float32)
    energy = []
    for step in range(STEPS):
        pipe.push(None, out)  # live readback every generation
        energy.append(float(np.square(out).sum()))
    pipe.dispose()

    want = host_reference(u0_init, u1_init, STEPS)
    err = float(np.abs(out - want).max())
    print(f"max |device - host reference| after {STEPS} steps: {err:.3e}")
    assert err < 1e-3, "device simulation diverged from the host reference"
    print(f"field energy: start {energy[0]:.4f} -> end {energy[-1]:.4f}")
    print(ascii_frame(out))
    print("OK")


if __name__ == "__main__":
    main()
