"""Multi-host demo — one compute() spanning N processes over DCN.

Reference: the cluster tier (ClusterAccelerator.cs:170-355) driving
remote ``Cores`` over TCP.  This demo runs the TPU-pod idiom instead:
:class:`cekirdekler_tpu.cluster.DistributedAccelerator` — the same
``compute()`` surface spanning the processes of a ``jax.distributed``
job, with the LCM-step cluster balancer splitting the global range
across processes and written ranges exchanged by XLA collectives.

Self-launching: run with no arguments and it spawns ``--procs`` worker
copies of itself (each a separate OS process with its own virtual CPU
devices, joined through a coordinator on localhost), then waits for the
consolidated report.  On a real multi-host pod you would instead start
one copy per host with ``--worker <pid> --procs <N> --coordinator
<host:port>`` pointing every process at the same coordinator — the
worker path is exactly that program.

    python examples/dcn_cluster.py                  # 2 procs x 4 devices
    python examples/dcn_cluster.py --procs 4        # 4 procs x 4 devices

The workload: a skewed-cost kernel (items in the lower half of the range
iterate 8x longer), so the equal first split is WRONG and the balancer
must move work between processes.  Timing skew is real wall time here —
each process genuinely computes — and the report shows the share
trajectory converging.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

SRC = """
__kernel void skewed(__global float* x, __global float* y, int n) {
    int i = get_global_id(0);
    int iters = (i < n / 2) ? 4000 : 500;
    float acc = x[i];
    for (int k = 0; k < iters; k++) {
        acc = acc + 0.25f;
    }
    y[i] = acc;
}
"""


def worker(pid: int, nproc: int, coordinator: str,
           devices_per_proc: int) -> None:
    # hand-launched workers (real pods) may not have the virtual-device
    # flag exported; set it before jax first initializes (best effort —
    # if something already imported jax this is a no-op and the
    # environment's device count wins)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{devices_per_proc}"
        ).strip()

    from cekirdekler_tpu.arrays.clarray import ClArray
    from cekirdekler_tpu.cluster import DistributedAccelerator
    from cekirdekler_tpu.cluster.dcn import initialize

    initialize(coordinator, nproc, pid)
    import jax

    acc = DistributedAccelerator()
    try:
        acc.setup_nodes(SRC)
        n = 16384
        calls = 8
        x = ClArray(np.arange(n, dtype=np.float32), partial_read=True,
                    read_only=True)
        y = ClArray(np.zeros(n, np.float32), partial_read=True,
                    write_only=True)
        t0 = time.perf_counter()
        traj = []
        for _ in range(calls):
            acc.compute("skewed", [x, y], compute_id=1, global_range=n,
                        local_range=64, values=(n,))
            traj.append(acc.ranges_of(1))
        wall = time.perf_counter() - t0
        # self-check: acc = x[i] + iters * 0.25, exact in f32
        iters = np.where(np.arange(n) < n // 2, 4000, 500)
        np.testing.assert_array_equal(
            np.asarray(y),
            np.arange(n, dtype=np.float32) + iters.astype(np.float32) * 0.25,
        )
        if pid == 0:
            print(f"[demo] {nproc} processes x "
                  f"{jax.local_device_count()} devices, n={n}, "
                  f"{calls} calls in {wall:.2f}s", flush=True)
            print(f"[demo] share trajectory (process 0's view):", flush=True)
            for i, r in enumerate(traj):
                print(f"  call {i}: {r}", flush=True)
            print(f"[demo] result exact on every process; timings "
                  f"{[f'{t:.0f}ms' for t in acc.compute_timing(1)]}",
                  flush=True)
        print(f"[worker {pid}] OK", flush=True)
    finally:
        acc.dispose()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", type=int, default=None)
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--coordinator", default=None,
                    help="host:port of the coordinator (real multi-host "
                         "launches; defaults to localhost:--port)")
    ap.add_argument("--devices-per-proc", type=int, default=4)
    args = ap.parse_args()
    if args.worker is not None:
        if args.coordinator is None and args.port == 0:
            ap.error("hand-launched workers need --coordinator host:port "
                     "(or --port from the self-launching parent)")
        worker(args.worker, args.procs, args.coordinator or
               f"localhost:{args.port}", args.devices_per_proc)
        return
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={args.devices_per_proc}"
    )
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--worker", str(pid), "--procs", str(args.procs),
             "--port", str(port),
             "--devices-per-proc", str(args.devices_per_proc)],
            env=env,
        )
        for pid in range(args.procs)
    ]
    # a worker killed by a signal has a NEGATIVE returncode — any nonzero
    # exit (either sign) must fail the demo, and a hung worker (e.g. the
    # coordinator never formed) must not block forever or leave orphans
    rc = 0
    try:
        deadline = time.monotonic() + 600
        for p in procs:
            remaining = max(1.0, deadline - time.monotonic())
            try:
                if p.wait(timeout=remaining) != 0:
                    rc = 1
            except subprocess.TimeoutExpired:
                rc = 1
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass  # keep killing the rest; the OS reaps on exit
    sys.exit(rc)


if __name__ == "__main__":
    main()
